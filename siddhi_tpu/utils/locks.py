"""Named lock factories + the runtime lock-witness.

Every lock the engine creates goes through `new_lock(name)` /
`new_rlock(name)` with a canonical name (`"ClassName._attr"` for
instance locks, `"module.CONST"` for module-level ones) matching the
node names the static concurrency analyzer
(`siddhi_tpu/analysis/concurrency.py`) derives from the source.  In
normal operation the factories return plain `threading.Lock`/`RLock`
objects — zero overhead, zero behavior change.

With `SIDDHI_LOCK_CHECK=1` in the environment they return *witness*
wrappers instead: every acquisition records, per thread, which other
named locks were already held, building the ACTUAL acquisition-order
graph the process exhibits.  That graph is the ground truth the static
analyzer's model (`--threads` SL04 lock-order pass) is validated
against — the analyzer is trusted only as far as the witness agrees
with it:

    SIDDHI_LOCK_CHECK=1 SIDDHI_LOCK_WITNESS_OUT=/tmp/w.json \
        python -m pytest tests/test_net_admission.py -q
    python -m siddhi_tpu.analysis --threads --witness /tmp/w.json

The second command exits non-zero if any witnessed acquisition order
contradicts the static graph (reversed edge, or an edge between two
statically-known locks the model missed) — see docs/ANALYSIS.md
"Concurrency self-analysis".

The witness also trips a HARD failure on a dynamically observed
cycle: if thread A acquires X→Y while the recorded graph already holds
Y→…→X, the acquire raises `LockOrderError` immediately (under the
check flag only) — a deadlock that would otherwise need two unlucky
threads to manifest becomes a deterministic test failure.

This module must stay dependency-free (threading/os/json only): it is
imported by every core/net module at startup.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Optional

ENV_FLAG = "SIDDHI_LOCK_CHECK"
ENV_OUT = "SIDDHI_LOCK_WITNESS_OUT"


def check_enabled() -> bool:
    v = os.environ.get(ENV_FLAG, "")
    return v not in ("", "0", "false", "off")


class LockOrderError(RuntimeError):
    """The witness observed an acquisition order that completes a cycle
    with previously observed orders — a potential deadlock."""


class LockWitness:
    """Process-wide recorder of (outer, inner) lock acquisition pairs.

    Thread-safe; the held-stack is thread-local.  `edges()` is the
    observed order relation; `locks()` every named lock that was
    acquired at least once."""

    def __init__(self):
        self._mutex = threading.Lock()          # guards the graphs only
        self._edges: set = set()                # (outer, inner) names
        self._locks: set = set()
        self._succ: dict = {}                   # outer -> set(inner)
        self._tls = threading.local()

    # -- recording ----------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _reaches_locked(self, src: str, dst: str) -> bool:
        """Is there a recorded path src -> ... -> dst?  (Caller holds
        self._mutex; the graphs are small — dozens of nodes.)"""
        seen, todo = set(), [src]
        while todo:
            n = todo.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            todo.extend(self._succ.get(n, ()))
        return False

    def on_acquired(self, name: str) -> None:
        stack = self._stack()
        outer = stack[-1] if stack else None
        with self._mutex:
            self._locks.add(name)
            if outer is not None and outer != name:
                if (outer, name) not in self._edges:
                    if self._reaches_locked(name, outer):
                        # completing a cycle: this order, combined with
                        # an order some other code path already
                        # exhibited, can deadlock.  Raised BEFORE the
                        # name goes on the held stack, so the wrapper's
                        # cleanup leaves the witness state consistent
                        raise LockOrderError(
                            f"lock-order inversion: acquiring {name!r} "
                            f"while holding {outer!r}, but the reverse "
                            f"order {name!r} -> ... -> {outer!r} was "
                            f"already witnessed")
                    self._edges.add((outer, name))
                    self._succ.setdefault(outer, set()).add(name)
        stack.append(name)

    def on_released(self, name: str) -> None:
        stack = self._stack()
        # release order need not be LIFO (rare but legal): drop the
        # most recent matching entry
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # -- reporting ----------------------------------------------------------

    def edges(self) -> set:
        with self._mutex:
            return set(self._edges)

    def locks(self) -> set:
        with self._mutex:
            return set(self._locks)

    def reset(self) -> None:
        with self._mutex:
            self._edges.clear()
            self._locks.clear()
            self._succ.clear()

    def to_dict(self) -> dict:
        with self._mutex:
            return {"locks": sorted(self._locks),
                    "edges": sorted(list(e) for e in self._edges)}

    def dump(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=1)
        os.replace(tmp, path)

    def merge_dump(self, path: str) -> None:
        """Dump, merging with whatever a previous process already wrote
        there — several test processes can share one witness file.  The
        read-merge-write runs under an flock'd sidecar so two processes
        exiting together cannot clobber each other's edges (a lost edge
        cannot fail the --witness gate, so the loss would be invisible)."""
        lock_path = path + ".lock"
        lock_f = open(lock_path, "a+")
        try:
            try:
                import fcntl
                fcntl.flock(lock_f.fileno(), fcntl.LOCK_EX)
            except ImportError:         # non-POSIX: best-effort
                pass
            data = self.to_dict()
            try:
                with open(path, encoding="utf-8") as f:
                    prev = json.load(f)
                data["locks"] = sorted(set(data["locks"])
                                       | set(prev["locks"]))
                data["edges"] = sorted({tuple(e) for e in data["edges"]}
                                       | {tuple(e) for e in prev["edges"]})
                data["edges"] = [list(e) for e in data["edges"]]
            except (OSError, ValueError, KeyError):
                pass
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(data, f, indent=1)
            os.replace(tmp, path)
        finally:
            lock_f.close()              # releases the flock


_WITNESS = LockWitness()
_ATEXIT_ARMED = False


def witness() -> LockWitness:
    return _WITNESS


def _arm_atexit() -> None:
    global _ATEXIT_ARMED
    if _ATEXIT_ARMED:
        return
    out = os.environ.get(ENV_OUT)
    if not out:
        return
    import atexit
    atexit.register(lambda: _WITNESS.merge_dump(out))
    _ATEXIT_ARMED = True


class _WitnessLockBase:
    """Context-manager wrapper over a real lock, reporting to the
    witness.  Mirrors the small Lock surface the engine uses
    (acquire/release/with; RLock adds reentrancy via the inner lock)."""

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                _WITNESS.on_acquired(self.name)
            except BaseException:
                # a LockOrderError must not leave the real lock held —
                # the test that provoked it should fail, not wedge
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._inner.release()
        _WITNESS.on_released(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<witness {type(self._inner).__name__} {self.name!r}>"


class _WitnessLock(_WitnessLockBase):
    def locked(self) -> bool:           # plain Lock surface only —
        return self._inner.locked()     # RLock has no locked() here


class _WitnessRLock(_WitnessLockBase):
    def _is_owned(self) -> bool:        # runtime.flush() introspects this
        return self._inner._is_owned()


def new_lock(name: str):
    """A `threading.Lock`, witness-wrapped under SIDDHI_LOCK_CHECK=1.
    `name` must match the static analyzer's node name for the
    construction site: `"ClassName._attr"` / `"module.CONST"`."""
    if not check_enabled():
        return threading.Lock()
    _arm_atexit()
    return _WitnessLock(name, threading.Lock())


def new_rlock(name: str):
    """A `threading.RLock`, witness-wrapped under SIDDHI_LOCK_CHECK=1."""
    if not check_enabled():
        return threading.RLock()
    _arm_atexit()
    return _WitnessRLock(name, threading.RLock())
