"""Minimal quartz-style cron evaluator for triggers and cron windows
(reference uses the Quartz library: core:trigger/CronTrigger.java:22,
core:query/processor/stream/window/CronWindowProcessor.java).

Supports 6-field quartz expressions "sec min hour dom mon dow" with
`*`, `*/n`, lists `a,b,c`, ranges `a-b`, and `?`.  Evaluation is
second-granular in UTC.
"""
from __future__ import annotations

import calendar
import datetime as _dt
from typing import Optional


class CronError(Exception):
    pass


def _parse_field(spec: str, lo: int, hi: int) -> Optional[frozenset]:
    """None means 'any'."""
    if spec in ("*", "?"):
        return None
    vals: set = set()
    for part in spec.split(","):
        if part.startswith("*/"):
            step = int(part[2:])
            vals.update(range(lo, hi + 1, step))
        elif "-" in part and not part.lstrip("-").isdigit():
            a, b = part.split("-", 1)
            vals.update(range(int(a), int(b) + 1))
        elif "/" in part:
            base, step = part.split("/", 1)
            vals.update(range(int(base), hi + 1, int(step)))
        else:
            vals.add(int(part))
    for v in vals:
        if not lo <= v <= hi:
            raise CronError(f"cron value {v} out of range [{lo},{hi}]")
    return frozenset(vals)


class CronSchedule:
    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) == 5:            # standard cron: prepend seconds=0
            fields = ["0"] + fields
        if len(fields) not in (6, 7):   # quartz allows optional year; ignore it
            raise CronError(f"bad cron expression {expr!r}")
        self.sec = _parse_field(fields[0], 0, 59)
        self.min = _parse_field(fields[1], 0, 59)
        self.hour = _parse_field(fields[2], 0, 23)
        self.dom = _parse_field(fields[3], 1, 31)
        self.mon = _parse_field(fields[4], 1, 12)
        self.dow = _parse_field(fields[5], 0, 7)
        if self.dow is not None:
            # quartz: 1=SUN..7=SAT; python weekday(): Mon=0..Sun=6.
            # normalize quartz 1-7 -> python 6,0,1,...,5 ; accept 0 as SUN too.
            conv = set()
            for v in self.dow:
                v = v % 7          # 7->0 (SUN)
                conv.add((v - 1) % 7 if v else 6)
            self.dow = frozenset(conv)

    def _match(self, t: _dt.datetime) -> bool:
        return ((self.sec is None or t.second in self.sec)
                and (self.min is None or t.minute in self.min)
                and (self.hour is None or t.hour in self.hour)
                and (self.dom is None or t.day in self.dom)
                and (self.mon is None or t.month in self.mon)
                and (self.dow is None or t.weekday() in self.dow))

    def next_fire(self, after_ms: int) -> int:
        """Next fire time strictly after `after_ms` (epoch millis, UTC)."""
        t = _dt.datetime.fromtimestamp(after_ms // 1000 + 1, tz=_dt.timezone.utc)
        t = t.replace(microsecond=0)
        # bounded scan: seconds granularity with fast-forward on mismatch
        for _ in range(4 * 366 * 24 * 60 * 60):   # hard bound ~4 years
            if self.mon is not None and t.month not in self.mon:
                if t.month == 12:
                    t = t.replace(year=t.year + 1, month=1, day=1,
                                  hour=0, minute=0, second=0)
                else:
                    t = t.replace(month=t.month + 1, day=1, hour=0,
                                  minute=0, second=0)
                continue
            if (self.dom is not None and t.day not in self.dom) or \
                    (self.dow is not None and t.weekday() not in self.dow):
                t = (t + _dt.timedelta(days=1)).replace(hour=0, minute=0, second=0)
                continue
            if self.hour is not None and t.hour not in self.hour:
                t = (t + _dt.timedelta(hours=1)).replace(minute=0, second=0)
                continue
            if self.min is not None and t.minute not in self.min:
                t = (t + _dt.timedelta(minutes=1)).replace(second=0)
                continue
            if self.sec is not None and t.second not in self.sec:
                t = t + _dt.timedelta(seconds=1)
                continue
            return int(t.timestamp() * 1000)
        raise CronError("no cron fire time found within 4 years")
