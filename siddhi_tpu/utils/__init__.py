"""Cross-cutting services: scheduler, cron, statistics, snapshots."""
