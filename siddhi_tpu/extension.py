"""Extension metadata tier — the `@Extension` annotation analog.

Reference: modules/siddhi-annotations/src/main/java/org/wso2/siddhi/
annotation/Extension.java:52 (name/namespace/description/parameters/
examples carried on every extension class) and
SiddhiAnnotationProcessor.java:55-73 (compile-time validation: names
must be declared and non-empty, descriptions mandatory, each @Parameter
and @Example fully populated).  Here registration time IS compile time:
`register_*(..., meta=ExtensionMeta(...))` validates eagerly and feeds
the central registry that `docgen` renders.

Built-in windows/aggregators are compiled directly (no registry
objects), so their metadata lives in BUILTIN_META below — the docgen
"every built-in has parameters + examples" guarantee comes from the
test suite asserting this table covers the parser's built-in surface.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .utils.locks import new_rlock


class ExtensionError(Exception):
    """Invalid extension metadata (registration-time validation)."""


@dataclass(frozen=True)
class Parameter:
    name: str
    type: tuple = ()            # accepted attribute types, e.g. ("INT",)
    description: str = ""
    optional: bool = False
    default: object = None


@dataclass(frozen=True)
class Example:
    syntax: str
    description: str = ""


@dataclass(frozen=True)
class ExtensionMeta:
    name: str
    description: str
    namespace: str = ""
    parameters: tuple = ()
    examples: tuple = ()
    returns: str = ""


def validate_meta(meta: ExtensionMeta, kind: str = "extension") -> None:
    """Registration-time validation (SiddhiAnnotationProcessor analog):
    fail LOUDLY at registration, not when a user first reads the docs."""
    problems = []
    if not meta.name or not str(meta.name).strip():
        problems.append("name must be non-empty")
    elif any(c.isspace() for c in meta.name):
        problems.append(f"name {meta.name!r} must not contain whitespace")
    if not meta.description or not str(meta.description).strip():
        problems.append(f"{meta.name!r}: description is mandatory")
    for p in meta.parameters:
        if not isinstance(p, Parameter):
            problems.append(f"{meta.name!r}: parameters must be Parameter "
                            f"instances (got {type(p).__name__})")
            continue
        if not p.name:
            problems.append(f"{meta.name!r}: parameter with empty name")
        if not p.description:
            problems.append(
                f"{meta.name!r}: parameter {p.name!r} needs a description")
        if not p.type:
            problems.append(
                f"{meta.name!r}: parameter {p.name!r} needs accepted types")
        if p.optional and p.default is None and "none" not in \
                [str(t).lower() for t in p.type]:
            problems.append(
                f"{meta.name!r}: optional parameter {p.name!r} needs a "
                f"default value")
    for e in meta.examples:
        if not isinstance(e, Example):
            problems.append(f"{meta.name!r}: examples must be Example "
                            f"instances (got {type(e).__name__})")
            continue
        if not e.syntax:
            problems.append(f"{meta.name!r}: example with empty syntax")
        if not e.description:
            problems.append(
                f"{meta.name!r}: example {e.syntax[:30]!r} needs a "
                f"description")
    if problems:
        raise ExtensionError(
            f"invalid {kind} metadata: " + "; ".join(problems))


# central metadata registry: (kind, namespace, lowercase name) -> meta
_REGISTRY: dict = {}
# set during entry-point discovery: duplicate registrations raise.
# Guarded by _REGISTRY_LOCK — a register_meta from another thread while a
# discovery scan runs must neither see the strict flag flip mid-call nor
# race the check-then-insert (the RLock lets the discovery thread's own
# nested register_* calls through)
_strict_collisions = False
_REGISTRY_LOCK = new_rlock("extension._REGISTRY_LOCK")


def register_meta(kind: str, meta, strict: bool = None) -> None:
    """Validate + index extension metadata; None is a no-op so the
    register_* SPI can forward its optional `meta` unconditionally.
    `strict` overrides the discovery-scoped collision policy explicitly
    (None = inherit the module flag)."""
    if meta is None:
        return
    validate_meta(meta, kind)
    key = (kind, meta.namespace or "", meta.name.lower())
    with _REGISTRY_LOCK:
        eff_strict = _strict_collisions if strict is None else strict
        if eff_strict and key in _REGISTRY:
            raise ExtensionError(
                f"duplicate {kind} extension "
                f"{(meta.namespace + ':') if meta.namespace else ''}"
                f"{meta.name!r} (already registered) — entry-point extensions "
                f"must use unique namespace:name pairs")
        _REGISTRY[key] = meta


def meta_for(kind: str, name: str, namespace: str = ""):
    return _REGISTRY.get((kind, namespace or "", name.lower()))


def all_meta(kind: str) -> list:
    return sorted((m for (k, _ns, _n), m in _REGISTRY.items() if k == kind),
                  key=lambda m: (m.namespace, m.name))


# ---------------------------------------------------------------------------
# built-in surface metadata (windows + aggregators compile directly; the
# registries only hold user extensions, so the built-ins declare here)
# ---------------------------------------------------------------------------

def _w(name, desc, params, example, edesc, returns="current + expired "
       "events per the window's retention policy"):
    return ExtensionMeta(name=name, description=desc, parameters=params,
                         examples=(Example(example, edesc),),
                         returns=returns)


_NUM = ("INT", "LONG", "FLOAT", "DOUBLE")
_TIME = ("TIME (constant like `1 sec`)", "LONG (millis)")

BUILTIN_WINDOWS = [
    _w("length",
       "Sliding window holding the most recent N events (reference "
       "LengthWindowProcessor).",
       (Parameter("window.length", ("INT",), "number of events retained"),),
       "from S#window.length(10) select sum(x) as s insert into O;",
       "running sum over the last 10 events"),
    _w("lengthBatch",
       "Tumbling window emitting every N-th event as one batch "
       "(reference LengthBatchWindowProcessor).",
       (Parameter("window.length", ("INT",), "batch size in events"),),
       "from S#window.lengthBatch(4) select avg(x) as m insert into O;",
       "average per completed 4-event batch"),
    _w("time",
       "Sliding window holding events younger than D (reference "
       "TimeWindowProcessor).",
       (Parameter("window.time", _TIME, "retention duration"),),
       "from S#window.time(1 sec) select count() as c insert into O;",
       "events seen in the last second"),
    _w("timeBatch",
       "Tumbling window emitting once per period D (reference "
       "TimeBatchWindowProcessor).",
       (Parameter("window.time", _TIME, "batch period"),
        Parameter("start.time", ("INT", "LONG"),
                  "anchor offset for the first batch", optional=True,
                  default=0),),
       "from S#window.timeBatch(5 sec) select sum(x) as s insert into O;",
       "per-5-second tumbling sums"),
    _w("timeLength",
       "Sliding window bounded by BOTH a duration and a max event count "
       "(reference TimeLengthWindowProcessor).",
       (Parameter("window.time", _TIME, "retention duration"),
        Parameter("window.length", ("INT",), "max events retained"),),
       "from S#window.timeLength(2 sec, 10) select avg(x) as m "
       "insert into O;",
       "average over at most 10 events no older than 2s"),
    _w("externalTime",
       "Sliding duration window driven by an event attribute instead of "
       "the wall clock (reference ExternalTimeWindowProcessor).",
       (Parameter("timestamp", ("LONG",),
                  "attribute carrying event time in millis"),
        Parameter("window.time", _TIME, "retention duration"),),
       "from S#window.externalTime(ts, 1 sec) select count() as c "
       "insert into O;",
       "event-time sliding count"),
    _w("externalTimeBatch",
       "Tumbling duration window driven by an event attribute (reference "
       "ExternalTimeBatchWindowProcessor).",
       (Parameter("timestamp", ("LONG",), "event-time attribute"),
        Parameter("window.time", _TIME, "batch period"),
        Parameter("start.time", ("INT", "LONG"), "first batch anchor",
                  optional=True, default=0),
        Parameter("timeout", _TIME, "flush an incomplete batch after "
                  "this idle time", optional=True, default=0),),
       "from S#window.externalTimeBatch(ts, 1 sec) select sum(x) as s "
       "insert into O;",
       "event-time tumbling sums"),
    _w("batch",
       "Re-emits each arriving micro-batch as one window generation "
       "(reference BatchWindowProcessor).",
       (Parameter("window.length", ("INT",), "optional size cap",
                  optional=True, default=0),),
       "from S#window.batch() select x insert into O;",
       "pass each ingest batch through as a unit"),
    _w("session",
       "Groups events into sessions separated by a silence gap "
       "(reference SessionWindowProcessor).",
       (Parameter("session.gap", _TIME, "idle gap ending a session"),
        Parameter("session.key", ("STRING",), "per-key sessions",
                  optional=True, default="single shared session"),
        Parameter("allowed.latency", _TIME, "late-arrival grace",
                  optional=True, default=0),),
       "from S#window.session(2 sec, user) select user, count() as c "
       "insert into O;",
       "events per user session"),
    _w("sort",
       "Keeps the top/bottom N events by a sort key (reference "
       "SortWindowProcessor).",
       (Parameter("window.length", ("INT",), "events retained"),
        Parameter("attribute", ("any comparable attribute",),
                  "sort key(s), each optionally followed by 'asc'/'desc'"),),
       "from S#window.sort(5, price, 'desc') select price insert into O;",
       "the 5 highest prices seen"),
    _w("delay",
       "Re-emits events after a fixed delay (reference "
       "DelayWindowProcessor).",
       (Parameter("window.delay", _TIME, "delay duration"),),
       "from S#window.delay(1 sec) select x insert into O;",
       "everything shifted one second later"),
    _w("frequent",
       "Retains the N most frequently recurring event groups "
       "(reference FrequentWindowProcessor, Misra-Gries).",
       (Parameter("event.count", ("INT",), "distinct groups retained"),
        Parameter("attribute", ("any attribute",),
                  "grouping attributes (defaults to all)", optional=True,
                  default="all attributes"),),
       "from S#window.frequent(3, sym) select sym insert into O;",
       "events of the 3 most frequent symbols"),
    _w("lossyFrequent",
       "Frequency-threshold retention with bounded error (reference "
       "LossyFrequentWindowProcessor, lossy counting).",
       (Parameter("support.threshold", ("DOUBLE",),
                  "minimum frequency fraction"),
        Parameter("error.bound", ("DOUBLE",), "allowed undercount",
                  optional=True, default="support/10"),
        Parameter("attribute", ("any attribute",), "grouping attributes",
                  optional=True, default="all attributes"),),
       "from S#window.lossyFrequent(0.1, 0.01) select * insert into O;",
       "events whose group exceeds 10% frequency"),
    _w("cron",
       "Tumbling window flushed on a cron schedule (reference "
       "CronWindowProcessor).",
       (Parameter("cron.expression", ("STRING",),
                  "quartz-style cron schedule"),),
       "from S#window.cron('0 * * * * ?') select count() as c "
       "insert into O;",
       "per-minute counts"),
]

_AGG_RET = "one aggregated value per group per output event"

BUILTIN_AGGREGATORS = [
    ExtensionMeta("sum", "Sum of the argument over the window/group "
                  "(reference SumAttributeAggregator).",
                  parameters=(Parameter("arg", _NUM, "value to sum"),),
                  examples=(Example(
                      "select sum(volume) as v", "total volume"),),
                  returns="LONG for int/long args, DOUBLE otherwise"),
    ExtensionMeta("count", "Event count (reference "
                  "CountAttributeAggregator).",
                  parameters=(Parameter("arg", ("none",),
                                        "no argument: counts events",
                                        optional=True, default="-"),),
                  examples=(Example("select count() as c", "group size"),),
                  returns="LONG"),
    ExtensionMeta("avg", "Arithmetic mean (reference "
                  "AvgAttributeAggregator).",
                  parameters=(Parameter("arg", _NUM, "value to average"),),
                  examples=(Example("select avg(price) as p", "mean "
                                    "price"),),
                  returns="DOUBLE"),
    ExtensionMeta("min", "Minimum within the window/group (reference "
                  "MinAttributeAggregator); expired events restore "
                  "earlier minima.",
                  parameters=(Parameter("arg", _NUM + ("STRING",),
                                        "value to minimize"),),
                  examples=(Example("select min(price) as lo",
                                    "lowest retained price"),),
                  returns=_AGG_RET),
    ExtensionMeta("max", "Maximum within the window/group (reference "
                  "MaxAttributeAggregator).",
                  parameters=(Parameter("arg", _NUM + ("STRING",),
                                        "value to maximize"),),
                  examples=(Example("select max(price) as hi",
                                    "highest retained price"),),
                  returns=_AGG_RET),
    ExtensionMeta("minForever", "All-time minimum — never expires "
                  "(reference MinForeverAttributeAggregator).",
                  parameters=(Parameter("arg", _NUM, "value"),),
                  examples=(Example("select minForever(price) as lo",
                                    "lowest price ever seen"),),
                  returns=_AGG_RET),
    ExtensionMeta("maxForever", "All-time maximum (reference "
                  "MaxForeverAttributeAggregator).",
                  parameters=(Parameter("arg", _NUM, "value"),),
                  examples=(Example("select maxForever(price) as hi",
                                    "highest price ever seen"),),
                  returns=_AGG_RET),
    ExtensionMeta("stdDev", "Population standard deviation (reference "
                  "StdDevAttributeAggregator).",
                  parameters=(Parameter("arg", _NUM, "value"),),
                  examples=(Example("select stdDev(price) as sd",
                                    "price volatility"),),
                  returns="DOUBLE"),
    ExtensionMeta("distinctCount", "Count of distinct argument values "
                  "(reference DistinctCountAttributeAggregator).",
                  parameters=(Parameter("arg", ("any attribute",),
                                        "value whose distincts count"),),
                  examples=(Example("select distinctCount(sym) as n",
                                    "distinct symbols in window"),),
                  returns="LONG"),
    ExtensionMeta("and", "Boolean AND over the group (reference "
                  "AndAttributeAggregator).",
                  parameters=(Parameter("arg", ("BOOL",), "conditions"),),
                  examples=(Example("select and(ok) as allOk",
                                    "true when every event is ok"),),
                  returns="BOOL"),
    ExtensionMeta("or", "Boolean OR over the group (reference "
                  "OrAttributeAggregator).",
                  parameters=(Parameter("arg", ("BOOL",), "conditions"),),
                  examples=(Example("select or(alarm) as anyAlarm",
                                    "true when any event alarms"),),
                  returns="BOOL"),
    ExtensionMeta("unionSet", "Accumulates values into a set (reference "
                  "UnionSetAttributeAggregator).",
                  parameters=(Parameter("arg", ("OBJECT (set)",
                                                "any attribute"),
                                        "sets/values to union"),),
                  examples=(Example("select unionSet(createSet(sym)) as "
                                    "syms", "set of symbols seen"),),
                  returns="OBJECT (set)"),
]

for _m in BUILTIN_WINDOWS:
    register_meta("window", _m)
for _m in BUILTIN_AGGREGATORS:
    register_meta("aggregator", _m)


# ---------------------------------------------------------------------------
# entry-point discovery (reference: core:util/SiddhiExtensionLoader.java:50-95
# scans the annotation-indexed classpath for @Extension classes and fills the
# namespace:name -> class map; the Python analog scans installed packages'
# entry points)
# ---------------------------------------------------------------------------

ENTRY_POINT_GROUP = "siddhi_tpu.extensions"
_discovered = False
_loaded_eps: set = set()      # "name = module:attr" values already invoked


def discover_extensions(force: bool = False) -> list:
    """Scan installed distributions for `[siddhi_tpu.extensions]` entry
    points and invoke each (the loaded object must be a callable that
    performs its `register_*` calls, passing ExtensionMeta so the
    registration-time validation tier applies).  During the scan,
    namespace:name collisions in the metadata registry raise
    ExtensionError instead of silently overwriting (the reference loader
    logs-and-keeps-first; we fail loud).  Runs once per process unless
    `force`; returns the entry-point names loaded this call."""
    global _discovered, _strict_collisions
    # the whole scan runs under the registry lock: the strict-collision
    # flag flip is never observable to concurrent register_meta callers
    # (which would otherwise spuriously raise on a legitimate override),
    # and two threads creating managers at once scan serially.  The
    # discovery thread's own nested register_* calls re-enter the RLock.
    with _REGISTRY_LOCK:
        if _discovered and not force:
            return []
        import importlib.metadata as md
        try:
            eps = md.entry_points(group=ENTRY_POINT_GROUP)
        except TypeError:       # pre-3.10 signature
            eps = md.entry_points().get(ENTRY_POINT_GROUP, [])
        loaded = []
        _strict_collisions = True
        try:
            for ep in eps:
                ident = f"{ep.name}={ep.value}"
                if ident in _loaded_eps:
                    continue      # forced rescan: only NEW entry points run
                reg = ep.load()
                if not callable(reg):
                    raise ExtensionError(
                        f"entry point {ep.name!r} in group "
                        f"{ENTRY_POINT_GROUP!r} must load to a callable "
                        f"register function, got {type(reg).__name__}")
                reg()
                _loaded_eps.add(ident)
                loaded.append(ep.name)
            # only a FULLY successful scan latches: a failing entry point
            # can be fixed/uninstalled and the next manager retries the rest
            _discovered = True
        finally:
            _strict_collisions = False
        return loaded
