"""Shared-memory frame ring for co-located producers.

A single-producer/single-consumer ring over
`multiprocessing.shared_memory`, carrying the SAME frame bytes as the
TCP/WS transports (net/frame.py) — a producer process encodes columnar
frames and pushes them through shared memory with no socket, no
serialization beyond the frame itself, and no copies on the consumer
side until the numpy column views are built.

Layout (all little-endian, 64-byte header then `slots` fixed slots):

    header:  0  u32  magic 0x53524E47 ("SRNG")
             4  u32  version (1)
             8  u32  slots
            12  u32  slot_size   (payload capacity per slot + 16)
            16  u64  head        (frames pushed;  producer-owned)
            24  u64  tail        (frames popped;  consumer-owned)
            32  ..   reserved
    slot i (at 64 + i*slot_size):
             0  u64  seq         (seqlock: slot holds frame `seq-1`)
             8  u32  length      (payload bytes)
            12  u32  reserved
            16  ..   payload

Seqlock discipline: the producer writes payload THEN publishes
`seq = frame_index + 1`; the consumer reads `seq`, and only when it
equals its expected index + 1 copies the payload out and advances
`tail`.  head/tail are monotonic u64 frame counts; slot index =
count % slots.  Aligned 8-byte stores through memoryview are atomic
enough on every platform CPython runs on for this SPSC pattern (one
writer per field).

Waiting is busy/park hybrid: spin ~200 iterations, then sleep with
exponential backoff capped at 2 ms — sub-µs latency when hot, ~zero
CPU when idle.
"""
from __future__ import annotations

import struct
import time
from multiprocessing import shared_memory
from typing import Optional

MAGIC = 0x53524E47
VERSION = 1
HEADER_SIZE = 64
SLOT_OVERHEAD = 16

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


class RingError(Exception):
    pass


class ShmRing:
    """One SPSC shared-memory frame ring.  `create()` on the owning
    (consumer/engine) side, `attach()` from the producer; both ends
    call `close()`, the owner also `unlink()`s."""

    def __init__(self, shm: shared_memory.SharedMemory, owner: bool):
        self.shm = shm
        self.owner = owner
        self.buf = shm.buf
        magic = _U32.unpack_from(self.buf, 0)[0]
        if magic != MAGIC:
            raise RingError(f"not a siddhi ring (magic 0x{magic:08x})")
        ver = _U32.unpack_from(self.buf, 4)[0]
        if ver != VERSION:
            raise RingError(f"unsupported ring version {ver}")
        self.slots = _U32.unpack_from(self.buf, 8)[0]
        self.slot_size = _U32.unpack_from(self.buf, 12)[0]
        self.capacity = self.slot_size - SLOT_OVERHEAD

    # -- construction -------------------------------------------------------

    @classmethod
    def create(cls, name: Optional[str] = None, slots: int = 64,
               slot_size: int = 256 << 10) -> "ShmRing":
        slots = int(slots)
        slot_size = int(slot_size) + SLOT_OVERHEAD
        size = HEADER_SIZE + slots * slot_size
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
        buf = shm.buf
        buf[:HEADER_SIZE] = b"\x00" * HEADER_SIZE
        _U32.pack_into(buf, 0, MAGIC)
        _U32.pack_into(buf, 4, VERSION)
        _U32.pack_into(buf, 8, slots)
        _U32.pack_into(buf, 12, slot_size)
        return cls(shm, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        return cls(shared_memory.SharedMemory(name=name), owner=False)

    @property
    def name(self) -> str:
        return self.shm.name

    # -- counters -----------------------------------------------------------

    @property
    def head(self) -> int:
        return _U64.unpack_from(self.buf, 16)[0]

    @property
    def tail(self) -> int:
        return _U64.unpack_from(self.buf, 24)[0]

    def occupancy(self) -> tuple:
        """(frames in flight, slots)."""
        return self.head - self.tail, self.slots

    # -- producer side ------------------------------------------------------

    def push(self, data: bytes, timeout: Optional[float] = None) -> bool:
        """Publish one frame.  Blocks (hybrid wait) while the ring is
        full; returns False if `timeout` elapses first, True on
        publish.  Single producer only."""
        n = len(data)
        if n > self.capacity:
            raise RingError(f"frame ({n} bytes) exceeds slot capacity "
                            f"({self.capacity}); raise slot.size or split "
                            f"the batch")
        head = self.head
        if not self._wait(lambda: self.head - self.tail < self.slots,
                          timeout):
            return False
        off = HEADER_SIZE + (head % self.slots) * self.slot_size
        self.buf[off + SLOT_OVERHEAD:off + SLOT_OVERHEAD + n] = data
        _U32.pack_into(self.buf, off + 8, n)
        _U64.pack_into(self.buf, off, head + 1)      # seqlock publish
        _U64.pack_into(self.buf, 16, head + 1)       # head
        return True

    # -- consumer side ------------------------------------------------------

    def pop(self, timeout: Optional[float] = None) -> Optional[bytes]:
        """Take the next frame (copied out of the slot), or None on
        timeout.  Single consumer only."""
        tail = self.tail
        off = HEADER_SIZE + (tail % self.slots) * self.slot_size
        if not self._wait(
                lambda: _U64.unpack_from(self.buf, off)[0] == tail + 1,
                timeout):
            return None
        n = _U32.unpack_from(self.buf, off + 8)[0]
        data = bytes(self.buf[off + SLOT_OVERHEAD:off + SLOT_OVERHEAD + n])
        _U64.pack_into(self.buf, 24, tail + 1)       # tail: slot reusable
        return data

    def join(self, timeout: Optional[float] = None) -> bool:
        """Producer-side barrier: wait until the consumer drained every
        pushed frame (tail == head)."""
        return self._wait(lambda: self.tail >= self.head, timeout)

    # -- hybrid wait --------------------------------------------------------

    @staticmethod
    def _wait(cond, timeout: Optional[float]) -> bool:
        for _ in range(200):            # busy phase: sub-µs wakeups
            if cond():
                return True
        deadline = None if timeout is None else time.monotonic() + timeout
        park = 50e-6
        while not cond():
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(park)
            park = min(park * 2, 2e-3)  # park phase: bounded CPU
        return True

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        try:
            # the memoryview must go before SharedMemory.close()
            self.buf = None
            self.shm.close()
        except Exception:
            pass

    def unlink(self) -> None:
        try:
            self.shm.unlink()
        except Exception:
            pass
