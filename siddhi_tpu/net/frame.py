"""Columnar wire frame protocol — the serving plane's binary transport.

Replaces per-event JSON-over-HTTP with length-prefixed binary frames
whose DATA payloads are raw little-endian column buffers decoded
straight into numpy views (`np.frombuffer`) and fed to
`BatchBuilder.append_columnar` with zero per-event Python.  The same
frame bytes ride TCP, WebSocket binary messages, and the shared-memory
ring (net/ring.py) unchanged.

Frame layout (all integers little-endian):

    offset  size  field
    0       2     magic   0x5346  ("SF")
    2       1     version (1)
    3       1     type    (FrameType)
    4       4     payload length N
    8       N     payload
    8+N     4     CRC32 of payload (zlib.crc32)

Frame types and payloads:

    HELLO (1), client->server, JSON: {"app", "stream",
        "cols": [[name, type], ...], "credit": bool}.  Schema is
        negotiated ONCE per connection: names/types must match the
        stream definition in order; every later DATA frame is raw
        buffers with no per-frame schema.
    HELLO_OK (2), server->client, JSON: {"ok": true, "credit": int}.
    DATA (3): u32 n_rows, then the int64 timestamp column
        (n_rows * 8 bytes), then each schema column's raw buffer in
        declaration order (string columns as int32 CONNECTION-LOCAL
        dictionary codes — see STRINGS).
    STRINGS (4): string-table delta — u32 start_code, u32 count, then
        per string u16 utf-8 byte length + bytes; the first string
        holds `start_code`, the rest follow sequentially.  Codes are
        assigned from 1 upward on both ends (code 0 is reserved for
        null, mirroring schema.StringTable); the explicit start makes
        re-sent deltas idempotent and lost-delta gaps loud.  The
        server remaps connection codes -> runtime StringTable codes
        with one vectorized gather per DATA frame.
    CREDIT (5), server->client: i64 additional DATA frames the client
        may send (explicit backpressure/credit signaling; a server
        under admission pressure simply stops granting).
    ACK (6), server->client: u64 token — reply to PING after
        everything before the PING has been admitted and fed.
    ERROR (7), server->client, JSON: {"error": "..."}.
    PING (8), client->server: u64 token (the flush barrier).
    BYE (9): empty; graceful close.
    TRACE (10), either direction, JSON: {"trace": "<id>", "span": int}
        — OPTIONAL trace-context extension (docs/OBSERVABILITY.md
        "Frame tracing").  Applies to the NEXT DATA frame on this
        connection: the server adopts the producer-stamped trace id
        for that frame's span tree (always traced, bypassing
        sampling), and net sinks re-stamp egress DATA frames with the
        ingress id so traces compose across engine hops.  `span`
        is the sender's current head span id (0 = none), recorded as
        the downstream root's `remote_parent` annotation (span ids
        are host-local).  Receivers that do not trace consume it.

REPL frame family — hot-standby WAL replication (docs/RELIABILITY.md
"High availability & failover").  A standby connects to the primary's
frame port and sends REPL_SUBSCRIBE; the connection then becomes a
replication link: the primary streams WAL records (and snapshot
revisions for catch-up) down it, the standby streams append-acks back.

    REPL_SUBSCRIBE (11), standby->primary, JSON: {"app": name,
        "watermark": {stream: seq}, "generation": int} — subscribe to
        the app's WAL from the standby's durable per-stream watermark.
        `generation` is the highest fencing token the standby has
        seen (0 on a fresh log).
    REPL_RECORD (12), primary->standby: u64 generation, then one raw
        WAL record (wal.py layout, its own CRC) verbatim — the
        standby appends it byte-identically at its explicit seq.
    REPL_SNAPSHOT (13), primary->standby: u64 generation, u32 meta
        length, meta JSON {"revision", "watermark": {...}|null,
        "final": bool}, then the revision blob — Revision shipping
        for catch-up when the standby's watermark is behind a
        snapshot-barrier truncation.  A chain ships oldest-first;
        only the `final` frame's watermark floors the standby's seqs.
    REPL_HEARTBEAT (14), primary->standby, JSON: {"generation",
        "watermark": {stream: seq}, "ts_ms"} — periodic watermark so
        the standby can compute replication lag while idle.
    REPL_ACK (15), standby->primary, JSON: {"generation",
        "watermark": {stream: seq}} — everything at-or-below the
        watermark is appended (and, per the standby's sync policy,
        synced) on the standby.  Under semi-sync this is half of the
        primary's durable-ACK barrier.

QUERY/RESULT — wire-served store queries (docs/AGGREGATION.md "Store
queries over the wire").  A client sends a SiddhiQL store query string;
the server compiles it once per connection (cached by query text),
executes it against live tables/windows/aggregations under the runtime
feed gate, and streams the rows back in the standard columnar DATA
encoding, string columns as dictionary codes against a SERVER->client
egress string table shipped as STRINGS deltas before the RESULT.

    QUERY (16), client->server: u64 token, u16 app-name byte length,
        app-name utf-8 (may be empty: the HELLO-bound app), then the
        SiddhiQL store query text utf-8 to the end of the payload.
    RESULT (17), server->client: u64 token (echoing the QUERY), u32
        meta length, meta JSON {"cols": [[name, type], ...]} (or
        {"error": "..."} with an empty body — errors ride RESULT, not
        ERROR, so token correlation survives pipelining), then a
        DATA-layout body: u32 n_rows, i64 timestamps, each column's
        raw little-endian buffer in meta-declared order.  `double`
        columns are always float64 on this plane (store-query rows are
        host Python floats) regardless of the engine's compute dtype;
        numeric nulls encode as NaN (floats) / 0 (ints), string nulls
        as code 0.

docs/SERVING.md carries the normative spec with a worked hex example.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Callable

import numpy as np

MAGIC = 0x5346
VERSION = 1
HEADER = struct.Struct("<HBBI")          # magic, version, type, payload len
TRAILER = struct.Struct("<I")            # crc32(payload)
MAX_PAYLOAD = 64 << 20                   # 64 MiB sanity bound

HELLO = 1
HELLO_OK = 2
DATA = 3
STRINGS = 4
CREDIT = 5
ACK = 6
ERROR = 7
PING = 8
BYE = 9
TRACE = 10
REPL_SUBSCRIBE = 11
REPL_RECORD = 12
REPL_SNAPSHOT = 13
REPL_HEARTBEAT = 14
REPL_ACK = 15
QUERY = 16
RESULT = 17

_TYPE_NAMES = {HELLO: "HELLO", HELLO_OK: "HELLO_OK", DATA: "DATA",
               STRINGS: "STRINGS", CREDIT: "CREDIT", ACK: "ACK",
               ERROR: "ERROR", PING: "PING", BYE: "BYE", TRACE: "TRACE",
               REPL_SUBSCRIBE: "REPL_SUBSCRIBE", REPL_RECORD: "REPL_RECORD",
               REPL_SNAPSHOT: "REPL_SNAPSHOT",
               REPL_HEARTBEAT: "REPL_HEARTBEAT", REPL_ACK: "REPL_ACK",
               QUERY: "QUERY", RESULT: "RESULT"}


class FrameError(Exception):
    """Malformed frame: a payload that does not parse, a rejected
    HELLO, or a stream desync.  Whether it kills the connection depends
    on where it surfaces: payload-level errors on a negotiated
    connection are rejected per-frame (the length prefix was already
    consumed, so framing stays aligned); desyncs are fatal."""


class FrameDesync(FrameError):
    """Bad magic/version/oversized length: the byte stream can no
    longer be trusted at all — connection-fatal."""


def type_name(t: int) -> str:
    return _TYPE_NAMES.get(t, f"type{t}")


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

def encode_frame(ftype: int, payload: bytes = b"") -> bytes:
    """One complete frame: header + payload + crc trailer."""
    return (HEADER.pack(MAGIC, VERSION, ftype, len(payload)) + payload
            + TRAILER.pack(zlib.crc32(payload) & 0xFFFFFFFF))


def encode_hello(app: str, stream: str, cols: list, credit: bool = True) -> bytes:
    """cols: [(name, type_name), ...] in declaration order; type names
    are the SiddhiQL attribute types ("string", "double", ...)."""
    return encode_frame(HELLO, json.dumps(
        {"app": app, "stream": stream, "cols": [list(c) for c in cols],
         "credit": bool(credit)}).encode())


def encode_hello_ok(credit: int) -> bytes:
    return encode_frame(HELLO_OK, json.dumps(
        {"ok": True, "credit": int(credit)}).encode())


def encode_error(message: str) -> bytes:
    return encode_frame(ERROR, json.dumps({"error": message}).encode())


def encode_credit(n: int) -> bytes:
    return encode_frame(CREDIT, struct.pack("<q", int(n)))


def encode_ack(token: int) -> bytes:
    return encode_frame(ACK, struct.pack("<Q", int(token)))


def encode_ping(token: int) -> bytes:
    return encode_frame(PING, struct.pack("<Q", int(token)))


def encode_trace(trace_id: str, span: int = 0) -> bytes:
    """Trace-context frame stamping the NEXT DATA frame (see the module
    docstring); `span` is the sender's head span id (0 = none) — the
    receiver annotates its root with it as `remote_parent`."""
    return encode_frame(TRACE, json.dumps(
        {"trace": str(trace_id), "span": int(span)}).encode())


def decode_trace(payload: bytes) -> tuple:
    """-> (trace_id, span)."""
    try:
        d = json.loads(payload)
        if not isinstance(d, dict) or not d.get("trace"):
            raise ValueError("missing trace id")
        return str(d["trace"]), int(d.get("span", 0) or 0)
    except (ValueError, TypeError, UnicodeDecodeError) as e:
        raise FrameError(f"bad TRACE payload: {e}") from None


# -- REPL family (hot-standby WAL replication) ------------------------------

def _watermark_dict(wm) -> dict:
    return {str(k): int(v) for k, v in (wm or {}).items()}


def encode_repl_subscribe(app: str, watermark: dict,
                          generation: int = 0) -> bytes:
    return encode_frame(REPL_SUBSCRIBE, json.dumps(
        {"app": str(app), "watermark": _watermark_dict(watermark),
         "generation": int(generation)}).encode())


def decode_repl_subscribe(payload: bytes) -> dict:
    try:
        d = json.loads(payload)
        if not isinstance(d, dict) or not d.get("app"):
            raise ValueError("missing app")
        d["watermark"] = _watermark_dict(d.get("watermark"))
        d["generation"] = int(d.get("generation", 0) or 0)
        return d
    except (ValueError, TypeError, UnicodeDecodeError) as e:
        raise FrameError(f"bad REPL_SUBSCRIBE payload: {e}") from None


def encode_repl_record(generation: int, record: bytes) -> bytes:
    """`record` is one raw WAL record (wal.py layout, self-CRC'd) —
    shipped verbatim so the standby's log is byte-identical."""
    return encode_frame(REPL_RECORD,
                        struct.pack("<Q", int(generation)) + record)


def decode_repl_record(payload: bytes) -> tuple:
    """-> (generation, raw_record_bytes)."""
    if len(payload) < 8:
        raise FrameError("truncated REPL_RECORD payload")
    (gen,) = struct.unpack_from("<Q", payload, 0)
    return gen, payload[8:]


def encode_repl_snapshot(generation: int, revision: str, watermark,
                         blob: bytes, final: bool = True) -> bytes:
    meta = json.dumps({"revision": str(revision),
                       "watermark": None if watermark is None
                       else _watermark_dict(watermark),
                       "final": bool(final)}).encode()
    return encode_frame(REPL_SNAPSHOT,
                        struct.pack("<QI", int(generation), len(meta))
                        + meta + blob)


def decode_repl_snapshot(payload: bytes) -> tuple:
    """-> (generation, meta_dict, blob_bytes)."""
    if len(payload) < 12:
        raise FrameError("truncated REPL_SNAPSHOT payload")
    gen, mlen = struct.unpack_from("<QI", payload, 0)
    if 12 + mlen > len(payload):
        raise FrameError("truncated REPL_SNAPSHOT meta")
    try:
        meta = json.loads(payload[12:12 + mlen])
        if not isinstance(meta, dict) or not meta.get("revision"):
            raise ValueError("missing revision")
    except (ValueError, UnicodeDecodeError) as e:
        raise FrameError(f"bad REPL_SNAPSHOT meta: {e}") from None
    return gen, meta, payload[12 + mlen:]


def encode_repl_heartbeat(generation: int, watermark: dict,
                          ts_ms: int) -> bytes:
    return encode_frame(REPL_HEARTBEAT, json.dumps(
        {"generation": int(generation),
         "watermark": _watermark_dict(watermark),
         "ts_ms": int(ts_ms)}).encode())


def encode_repl_ack(generation: int, watermark: dict) -> bytes:
    return encode_frame(REPL_ACK, json.dumps(
        {"generation": int(generation),
         "watermark": _watermark_dict(watermark)}).encode())


def decode_repl_status(payload: bytes) -> dict:
    """Shared decoder for REPL_HEARTBEAT and REPL_ACK (both are a
    {generation, watermark[, ts_ms]} JSON object)."""
    try:
        d = json.loads(payload)
        if not isinstance(d, dict):
            raise ValueError("not an object")
        d["generation"] = int(d.get("generation", 0) or 0)
        d["watermark"] = _watermark_dict(d.get("watermark"))
        return d
    except (ValueError, TypeError, UnicodeDecodeError) as e:
        raise FrameError(f"bad REPL status payload: {e}") from None


# -- QUERY/RESULT (wire-served store queries) -------------------------------

def encode_query(token: int, text: str, app: str = None) -> bytes:
    """Store-query request: the SiddhiQL text runs server-side against
    the named app (empty -> the connection's HELLO-bound app)."""
    ab = (app or "").encode()
    if len(ab) > 0xFFFF:
        raise FrameError(f"app name too long for wire ({len(ab)} bytes)")
    return encode_frame(QUERY, struct.pack("<QH", int(token), len(ab))
                        + ab + str(text).encode())


def decode_query(payload: bytes) -> tuple:
    """-> (token, app_or_None, query_text)."""
    if len(payload) < 10:
        raise FrameError("truncated QUERY payload")
    token, alen = struct.unpack_from("<QH", payload, 0)
    if 10 + alen > len(payload):
        raise FrameError("truncated QUERY app name")
    try:
        app = payload[10:10 + alen].decode()
        text = payload[10 + alen:].decode()
    except UnicodeDecodeError as e:
        raise FrameError(f"bad QUERY payload: {e}") from None
    if not text.strip():
        raise FrameError("empty QUERY text")
    return token, (app or None), text


def encode_result(token: int, meta: dict, body: bytes = b"") -> bytes:
    """Store-query reply.  `meta` is {"cols": [[name, type], ...]} (or
    {"error": str} with an empty body); `body` is a DATA-layout blob
    from `encode_data_payload`."""
    mb = json.dumps(meta).encode()
    return encode_frame(RESULT, struct.pack("<QI", int(token), len(mb))
                        + mb + body)


def decode_result(payload: bytes) -> tuple:
    """-> (token, meta_dict, body_bytes)."""
    if len(payload) < 12:
        raise FrameError("truncated RESULT payload")
    token, mlen = struct.unpack_from("<QI", payload, 0)
    if 12 + mlen > len(payload):
        raise FrameError("truncated RESULT meta")
    try:
        meta = json.loads(payload[12:12 + mlen])
        if not isinstance(meta, dict):
            raise ValueError("not an object")
    except (ValueError, UnicodeDecodeError) as e:
        raise FrameError(f"bad RESULT meta: {e}") from None
    return token, meta, payload[12 + mlen:]


def decode_result_body(body: bytes, cols: list) -> tuple:
    """RESULT body -> (timestamps view, [column views] in meta order).
    `cols` is the meta's [[name, type], ...]; string columns come back
    as int32 server-egress dictionary codes — resolve against the
    STRINGS deltas the server shipped on this connection.  `double` is
    always float64 here (see the module docstring)."""
    from ..core.schema import dtype_of
    from ..query.ast import AttrType
    if len(body) < 4:
        raise FrameError("truncated RESULT body")
    (n,) = struct.unpack_from("<I", body, 0)
    off = 4
    need = 8 * n
    if off + need > len(body):
        raise FrameError("truncated RESULT body (timestamps)")
    ts = np.frombuffer(body, dtype="<i8", count=n, offset=off)
    off += need
    out = []
    for c in cols:
        name, tname = str(c[0]), str(c[1])
        try:
            at = AttrType[tname.upper()]
        except KeyError:
            raise FrameError(f"RESULT column {name!r} has unknown type "
                             f"{tname!r}") from None
        dt = np.dtype(dtype_of(at, float64=True)).newbyteorder("<")
        if dt.kind == "O":
            raise FrameError(f"RESULT object column {name!r} cannot ride "
                             f"the wire")
        need = dt.itemsize * n
        if off + need > len(body):
            raise FrameError(f"truncated RESULT body (column {name!r})")
        out.append(np.frombuffer(body, dtype=dt, count=n, offset=off))
        off += need
    if off != len(body):
        raise FrameError(f"RESULT body has {len(body) - off} trailing bytes")
    return ts, out


def encode_strings(new_strings: list, start_code: int = None) -> bytes:
    """String-table delta frame; `new_strings` in code-assignment
    order, the first holding code `start_code`.  The explicit start
    makes deltas idempotent: a re-sent (full-table or overlapping)
    delta overwrites the same positions, and a GAP — a delta whose
    predecessor was lost — fails loudly instead of silently remapping
    every later code."""
    if start_code is None:
        start_code = 1
    parts = [struct.pack("<II", int(start_code), len(new_strings))]
    for s in new_strings:
        b = s.encode()
        if len(b) > 0xFFFF:
            raise FrameError(f"string too long for wire ({len(b)} bytes)")
        parts.append(struct.pack("<H", len(b)))
        parts.append(b)
    return encode_frame(STRINGS, b"".join(parts))


def encode_data_payload(timestamps: np.ndarray, columns: list) -> bytes:
    """The DATA columnar layout (u32 n_rows + i64 timestamps + raw
    column buffers) WITHOUT the frame envelope — shared by DATA frames
    and RESULT bodies."""
    ts = np.ascontiguousarray(timestamps, dtype="<i8")
    n = int(ts.shape[0])
    parts = [struct.pack("<I", n), ts.tobytes()]
    for col in columns:
        arr = np.ascontiguousarray(col)
        if arr.shape[0] != n:
            raise FrameError(f"column has {arr.shape[0]} rows, expected {n}")
        parts.append(arr.astype(arr.dtype.newbyteorder("<"),
                                copy=False).tobytes())
    return b"".join(parts)


def encode_data(timestamps: np.ndarray, columns: list) -> bytes:
    """DATA frame from an int64 timestamp array + schema-ordered column
    arrays (strings already encoded to int32 connection codes).  One
    `tobytes` per column — no per-event work."""
    return encode_frame(DATA, encode_data_payload(timestamps, columns))


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

def read_frame(read_exact: Callable[[int], bytes]) -> tuple:
    """Read one frame from a byte stream.  `read_exact(n)` must return
    exactly n bytes or raise EOFError/ConnectionError.  Returns
    (ftype, payload bytes); raises FrameError on protocol violations."""
    hdr = read_exact(HEADER.size)
    magic, ver, ftype, n = HEADER.unpack(hdr)
    if magic != MAGIC:
        raise FrameDesync(f"bad magic 0x{magic:04x} (want 0x{MAGIC:04x})")
    if ver != VERSION:
        raise FrameDesync(f"unsupported protocol version {ver}")
    if n > MAX_PAYLOAD:
        raise FrameDesync(f"oversized payload ({n} bytes)")
    payload = read_exact(n) if n else b""
    (crc,) = TRAILER.unpack(read_exact(TRAILER.size))
    if crc != (zlib.crc32(payload) & 0xFFFFFFFF):
        raise FrameError(f"checksum mismatch on {type_name(ftype)} frame")
    return ftype, payload


def _scan_frames(view: memoryview) -> tuple:
    """-> ([(ftype, payload), ...], consumed_offset) over any
    buffer-like object.  A frame whose CRC fails is returned as
    (ftype, None) — the length prefix already consumed it whole, so the
    stream stays aligned and the caller can reject that ONE frame
    without dropping the connection.  Desyncs (bad magic/version/
    oversized length) raise FrameDesync: past those, no later length
    can be trusted."""
    frames = []
    off = 0
    while len(view) - off >= HEADER.size + TRAILER.size:
        magic, ver, ftype, n = HEADER.unpack_from(view, off)
        if magic != MAGIC:
            raise FrameDesync(f"bad magic 0x{magic:04x}")
        if ver != VERSION:
            raise FrameDesync(f"unsupported protocol version {ver}")
        if n > MAX_PAYLOAD:
            raise FrameDesync(f"oversized payload ({n} bytes)")
        end = off + HEADER.size + n + TRAILER.size
        if end > len(view):
            break
        payload = bytes(view[off + HEADER.size:off + HEADER.size + n])
        (crc,) = TRAILER.unpack_from(view, off + HEADER.size + n)
        if crc != (zlib.crc32(payload) & 0xFFFFFFFF):
            payload = None              # corrupt but aligned: reject one
        frames.append((ftype, payload))
        off = end
    return frames, off


def parse_buffer(buf: bytes) -> tuple:
    """Parse as many complete frames as `buf` holds.  Returns
    ([(ftype, payload), ...], leftover_bytes) — the ring/WS path, where
    input arrives as discrete byte blobs rather than a stream."""
    view = memoryview(buf)
    try:
        frames, off = _scan_frames(view)
        return frames, bytes(view[off:])
    finally:
        view.release()


def parse_buffer_inplace(buf: bytearray) -> list:
    """parse_buffer over an accumulating bytearray: consumed frames are
    deleted from the FRONT of `buf` in place, and an incomplete tail
    stays put with NO copy — so socket readers appending 64 KB recv
    chunks stay O(total) instead of O(total^2) on multi-chunk frames."""
    view = memoryview(buf)
    try:
        frames, off = _scan_frames(view)
    finally:
        view.release()      # an exported view blocks bytearray resize
    if off:
        del buf[:off]
    return frames


def decode_hello(payload: bytes) -> dict:
    try:
        d = json.loads(payload)
        if not isinstance(d, dict) or "stream" not in d:
            raise ValueError("missing stream")
        d.setdefault("app", None)
        d.setdefault("cols", [])
        d.setdefault("credit", True)
        return d
    except (ValueError, UnicodeDecodeError) as e:
        raise FrameError(f"bad HELLO payload: {e}") from None


def decode_strings(payload: bytes) -> tuple:
    """-> (start_code, [strings])."""
    try:
        start, count = struct.unpack_from("<II", payload, 0)
        off = 8
        out = []
        for _ in range(count):
            (ln,) = struct.unpack_from("<H", payload, off)
            off += 2
            if off + ln > len(payload):
                raise ValueError("truncated string entry")
            out.append(payload[off:off + ln].decode())
            off += ln
        return start, out
    except (struct.error, UnicodeDecodeError, ValueError) as e:
        raise FrameError(f"bad STRINGS payload: {e}") from None


def validate_hello_schema(hello: dict, schema) -> None:
    """Negotiation check: the HELLO's declared columns must match the
    stream schema by name and type, in order."""
    want = [(a.name, a.type.name.lower()) for a in schema.attributes]
    got = [(str(c[0]), str(c[1]).lower()) for c in hello.get("cols", [])]
    if got != want:
        raise FrameError(
            f"schema mismatch for stream {schema.id!r}: client declared "
            f"{got}, server has {want}")


def decode_data(payload: bytes, schema, float64: bool = False) -> tuple:
    """DATA payload -> (timestamps view, {name: column view}).  Views
    alias the payload buffer zero-copy (read-only); string columns come
    back as int32 CONNECTION codes — remap before ingest."""
    from ..core.schema import dtype_of
    if len(payload) < 4:
        raise FrameError("truncated DATA payload")
    (n,) = struct.unpack_from("<I", payload, 0)
    off = 4
    need = 8 * n
    if off + need > len(payload):
        raise FrameError("truncated DATA payload (timestamps)")
    ts = np.frombuffer(payload, dtype="<i8", count=n, offset=off)
    off += need
    cols = {}
    for a in schema.attributes:
        dt = np.dtype(dtype_of(a.type, float64=float64)).newbyteorder("<")
        if dt.kind == "O":
            raise FrameError(
                f"stream {schema.id!r}: object column {a.name!r} cannot "
                f"ride the wire")
        need = dt.itemsize * n
        if off + need > len(payload):
            raise FrameError(f"truncated DATA payload (column {a.name!r})")
        cols[a.name] = np.frombuffer(payload, dtype=dt, count=n, offset=off)
        off += need
    if off != len(payload):
        raise FrameError(f"DATA payload has {len(payload) - off} "
                         f"trailing bytes")
    return ts, cols


def decode_i64(payload: bytes) -> int:
    try:
        return struct.unpack("<q", payload)[0]
    except struct.error as e:
        raise FrameError(f"bad credit payload: {e}") from None


def decode_u64(payload: bytes) -> int:
    try:
        return struct.unpack("<Q", payload)[0]
    except struct.error as e:
        raise FrameError(f"bad token payload: {e}") from None


# ---------------------------------------------------------------------------
# connection-local string dictionary (client side + server remap)
# ---------------------------------------------------------------------------

class WireStringTable:
    """Client-side connection dictionary: str -> sequential code from 1
    (0 = null, mirroring schema.StringTable).  `encode_column` returns
    the int32 code array plus the delta of never-sent strings — the
    caller ships the delta as ONE STRINGS frame before the DATA frame."""

    def __init__(self):
        self._to_code: dict = {}
        self._ordered: list = []        # strings in code order (code i+1)
        self._n = 1                     # 0 reserved for null

    def __len__(self) -> int:
        return self._n

    def all_strings(self) -> list:
        """Every string ever encoded, in code-assignment order — the
        full-table replay a reconnecting sink ships so already-encoded
        payloads keep decoding (codes <= len are stable; the peer's
        remap extends append-only, so re-declared strings are harmless
        duplicates at higher codes)."""
        return list(self._ordered)

    def strings_from(self, code: int) -> list:
        """Strings holding codes >= `code`, in order — the catch-up
        delta for a peer known to have mapped codes < `code`."""
        return list(self._ordered[max(0, code - 1):])

    def encode_column(self, values) -> tuple:
        arr = np.asarray(values)
        if arr.dtype.kind in "iu":
            raise FrameError(
                "wire string columns must be str values, not codes "
                "(dictionary codes are connection-local)")
        new: list = []
        if arr.dtype.kind == "U" and arr.ndim == 1:
            uniq, first, inv = np.unique(arr, return_index=True,
                                         return_inverse=True)
            codes = np.empty(len(uniq), dtype=np.int32)
            for j in np.argsort(first, kind="stable").tolist():
                s = str(uniq[j])
                c = self._to_code.get(s)
                if c is None:
                    c = self._to_code[s] = self._n
                    self._n += 1
                    self._ordered.append(s)
                    new.append(s)
                codes[j] = c
            return codes[inv], new
        out = np.empty(len(arr), dtype=np.int32)
        for i, v in enumerate(arr.tolist()):
            if v is None:
                out[i] = 0
                continue
            c = self._to_code.get(v)
            if c is None:
                c = self._to_code[v] = self._n
                self._n += 1
                self._ordered.append(str(v))
                new.append(str(v))
            out[i] = c
        return out, new


class StringRemap:
    """Server-side: connection code -> runtime StringTable code, applied
    as one vectorized gather per string column.  Extended under the
    runtime lock when a STRINGS delta arrives."""

    def __init__(self):
        self._map = np.zeros(1, dtype=np.int32)     # code 0 -> null (0)

    def __len__(self) -> int:
        return int(self._map.shape[0])

    def extend(self, start_code: int, new_strings: list, strings) -> None:
        """Apply a STRINGS delta starting at `start_code`.  `strings` is
        the runtime's schema.StringTable; caller holds the runtime lock
        (table writes are shared state).  Overlapping re-declarations
        overwrite idempotently; a gap (a lost predecessor delta) raises."""
        if not new_strings:
            return
        if start_code > self._map.shape[0]:
            raise FrameError(
                f"STRINGS delta starts at code {start_code} but only "
                f"{self._map.shape[0]} codes are mapped (lost delta?)")
        add = np.fromiter((strings.encode(s) for s in new_strings),
                          dtype=np.int32, count=len(new_strings))
        end = start_code + len(new_strings)
        if end > self._map.shape[0]:
            self._map = np.concatenate(
                [self._map, np.zeros(end - self._map.shape[0],
                                     dtype=np.int32)])
        self._map[start_code:end] = add

    def apply(self, codes: np.ndarray) -> np.ndarray:
        arr = np.asarray(codes)
        if arr.size and (int(arr.max(initial=0)) >= self._map.shape[0]
                         or int(arr.min(initial=0)) < 0):
            raise FrameError(
                "DATA frame references string codes never declared in a "
                "STRINGS delta (out-of-order frames?)")
        return self._map[arr.astype(np.int64, copy=False)]


def _scan_ws_frame(buf) -> tuple:
    """One complete RFC-6455 frame from the front of `buf` ->
    (opcode, body_bytes, end_offset), or None while incomplete —
    nothing is consumed until whole, so a read timeout mid-frame can
    never desync the stream.  Unmasks when the mask bit is set."""
    if len(buf) < 2:
        return None
    opcode = buf[0] & 0x0F
    masked = bool(buf[1] & 0x80)
    n = buf[1] & 0x7F
    off = 2
    if n == 126:
        if len(buf) < 4:
            return None
        n = struct.unpack_from(">H", buf, 2)[0]
        off = 4
    elif n == 127:
        if len(buf) < 10:
            return None
        n = struct.unpack_from(">Q", buf, 2)[0]
        off = 10
    if n > MAX_PAYLOAD + 64:
        # same sanity bound the raw-TCP path enforces on the length
        # prefix (+ header slack: one ws message wraps one protocol
        # frame) — without it a peer declaring a 2^40-byte message
        # grows the receive buffer without limit
        raise FrameDesync(
            f"websocket frame of {n} bytes exceeds the "
            f"{MAX_PAYLOAD >> 20} MiB bound")
    if masked:
        if len(buf) < off + 4:
            return None
        mask = bytes(buf[off:off + 4])
        off += 4
    else:
        mask = None
    if len(buf) < off + n:
        return None
    body = bytes(buf[off:off + n])
    if mask and n:
        arr = np.frombuffer(body, dtype=np.uint8)
        m = np.frombuffer((mask * ((n + 3) // 4))[:n], dtype=np.uint8)
        body = (arr ^ m).tobytes()
    return opcode, body, off + n


def parse_ws_frame(buf: bytes):
    """_scan_ws_frame returning (opcode, body, rest_bytes) — shared by
    the ws client and the server's ws path."""
    got = _scan_ws_frame(buf)
    if got is None:
        return None
    opcode, body, end = got
    return opcode, body, buf[end:]


def parse_ws_frame_inplace(buf: bytearray):
    """parse_ws_frame over an accumulating bytearray: the consumed
    message is deleted from the front in place (no tail copy) ->
    (opcode, body) or None while incomplete."""
    got = _scan_ws_frame(buf)
    if got is None:
        return None
    opcode, body, end = got
    del buf[:end]
    return opcode, body


def reader_for(sock) -> Callable[[int], bytes]:
    """`read_exact` over a socket for read_frame()."""
    def read_exact(n: int) -> bytes:
        chunks = []
        left = n
        while left:
            b = sock.recv(left)
            if not b:
                raise EOFError("connection closed mid-frame")
            chunks.append(b)
            left -= len(b)
        return b"".join(chunks)
    return read_exact
