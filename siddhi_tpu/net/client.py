"""Producer client library for the serving plane.

`TcpFrameClient` / `WsFrameClient` speak the columnar frame protocol
(net/frame.py) over loopback-or-real TCP / WebSocket; `RingProducer`
pushes the same frames through a shared-memory ring (net/ring.py) for
co-located producers.  All three share the encode path: string columns
are dictionary-encoded against a connection-local table whose deltas
ship as STRINGS frames, numeric columns go over the wire as raw
little-endian buffers — `send_batch` does no per-event Python.

`FrameReceiver` is the mirror half for sink egress: a tiny
accept-loop that decodes incoming frames back into columnar batches
(tests, downstream consumers, and `bench.py --net` use it).
"""
from __future__ import annotations

import json
import os
import base64
import socket
import struct
import threading
import time
from typing import Optional

import numpy as np

from ..utils.locks import new_lock
from . import frame as fp
from .ring import ShmRing

class NetClientError(Exception):
    pass


def _schema_cols(schema) -> list:
    return [(a.name, a.type.name.lower()) for a in schema.attributes]


def _batch_rows(columns: dict, timestamps) -> int:
    for v in columns.values():
        return int(np.asarray(v).shape[0])
    return int(np.asarray(timestamps).size)


class _FrameEncoder:
    """Shared columnar encode: schema order, string dictionary deltas."""

    def __init__(self, stream: str, cols: list, str_cols: set):
        from ..core.schema import dtype_of
        from ..query.ast import AttrType
        self.stream = stream
        self.cols = cols                       # [(name, type), ...]
        self.str_cols = str_cols               # names of string columns
        self.strings = fp.WireStringTable()
        # declared wire dtype per non-string column: values are CAST to
        # it before framing — an int array handed to a double column
        # must ship double bits, not get reinterpreted by the peer
        self.dtypes = {name: np.dtype(dtype_of(AttrType[t.upper()]))
                       for name, t in cols if name not in str_cols}

    def encode_batch(self, columns: dict, timestamps,
                     synced: int = None) -> bytes:
        """One batch -> (optional STRINGS frame) + DATA frame bytes.
        With `synced` (the highest code the peer is KNOWN to have
        mapped), the delta covers every code from there up — so a
        previously FAILED send whose delta never arrived is healed by
        the next one (explicit start codes make the re-declare
        idempotent server-side).  Without it, only never-sent strings
        ship (the caller does its own catch-up, e.g. TcpSink)."""
        ts = np.asarray(timestamps, dtype=np.int64)
        if ts.ndim == 0:
            ts = ts.reshape(1)
        out = []
        ordered = []
        new_strings: list = []
        for name, _t in self.cols:
            if name not in columns:
                raise NetClientError(f"missing column {name!r}")
            v = columns[name]
            if name in self.str_cols:
                codes, new = self.strings.encode_column(v)
                new_strings.extend(new)
                ordered.append(codes)
            else:
                ordered.append(np.asarray(v, dtype=self.dtypes[name]))
        if synced is not None:
            delta = self.strings.strings_from(synced)
            if delta:
                out.append(fp.encode_strings(delta, start_code=synced))
        elif new_strings:
            out.append(fp.encode_strings(
                new_strings, start_code=len(self.strings) - len(new_strings)))
        n = int(ts.shape[0])
        if ts.shape[0] == 1 and ordered and ordered[0].shape[0] > 1:
            n = int(ordered[0].shape[0])
            ts = np.full(n, int(ts[0]), dtype=np.int64)
        out.append(fp.encode_data(ts, ordered))
        return b"".join(out)


class FrameClient:
    """Base wire client: HELLO negotiation, credit accounting, batch
    sends, PING/ACK barrier.  Subclasses supply _send/_recv_frame."""

    def __init__(self, app: Optional[str], stream: str, cols: list,
                 credit: bool = True):
        str_cols = {name for name, t in cols if t == "string"}
        self.app = app
        self.stream = stream
        self.enc = _FrameEncoder(stream, cols, str_cols)
        self._synced = 1                # peer has mapped codes < this:
        #                                 advanced only AFTER a send
        #                                 succeeds, so a failed send's
        #                                 lost STRINGS delta is re-shipped
        #                                 by the next batch instead of
        #                                 desyncing the dictionary forever
        self.want_credit = credit
        self.credit = 0                 # frames we may send before blocking
        self.frames_sent = 0
        self.events_sent = 0
        self.bytes_sent = 0
        self._acks: dict = {}
        self._next_token = 1
        # store-query state: results keyed by token; the SERVER's egress
        # string dictionary (RESULT string columns ship as codes, their
        # strings as STRINGS deltas ahead of the RESULT)
        self._results: dict = {}
        self._peer_strings: list = [None]       # code 0 = null

    @classmethod
    def cols_of_schema(cls, schema) -> list:
        return _schema_cols(schema)

    # -- subclass surface ---------------------------------------------------

    def _send(self, data: bytes) -> None:
        raise NotImplementedError

    def _recv_frame(self, timeout: Optional[float]):
        """(ftype, payload) or None on timeout; None-able backchannel
        (ring) returns None always."""
        raise NotImplementedError

    # -- protocol -----------------------------------------------------------

    def hello(self, timeout: float = 5.0) -> None:
        self._synced = 1                # (re-)negotiation resets the
        #                                 server-side remap: re-ship all
        self._send(fp.encode_hello(self.app or "", self.stream,
                                   self.enc.cols, credit=self.want_credit))
        deadline = time.monotonic() + timeout
        while True:
            f = self._recv_frame(max(0.001, deadline - time.monotonic()))
            if f is None:
                # a partial read returns None with time still on the
                # clock (e.g. HELLO_OK split across TCP segments): only
                # the deadline itself fails the negotiation
                if time.monotonic() >= deadline:
                    raise NetClientError("HELLO timed out")
                continue
            ftype, payload = f
            if payload is None:         # CRC-rejected frame: wait on
                continue                # for an intact reply
            if ftype == fp.HELLO_OK:
                self.credit = json.loads(payload).get("credit", 0) or 0
                if not self.want_credit:
                    self.credit = 0
                elif self.credit <= 0:
                    # the server negotiated credit OFF (credit='0'):
                    # waiting for CREDIT frames would deadlock
                    self.want_credit = False
                return
            if ftype == fp.ERROR:
                raise NetClientError(json.loads(payload)["error"])

    def send_batch(self, columns: dict, timestamps,
                   trace_id: Optional[str] = None) -> None:
        """Encode + ship one columnar batch (strings as str arrays —
        dictionary codes are connection-local, never caller-visible).
        `trace_id` stamps a wire TRACE frame ahead of the DATA frame:
        the server adopts it as the frame's trace id (always traced,
        bypassing sampling) — docs/OBSERVABILITY.md "Frame tracing"."""
        blob = self.enc.encode_batch(columns, timestamps,
                                     synced=self._synced)
        if trace_id is not None:
            blob = fp.encode_trace(trace_id) + blob
        self._respect_credit()
        self._send(blob)
        self._synced = len(self.enc.strings)
        self.frames_sent += 1
        self.bytes_sent += len(blob)
        self.events_sent += _batch_rows(columns, timestamps)

    def barrier(self, timeout: float = 30.0) -> None:
        """PING/ACK round trip: returns once everything sent before it
        has been admitted, fed, and flushed server-side."""
        token = self._next_token
        self._next_token += 1
        self._send(fp.encode_ping(token))
        deadline = time.monotonic() + timeout
        while token not in self._acks:
            f = self._recv_frame(max(0.001, deadline - time.monotonic()))
            if f is not None:
                self._on_control(*f)
            elif time.monotonic() >= deadline:
                raise NetClientError("barrier timed out")
        del self._acks[token]

    def query(self, text: str, app: Optional[str] = None,
              timeout: float = 30.0) -> list:
        """Run a SiddhiQL store query server-side; returns
        [(timestamp, row_tuple), ...] exactly as `runtime.query(text)`
        would — byte-identical values, string columns resolved through
        the server's egress dictionary (docs/SERVING.md "Store
        queries").  `app` targets a deployed app by name; omitted, the
        connection's HELLO-bound app serves (a query-only connection —
        `stream=None` — defaults `app` to the constructor's)."""
        token = self._next_token
        self._next_token += 1
        if app is None and self.stream is None:
            app = self.app
        self._send(fp.encode_query(token, text, app=app))
        deadline = time.monotonic() + timeout
        while token not in self._results:
            f = self._recv_frame(max(0.001, deadline - time.monotonic()))
            if f is not None:
                self._on_control(*f)
            elif time.monotonic() >= deadline:
                raise NetClientError("query timed out")
        meta, body = self._results.pop(token)
        if "error" in meta:
            raise NetClientError(str(meta["error"]))
        cols = meta.get("cols", [])
        ts, views = fp.decode_result_body(body, cols)
        strs = self._peer_strings
        str_js = [j for j, c in enumerate(cols) if str(c[1]) == "string"]
        rows = []
        for i in range(int(ts.shape[0])):
            row = [v[i].item() for v in views]
            for j in str_js:
                code = int(views[j][i])
                if code >= len(strs):
                    raise NetClientError(
                        "RESULT string code beyond the shipped dictionary")
                row[j] = strs[code]         # code 0 -> strs[0] is None
            rows.append((int(ts[i]), tuple(row)))
        return rows

    def close(self) -> None:
        try:
            self._send(fp.encode_frame(fp.BYE))
        except Exception:
            pass

    # -- credit accounting --------------------------------------------------

    def _respect_credit(self, timeout: float = 30.0) -> None:
        if not self.want_credit:
            return
        self._drain_control()
        deadline = time.monotonic() + timeout
        while self.credit <= 0:
            f = self._recv_frame(max(0.001, deadline - time.monotonic()))
            if f is not None:
                self._on_control(*f)
            elif time.monotonic() >= deadline:
                raise NetClientError(
                    "no credit from server (backpressured) for "
                    f"{timeout:.0f}s")
        self.credit -= 1

    def _drain_control(self) -> None:
        while True:
            f = self._recv_frame(0.0)
            if f is None:
                return
            self._on_control(*f)

    def _on_control(self, ftype: int, payload) -> None:
        if payload is None:             # CRC-rejected reply frame: skip
            return                      # (the next CREDIT/ACK re-syncs)
        if ftype == fp.CREDIT:
            self.credit += fp.decode_i64(payload)
        elif ftype == fp.ACK:
            self._acks[fp.decode_u64(payload)] = True
        elif ftype == fp.STRINGS:
            # server egress dictionary delta (store-query results)
            start, new = fp.decode_strings(payload)
            if start > len(self._peer_strings):
                raise NetClientError("server STRINGS delta gap")
            self._peer_strings[start:start + len(new)] = new
        elif ftype == fp.RESULT:
            token, meta, body = fp.decode_result(payload)
            self._results[token] = (meta, body)
        elif ftype == fp.ERROR:
            raise NetClientError(json.loads(payload)["error"])


class TcpFrameClient(FrameClient):
    """Raw-TCP frame client.  Receives are buffer-based: a timeout
    mid-frame keeps the partial bytes, so control frames can never
    desync the stream."""

    def __init__(self, host: str, port: int, stream: Optional[str] = None,
                 cols: Optional[list] = None,
                 app: Optional[str] = None, credit: bool = True,
                 connect_timeout: float = 5.0):
        super().__init__(app, stream, cols or [], credit)
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rbuf = bytearray()        # append-in-place: O(1) amortized
        self._fq: list = []
        if stream:                      # stream=None: query-only client,
            self.hello()                # no ingest negotiation at all

    def _send(self, data: bytes) -> None:
        self.sock.sendall(data)

    def _recv_frame(self, timeout: Optional[float]):
        if self._fq:
            return self._fq.pop(0)
        self.sock.settimeout(
            timeout if timeout is None or timeout > 0 else 0.000001)
        try:
            b = self.sock.recv(1 << 16)
            if not b:
                raise EOFError("connection closed")
            self._rbuf += b
            self._fq.extend(fp.parse_buffer_inplace(self._rbuf))
        except (socket.timeout, BlockingIOError):
            pass
        finally:
            self.sock.settimeout(None)
        return self._fq.pop(0) if self._fq else None

    def close(self) -> None:
        super().close()
        try:
            self.sock.close()
        except OSError:
            pass


class WsFrameClient(FrameClient):
    """WebSocket frame client (RFC-6455 client side, binary messages).
    Connects to the same NetServer port — the server sniffs the
    upgrade."""

    def __init__(self, host: str, port: int, stream: Optional[str] = None,
                 cols: Optional[list] = None,
                 app: Optional[str] = None, credit: bool = True,
                 connect_timeout: float = 5.0):
        super().__init__(app, stream, cols or [], credit)
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = bytearray()
        self._wsq: list = []            # frames beyond the first per message
        self._handshake(host, port)
        if stream:                      # stream=None: query-only client
            self.hello()

    def _handshake(self, host: str, port: int) -> None:
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            (f"GET /siddhi/data HTTP/1.1\r\nHost: {host}:{port}\r\n"
             f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\n"
             f"Sec-WebSocket-Version: 13\r\n\r\n").encode())
        hdr = b""
        while b"\r\n\r\n" not in hdr:
            b = self.sock.recv(4096)
            if not b:
                raise NetClientError("websocket handshake failed (EOF)")
            hdr += b
        head, _, rest = hdr.partition(b"\r\n\r\n")
        status = head.split(b"\r\n", 1)[0]
        if b" 101 " not in status:
            raise NetClientError("websocket handshake rejected: "
                                 + status.decode("latin1"))
        self._buf = bytearray(rest)

    # ws client frames MUST be masked
    def _send(self, data: bytes) -> None:
        mask = os.urandom(4)
        n = len(data)
        if n < 126:
            hdr = bytes([0x82, 0x80 | n])
        elif n < (1 << 16):
            hdr = bytes([0x82, 0x80 | 126]) + struct.pack(">H", n)
        else:
            hdr = bytes([0x82, 0x80 | 127]) + struct.pack(">Q", n)
        arr = np.frombuffer(data, dtype=np.uint8)
        m = np.frombuffer((mask * ((n + 3) // 4))[:n], dtype=np.uint8)
        self.sock.sendall(hdr + mask + (arr ^ m).tobytes())

    def _recv_frame(self, timeout: Optional[float]):
        """Read one ws message, parse the protocol frame(s) inside.
        Buffer-based: a timeout mid-message keeps the partial bytes.
        One message may carry several frames (the server batches a
        STRINGS delta with its RESULT in one write) — extras queue."""
        if self._wsq:
            return self._wsq.pop(0)
        while True:
            got = fp.parse_ws_frame_inplace(self._buf)
            if got is None:
                self.sock.settimeout(
                    timeout if timeout is None or timeout > 0 else 0.000001)
                try:
                    b = self.sock.recv(1 << 16)
                    if not b:
                        raise EOFError("websocket closed")
                    self._buf += b
                except (socket.timeout, BlockingIOError):
                    return None
                finally:
                    self.sock.settimeout(None)
                continue
            opcode, body = got
            if opcode == 0x8:
                raise EOFError("websocket closed")
            if opcode in (0x9, 0xA):        # ping/pong: ignore
                continue
            frames, rest = fp.parse_buffer(body)
            if rest or not frames:
                raise fp.FrameError(
                    "ws message is not a whole number of frames")
            self._wsq.extend(frames[1:])
            return frames[0]

    def close(self) -> None:
        super().close()
        try:
            self.sock.close()
        except OSError:
            pass


class RingProducer(FrameClient):
    """Shared-memory producer: same frames, no backchannel — the ring's
    occupancy IS the backpressure (push blocks when full), and
    `barrier()` waits for the consumer to drain the ring."""

    def __init__(self, ring_name: str, stream: str, cols: list,
                 app: Optional[str] = None, push_timeout: float = 30.0):
        super().__init__(app, stream, cols, credit=False)
        self.ring = ShmRing.attach(ring_name)
        self.push_timeout = push_timeout
        self._send(fp.encode_hello(app or "", stream, cols, credit=False))

    def _send(self, data: bytes) -> None:
        if not self.ring.push(data, timeout=self.push_timeout):
            raise NetClientError(
                f"ring {self.ring.name!r} full for "
                f"{self.push_timeout:.0f}s (slow consumer)")

    def _recv_frame(self, timeout):
        return None

    def send_batch(self, columns: dict, timestamps,
                   trace_id: Optional[str] = None) -> None:
        if trace_id is not None:        # own slot: rings carry whole frames
            self._send(fp.encode_trace(trace_id))
        blob = self.enc.encode_batch(columns, timestamps,
                                     synced=self._synced)
        if len(blob) > self.ring.capacity:
            # split: a batch larger than one slot ships as several
            # frames.  The oversize blob already registered this batch's
            # new strings in the encoder, so its STRINGS delta MUST ship
            # first (the re-encoded row-range parts won't re-declare
            # them) — each delta frame rides its own slot.
            self._send_split(blob, columns, timestamps)
            return
        self._send(blob)
        self._synced = len(self.enc.strings)
        self.frames_sent += 1
        self.bytes_sent += len(blob)
        self.events_sent += _batch_rows(columns, timestamps)

    def _send_split(self, blob: bytes, columns: dict, timestamps) -> None:
        for ftype, payload in fp.parse_buffer(blob)[0]:
            if ftype != fp.STRINGS:
                continue
            delta = fp.encode_frame(ftype, payload)
            if len(delta) > self.ring.capacity:
                raise NetClientError(
                    f"STRINGS delta ({len(delta)} bytes) exceeds ring "
                    f"slot capacity {self.ring.capacity}; raise slot.size")
            self._send(delta)
            self.bytes_sent += len(delta)
        self._synced = len(self.enc.strings)    # deltas are in the ring
        ts = np.asarray(timestamps, dtype=np.int64)
        n = int(ts.shape[0])
        row_bytes = max(1, sum(np.asarray(v).dtype.itemsize if
                               np.asarray(v).dtype.kind != "U" else 4
                               for v in columns.values()) + 8)
        per = max(1, (self.ring.capacity - 1024) // row_bytes)
        for lo in range(0, n, per):
            hi = min(n, lo + per)
            part = {k: np.asarray(v)[lo:hi] for k, v in columns.items()}
            # the delta already shipped: these re-encodes are DATA-only
            part_blob = self.enc.encode_batch(part, ts[lo:hi])
            self._send(part_blob)
            self.frames_sent += 1
            self.bytes_sent += len(part_blob)
            self.events_sent += hi - lo

    def barrier(self, timeout: float = 30.0) -> None:
        if not self.ring.join(timeout=timeout):
            raise NetClientError("ring drain barrier timed out")

    def close(self) -> None:
        super().close()
        self.ring.close()


# ---------------------------------------------------------------------------
# egress receiver (sink counterpart; tests + bench)
# ---------------------------------------------------------------------------

class FrameReceiver:
    """Tiny frame-protocol receiver: accepts connections, answers
    HELLO/PING, decodes STRINGS + DATA frames into (stream, rows)
    batches.  The consuming end of `@sink(type='tcp')`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 fail_first: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.host, self.port = self._sock.getsockname()[:2]
        self.batches: list = []         # (stream, [(ts, row), ...])
        self.frames = 0
        self.strings_frames = 0         # dictionary deltas received
        # trace-context extension: one entry per DATA frame — the
        # trace id its preceding TRACE frame carried, or None.  Tests
        # pin "the egress frame carries the ingress trace id" here.
        self.trace_ids: list = []
        self._fail_first = fail_first   # refuse N connections (tests)
        self._stop = threading.Event()
        self._threads: list = []
        self._lock = new_lock("FrameReceiver._lock")
        self._accept = threading.Thread(target=self._accept_loop,
                                        name="siddhi-frame-receiver",
                                        daemon=True)
        self._accept.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._sock.accept()
            except OSError:
                return
            if self._fail_first > 0:
                # lint: unlocked-ok (test-harness fault knob, one writer)
                self._fail_first -= 1
                sock.close()
                continue
            t = threading.Thread(target=self._serve, args=(sock,),
                                 name="siddhi-frame-receiver-conn",
                                 daemon=True)
            with self._lock:    # stop() snapshots the join list
                self._threads.append(t)
            t.start()

    def _serve(self, sock: socket.socket) -> None:
        from types import SimpleNamespace
        from ..core.batch import rows_of_columns
        from ..core.schema import StreamSchema
        from ..query.ast import Attribute, AttrType
        read = fp.reader_for(sock)
        strings = [None]                # connection dictionary
        schema = None                   # decode via fp.decode_data —
        stream_id = ""                  # ONE wire-walk implementation
        next_trace = None               # TRACE ctx for the next DATA
        try:
            while not self._stop.is_set():
                ftype, payload = fp.read_frame(read)
                if ftype == fp.HELLO:
                    h = fp.decode_hello(payload)
                    stream_id = h["stream"]
                    schema = StreamSchema(stream_id, tuple(
                        Attribute(str(c[0]), AttrType[str(c[1]).upper()])
                        for c in h["cols"]))
                    sock.sendall(fp.encode_hello_ok(0))
                elif ftype == fp.STRINGS:
                    start, new = fp.decode_strings(payload)
                    if start > len(strings):
                        raise fp.FrameError("STRINGS delta gap")
                    strings[start:start + len(new)] = new
                    with self._lock:
                        self.strings_frames += 1
                elif ftype == fp.TRACE:
                    next_trace = fp.decode_trace(payload)
                elif ftype == fp.DATA:
                    if schema is None:
                        raise fp.FrameError("DATA before HELLO")
                    ts, cols = fp.decode_data(payload, schema)
                    rows = rows_of_columns(
                        schema, ts, cols, SimpleNamespace(_to_str=strings))
                    tid, next_trace = next_trace, None
                    with self._lock:
                        self.frames += 1
                        self.batches.append((stream_id, rows))
                        self.trace_ids.append(
                            None if tid is None else tid[0])
                elif ftype == fp.PING:
                    sock.sendall(fp.encode_ack(fp.decode_u64(payload)))
                elif ftype == fp.BYE:
                    return
        except (EOFError, ConnectionError, OSError, fp.FrameError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def rows(self, stream: Optional[str] = None) -> list:
        with self._lock:
            return [r for sid, rows in self.batches
                    for r in rows if stream is None or sid == stream]

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=2)
