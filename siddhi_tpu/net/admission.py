"""Admission control for the serving plane.

Per-stream token-bucket rate limits plus byte watermarks, declared on
net sources (`@source(type='tcp', rate.limit='50000',
shed.policy='shed', max.pending='4 MB')`) and consulted by every
transport that feeds the stream (TCP/WS connections, the shm ring,
and the service front door share ONE controller per stream, so the
limit is global, not per-connection).

Three shed policies once the bucket is empty:

    block  - the caller waits (`decision.wait_s`); a TCP reader thread
             that waits stops draining its socket, which is kernel-level
             backpressure all the way to the producer, and the server
             withholds CREDIT frames.
    shed   - the NEW frame is dropped into the runtime's ErrorStore
             (decoded to replayable events — zero unaccounted loss;
             `rt.error_store.replay(rt)` re-ingests once load clears).
    oldest - the new frame parks in a bounded pending queue; when the
             queue's byte watermark overflows, the OLDEST pending frame
             sheds to the ErrorStore (freshest-data-wins, the classic
             ticker-plant policy).  `pump()` drains pending frames as
             tokens refill.

The PR-5 SLO controller lowers admission BEFORE latency collapses via
`set_rate_factor` (autotune.SLOController.admission_factor): p99 over
target scales every bucket's refill rate down, recovery raises it back
to 1.0.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils.locks import new_lock

ADMIT = "admit"
SHED = "shed"
WAIT = "wait"
QUEUED = "queued"


class TokenBucket:
    """Classic token bucket in event units.  `rate` tokens/s refill up
    to `burst`; `None` rate = unlimited.  A monotonic-clock callable
    makes tests deterministic."""

    def __init__(self, rate: Optional[float], burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        # `rate=0` means ADMIT NOTHING (a declared quarantine: every
        # frame sheds/blocks, accounted) — only None means unlimited
        self.rate = float(rate) if rate is not None else None
        self.burst = float(burst) if burst is not None else \
            (self.rate if self.rate else 0.0)
        self.factor = 1.0               # SLO admission factor (0 < f <= 1)
        self._tokens = self.burst
        self._clock = clock
        self._t = clock()

    @property
    def effective_rate(self) -> Optional[float]:
        return None if self.rate is None else self.rate * self.factor

    def _refill(self) -> None:
        now = self._clock()
        dt = now - self._t
        self._t = now
        if self.rate is not None and dt > 0:
            self._tokens = min(self.burst, self._tokens
                               + dt * self.rate * self.factor)

    def try_take(self, n: float) -> float:
        """Take `n` tokens if available; returns 0.0 on success, else
        the estimated seconds until `n` tokens exist (never takes a
        partial amount)."""
        if self.rate is None:
            return 0.0
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return 0.0
        rate = max(self.rate * self.factor, 1e-9)
        return (n - self._tokens) / rate

    def set_factor(self, f: float) -> None:
        self._refill()                  # settle at the old rate first
        self.factor = min(1.0, max(0.01, float(f)))


@dataclass
class Work:
    """One admitted-or-pending unit: a decoded frame ready to feed.
    `feed` ingests it (already bound to runtime + stream); `rows`
    lazily decodes to [(ts_ms, row_tuple), ...] for ErrorStore
    capture on shed.  `trace` is the frame's TraceHandle
    (core/tracing.py) — it rides the park queue, so a frame drained
    and fed on ANOTHER thread (scheduler pump, a later connection
    tick) still lands its spans on the same tree."""
    n: int
    nbytes: int
    feed: Callable[[], None]
    rows: Callable[[], list]
    stream_id: str = ""
    trace: object = None


@dataclass
class Decision:
    action: str                         # ADMIT | SHED | WAIT | QUEUED
    wait_s: float = 0.0
    ready: list = field(default_factory=list)   # pending work now admitted


def parse_bytes(text) -> int:
    """'4 MB' / '512 KB' / '65536' -> bytes."""
    if text is None:
        return 0
    s = str(text).strip().lower()
    for suffix, mult in (("gb", 1 << 30), ("mb", 1 << 20), ("kb", 1 << 10),
                         ("g", 1 << 30), ("m", 1 << 20), ("k", 1 << 10),
                         ("b", 1)):
        if s.endswith(suffix):
            return int(float(s[:-len(suffix)].strip()) * mult)
    return int(float(s))


class AdmissionController:
    """Per-stream admission: rate limit + shed policy + pending-byte
    watermark.  Thread-safe — every transport feeding the stream shares
    one instance (registered in `rt.admission[stream_id]`)."""

    POLICIES = ("block", "shed", "oldest")

    def __init__(self, stream_id: str, rate_limit: Optional[float] = None,
                 policy: str = "block", max_pending_bytes: int = 4 << 20,
                 burst: Optional[float] = None, error_store=None,
                 on_fault: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic,
                 now_ms: Optional[Callable[[], int]] = None,
                 on_shed: Optional[Callable[[str, str], None]] = None):
        policy = (policy or "block").lower()
        if policy not in self.POLICIES:
            raise ValueError(f"stream {stream_id!r}: unknown shed.policy "
                             f"{policy!r} (have: block | shed | oldest)")
        self.stream_id = stream_id
        self.policy = policy
        self.bucket = TokenBucket(rate_limit, burst, clock)
        self.max_pending_bytes = int(max_pending_bytes)
        self.error_store = error_store
        self.on_fault = on_fault        # stats.on_fault hook
        # shed-burst trace trigger (core/tracing.py): nonblocking
        # enqueue, safe under this controller's lock; the tracer's
        # per-kind cooldown turns a shed storm into at most one dump.
        # Named after its target (FrameTracer.trigger) like wal's
        # injected `inject`, so the static lock graph composes the
        # AdmissionController._lock -> FrameTracer._lock edge the
        # runtime lock-witness observes
        self.trigger = on_shed
        self.now_ms = now_ms or (lambda: int(time.time() * 1000))
        self._pending: deque = deque()  # Work, oldest first
        self._inflight = 0              # drained-but-not-yet-fed frames
        self._lock = new_lock("AdmissionController._lock")
        # gauges/counters (statistics()["net"] + Prometheus)
        self.frames_in = 0
        self.events_in = 0
        self.bytes_in = 0
        self.admitted_events = 0
        self.shed_frames = 0
        self.shed_events = 0
        self.blocked_s = 0.0
        self.pending_bytes = 0

    # -- core ---------------------------------------------------------------

    def offer(self, work: Work) -> Decision:
        """Admit, queue, shed, or ask the caller to wait.  Admitted
        pending work (oldest policy) rides `Decision.ready` — the caller
        feeds those IN ORDER before `work` itself."""
        return self._decide(work, count=True)

    def submit(self, work: Work, stop: Optional[Callable[[], bool]] = None,
               sleep: Callable[[float], None] = time.sleep) -> Decision:
        """offer() plus the block-policy wait loop: under 'block' this
        call sleeps (in <=50 ms parks, so `stop` — shutdown — stays
        responsive) until tokens refill, which is what stalls a TCP
        reader thread and turns into kernel backpressure.  If `stop`
        fires first the frame sheds to the ErrorStore (accounted, never
        silently dropped)."""
        d = self._decide(work, count=True)
        while d.action == WAIT:
            if stop is not None and stop():
                with self._lock:
                    self._shed_locked(work, "transport stopping")
                return Decision(SHED, ready=d.ready)
            t0 = time.monotonic()
            sleep(min(d.wait_s, 0.05))
            with self._lock:
                self.blocked_s += time.monotonic() - t0
            nxt = self._decide(work, count=False)
            nxt.ready = d.ready + nxt.ready
            d = nxt
        return d

    def _decide(self, work: Work, count: bool) -> Decision:
        with self._lock:
            if count:
                self.frames_in += 1
                self.events_in += work.n
                self.bytes_in += work.nbytes
            # a frame with more events than the bucket can EVER hold
            # would wait forever under 'block' and jam the queue head
            # under 'oldest': shed it loudly (accounted + replayable —
            # replay re-enters via row ingest, which is not bucketed)
            if count and self.bucket.rate is not None \
                    and work.n > self.bucket.burst:
                self._shed_locked(
                    work, f"frame of {work.n} events exceeds the bucket "
                          f"burst ({self.bucket.burst:.0f}); split the "
                          f"batch or raise burst")
                return Decision(SHED, ready=self._drain_locked())
            ready = self._drain_locked()
            if self._pending or self._inflight:
                # order preserved: new work can never jump queued work,
                # including drained frames another thread is still
                # feeding outside this lock (admitting around those
                # would reorder one producer's frames)
                return self._enqueue_locked(work, ready)
            wait = self.bucket.try_take(work.n)
            if wait <= 0.0:
                self.admitted_events += work.n
                return Decision(ADMIT, ready=ready)
            if self.policy == "shed":
                self._shed_locked(work, "rate limit exceeded")
                return Decision(SHED, ready=ready)
            if self.policy == "oldest":
                return self._enqueue_locked(work, ready)
            return Decision(WAIT, wait_s=wait, ready=ready)

    def pump(self) -> list:
        """Admit pending work whose tokens have refilled (oldest
        policy); returns the Work list to feed, in order."""
        with self._lock:
            return self._drain_locked()

    def pending_count(self) -> int:
        """Frames admitted-but-not-yet-fed: parked in the 'oldest'
        queue or drained and still feeding on another thread.  The
        durable-ACK barrier waits on this — an ACK must never cover a
        frame that exists only in memory."""
        with self._lock:
            return len(self._pending) + self._inflight

    def feed_safely(self, work: Work) -> None:
        """Feed one admitted unit, capturing a failure into the
        ErrorStore — admitted work must never vanish.  (The server's
        own Work.feed closures self-capture; this guards feeds whose
        closure does not, e.g. queued REST batches drained by the
        runtime scheduler pump.)"""
        try:
            work.feed()
        except Exception as e:
            if self.error_store is None:
                raise
            if not getattr(e, "_wal_captured", False):
                # (a WAL append failure already captured the frame —
                # a second entry would double-ingest on replay)
                try:
                    rows = work.rows()
                except Exception:
                    rows = []
                self.error_store.add(
                    work.stream_id or self.stream_id, "net.feed", e,
                    self.now_ms(), events=rows)
            if self.on_fault is not None:
                try:
                    self.on_fault(self.stream_id, "net.feed")
                except Exception:
                    pass

    def flush_pending_to_store(self, reason: str = "source stopped") -> int:
        """Teardown: every still-pending frame sheds to the ErrorStore
        so nothing admitted-but-unfed is silently lost."""
        with self._lock:
            n = 0
            while self._pending:
                self._shed_locked(self._pending.popleft(), reason,
                                  from_pending=True)
                n += 1
            self.pending_bytes = 0
            return n

    def _drain_locked(self) -> list:
        if self._inflight:
            # strict FIFO: a previous drain's frames are still being
            # fed on another thread — handing out more now could feed
            # them out of order
            return []
        out = []
        while self._pending:
            head = self._pending[0]
            if self.bucket.try_take(head.n) > 0.0:
                break
            self._pending.popleft()
            self.pending_bytes -= head.nbytes
            self.admitted_events += head.n
            out.append(self._tracked(head))
        self._inflight = len(out)
        return out

    def _tracked(self, work: Work) -> Work:
        """Wrap a drained frame's feed so the in-flight count drops when
        it lands — every consumer (connection threads, the scheduler
        pump, REST handlers) feeds via `Work.feed`, so no call-site
        changes are needed."""
        inner = work.feed

        def feed():
            try:
                inner()
            finally:
                with self._lock:
                    self._inflight -= 1

        return Work(n=work.n, nbytes=work.nbytes, feed=feed,
                    rows=work.rows, stream_id=work.stream_id,
                    trace=work.trace)

    def _enqueue_locked(self, work: Work, ready: list) -> Decision:
        self._pending.append(work)
        self.pending_bytes += work.nbytes
        while self.pending_bytes > self.max_pending_bytes \
                and len(self._pending) > 1:
            oldest = self._pending.popleft()
            self.pending_bytes -= oldest.nbytes
            self._shed_locked(oldest, "pending watermark overflow",
                              from_pending=True)
        if self._pending and self.pending_bytes > self.max_pending_bytes:
            # a single frame larger than the watermark: shed it outright
            lone = self._pending.popleft()
            self.pending_bytes -= lone.nbytes
            self._shed_locked(lone, "frame exceeds pending watermark",
                              from_pending=True)
            if lone is work:
                # the just-offered frame itself was shed — telling the
                # caller QUEUED would promise a feed that never comes
                # (REST maps QUEUED to 202 "queued")
                return Decision(SHED, ready=ready)
        return Decision(QUEUED, ready=ready)

    def _shed_locked(self, work: Work, why: str,
                     from_pending: bool = False) -> None:
        self.shed_frames += 1
        self.shed_events += work.n
        if self.trigger is not None:
            try:
                self.trigger("shed_burst",
                             f"stream {self.stream_id!r}: {why} "
                             f"({self.shed_frames} frames shed)")
            except Exception:
                pass
        if self.on_fault is not None:
            try:
                self.on_fault(self.stream_id, "net.shed")
            except Exception:
                pass
        if self.error_store is not None:
            try:
                rows = work.rows()
            except Exception as e:      # decode failed: account anyway
                rows = []
                why = f"{why}; row decode failed: {e}"
            self.error_store.add(
                work.stream_id or self.stream_id, "net.shed",
                f"admission shed ({self.policy}): {why}",
                self.now_ms(), events=rows)

    # -- SLO hook -----------------------------------------------------------

    def set_rate_factor(self, f: float) -> None:
        """PR-5 SLO controller hook: scale the admitted rate (0..1] so
        overload lowers admission BEFORE engine p99 collapses.  Locked:
        set_factor refills the bucket, which races try_take's own
        read-modify-write on connection threads."""
        with self._lock:
            self.bucket.set_factor(f)

    # -- telemetry ----------------------------------------------------------

    def metrics(self) -> dict:
        with self._lock:
            m = {"policy": self.policy,
                 "frames_in": self.frames_in,
                 "events_in": self.events_in,
                 "bytes_in": self.bytes_in,
                 "admitted_events": self.admitted_events,
                 "shed_frames": self.shed_frames,
                 "shed_events": self.shed_events,
                 "pending_frames": len(self._pending),
                 "pending_bytes": self.pending_bytes,
                 "blocked_seconds": round(self.blocked_s, 6),
                 "rate_factor": self.bucket.factor}
            if self.bucket.rate is not None:
                m["rate_limit_eps"] = self.bucket.rate
            return m


def controller_from_options(stream_id: str, options: dict, rt,
                            clock=time.monotonic) -> AdmissionController:
    """Build a controller from @source annotation options
    (`rate.limit`, `shed.policy`, `max.pending`, `burst`)."""
    rate = options.get("rate.limit")
    tracer = getattr(rt, "tracing", None)
    return AdmissionController(
        stream_id,
        rate_limit=float(rate) if rate is not None else None,
        policy=options.get("shed.policy", "block"),
        max_pending_bytes=parse_bytes(options.get("max.pending")) or (4 << 20),
        burst=float(options["burst"]) if options.get("burst") else None,
        error_store=rt.error_store,
        on_fault=rt.stats.on_fault,
        clock=clock,
        now_ms=rt.now_ms,
        on_shed=None if tracer is None else tracer.trigger)
