"""Network sinks — `@sink(type='tcp'|'ws', host=..., port=..., ...)`.

Batched columnar egress: each emitted event batch encodes to ONE
DATA frame (plus any string-table delta), shipped over the same frame
protocol the ingest plane speaks — so a `@sink(type='tcp')` on one
engine can feed a `@source(type='tcp')` on another byte-identically,
and `net/client.py FrameReceiver` is the generic consuming end.

Fault tolerance rides the PR-4 machinery unchanged: `on.error`,
`max.retries`, `retry.interval`, `breaker.threshold`, ... arm the
same BackoffPolicy + CircuitBreaker guarded publish as every other
sink; a publish failure marks the connection dirty and the next
attempt reconnects and replays the FULL string table before data, so
retried frames always decode (the dictionary is connection state).

Payloads handed to the retry path are self-contained `bytes` (delta +
DATA frames concatenated), so an ErrorStore capture/replay round trip
re-publishes the exact wire bytes.
"""
from __future__ import annotations

import socket
from typing import Optional

import numpy as np

from ..core.io import Sink, register_sink_type
from ..utils.locks import new_lock
from ..core.planner import PlanError
from . import frame as fp
from .client import NetClientError, WsFrameClient, _FrameEncoder

# a dead peer can surface as refused/reset (OSError), as a clean EOF
# mid-handshake (EOFError from the frame reader), or as garbage bytes
# where the HELLO_OK should be (FrameError); the ws client wraps its
# handshake/HELLO rejections in NetClientError — all mean reconnect
_CONN_ERRORS = (OSError, ConnectionError, EOFError, NetClientError,
                fp.FrameError)


class _SinkPayload(bytes):
    """A sink payload blob plus the code range its embedded STRINGS
    delta covers [start_code, end_code) — so publish() can tell when
    the payload itself carries the peer forward and skip the catch-up
    delta that would otherwise re-ship every dictionary delta twice.
    Degrades safely: anything that strips the attributes (they do not
    survive pickling) just falls back to catch-up duplication, which
    the server-side remap accepts idempotently.

    `trace_ctx` is the originating frame's resumable (trace_id, head)
    (core/tracing.py): a stored payload replayed from the ErrorStore
    records its publish span on the SAME tree, and the blob itself
    already embeds the wire TRACE frame re-stamping the egress DATA."""
    start_code: Optional[int] = None
    end_code: Optional[int] = None
    trace_ctx: Optional[tuple] = None


class TcpSink(Sink):
    """Columnar frame egress over TCP."""

    transport = "tcp"

    def __init__(self, rt, stream_id, options, mapper):
        super().__init__(rt, stream_id, options, mapper)
        if not options.get("port"):
            raise PlanError(f"sink on {stream_id!r}: "
                            f"@sink(type='{self.transport}') needs a port")
        self.host = options.get("host", "127.0.0.1")
        self.tcp_port = int(options["port"])
        self.sock: Optional[socket.socket] = None
        self.frames_out = 0
        self.bytes_out = 0
        self.reconnects = 0
        schema = rt.schemas[stream_id]
        self._cols = [(a.name, a.type.name.lower())
                      for a in schema.attributes]
        self._schema = schema
        from ..query.ast import AttrType
        str_cols = {a.name for a in schema.attributes
                    if a.type == AttrType.STRING}
        # ONE encoder for the sink's lifetime: payload blobs reference a
        # monotone dictionary; _open replays the full table on every
        # (re)connect and publish() sends a catch-up delta whenever the
        # peer is behind (a shed payload took its STRINGS delta with it)
        # — so queued/ErrorStore payloads always decode
        self.enc = _FrameEncoder(stream_id, self._cols, str_cols)
        self._peer_codes = 1            # peer has mapped codes < this
        self._io_lock = new_lock("TcpSink._io_lock")

    # -- connection management ---------------------------------------------

    def connect(self) -> None:
        # under _io_lock: connect() can race a publish — a replay of
        # stored payloads, or the scheduler flushing the sink outbox,
        # may already be reconnecting on another thread, and _open's
        # negotiation plus the _peer_codes bookkeeping must not
        # interleave (surfaced by the SL03 lockset self-analysis)
        try:
            with self._io_lock:
                self._open_locked()
        except _CONN_ERRORS as e:
            if self.on_error is None:
                raise               # fail-fast sinks surface at start()
            # armed sinks defer: publish() reconnects per attempt, the
            # retry/breaker machinery owns the failure from here
            import warnings
            warnings.warn(
                f"sink on {self.stream_id!r}: peer "
                f"{self.host}:{self.tcp_port} unavailable at start ({e}); "
                f"deferring to per-publish retry", RuntimeWarning)
            with self._io_lock:
                try:
                    if self.sock is not None:
                        self.sock.close()
                except OSError:
                    pass
                self.sock = None

    def _open_locked(self) -> None:
        # blocking connect/negotiate under _io_lock is the sink's design:
        # the lock serializes ALL wire traffic, and a publisher blocked
        # behind a reconnect is exactly the retry/breaker back-off path
        # lint: allow (reconnect-under-io-lock serializes the wire by design)
        self.sock = socket.create_connection((self.host, self.tcp_port),
                                             timeout=5.0)
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._wire_send_locked(fp.encode_hello(self.rt.app.name,
                                            self.stream_id,
                                            self._cols, credit=False))
            ftype, payload = fp.read_frame(fp.reader_for(self.sock))
            if ftype == fp.ERROR:
                import json
                raise ConnectionError(json.loads(payload)["error"])
            if ftype != fp.HELLO_OK:
                raise ConnectionError(
                    f"expected HELLO_OK, got {fp.type_name(ftype)}")
            table = self.enc.strings.all_strings()
            if table:                   # dictionary replay (reconnect)
                self._wire_send_locked(fp.encode_strings(table, start_code=1))
        except BaseException:
            # a half-negotiated socket must not survive: publish() only
            # reconnects when self.sock is None, so leaving it set would
            # ship frames on a connection that never completed HELLO
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
            raise
        self._peer_codes = len(self.enc.strings)
        self.reconnects += 1

    def _wire_send_locked(self, data: bytes) -> None:
        # the socket IS the resource _io_lock serializes: frames must
        # not interleave, and a slow peer backpressures this sink's
        # publisher only (the retry machinery owns longer stalls)
        # lint: allow (wire writes must serialize under _io_lock by design)
        self.sock.sendall(data)

    def disconnect(self) -> None:
        # under _io_lock: a teardown racing an in-flight publish used to
        # interleave the BYE with a half-written DATA frame and null the
        # socket under the publisher's feet
        with self._io_lock:
            if self.sock is not None:
                try:
                    self._wire_send_locked(fp.encode_frame(fp.BYE))
                except OSError:
                    pass
                try:
                    self.sock.close()
                except OSError:
                    pass
                self.sock = None

    # -- egress -------------------------------------------------------------

    def on_events(self, events: list) -> None:
        if self.handler is not None:
            events = self.handler.on_events(events)
            if not events:
                return
        payload = self._encode_events(events)
        if self.on_error is None:       # legacy fail-fast path
            self.publish_attempt(payload)
            with self._io_lock:         # metrics scrapes read cross-thread
                self.published += 1
            return
        self._publish_guarded(payload)

    def _encode_events(self, events: list) -> bytes:
        """Events -> one self-contained frame blob (delta + DATA).
        Columnarizes ONCE per batch — no per-event wire work."""
        with self._io_lock:
            return self._encode_events_locked(events)

    def _encode_events_locked(self, events: list) -> bytes:
        n = len(events)
        ts = np.fromiter((e.timestamp for e in events), dtype=np.int64,
                         count=n)
        cols = {}
        for i, (name, tname) in enumerate(self._cols):
            vals = [e.data[i] for e in events]
            if tname == "string":
                cols[name] = np.asarray(
                    ["" if v is None else str(v) for v in vals])
            else:
                from ..core.schema import dtype_of
                dt = dtype_of(self._schema.types[name])
                fill = 0 if np.dtype(dt).kind in "iub" else np.nan
                cols[name] = np.asarray(
                    [fill if v is None else v for v in vals], dtype=dt)
        start = len(self.enc.strings)
        blob = self.enc.encode_batch(cols, ts)
        # wire trace-context re-stamp: the egress DATA frame carries the
        # INGRESS frame's trace id (the batch callback staged us under
        # its scope), so traces compose across engine hops — the
        # downstream engine adopts the id for its own span tree
        h = self.rt.current_trace()
        if h is not None:
            blob = fp.encode_trace(h.trace_id, h.head) + blob
        payload = _SinkPayload(blob)
        payload.start_code = start
        payload.end_code = len(self.enc.strings)
        if h is not None:
            payload.trace_ctx = h.ctx()
        return payload

    def publish(self, payload) -> None:
        with self._io_lock:
            if self.sock is None:       # reconnect + full dictionary replay
                self._open_locked()
            try:
                start = getattr(payload, "start_code", None)
                behind = len(self.enc.strings) - self._peer_codes
                if behind > 0 and (start is None
                                   or self._peer_codes < start):
                    # a shed/stored payload took its STRINGS delta down
                    # with it: catch the peer up before anything newer.
                    # Skipped when THIS payload's embedded delta already
                    # starts at (or before) the peer's mark — otherwise
                    # every dictionary delta would ship twice
                    self._wire_send_locked(fp.encode_strings(
                        self.enc.strings.strings_from(self._peer_codes),
                        start_code=self._peer_codes))
                    self._peer_codes = len(self.enc.strings)
                self._wire_send_locked(payload)
                end = getattr(payload, "end_code", None)
                if end is not None and end > self._peer_codes:
                    # the embedded delta advanced the peer too
                    self._peer_codes = end
                self.frames_out += 1
                self.bytes_out += len(payload)
            except _CONN_ERRORS:
                # dirty connection: the next attempt reconnects fresh
                try:
                    self.sock.close()
                except (OSError, AttributeError):
                    pass
                self.sock = None
                raise

    def metrics(self) -> dict:
        m = super().metrics()
        m.update({"frames_out": self.frames_out,
                  "bytes_out": self.bytes_out,
                  "transport": self.transport})
        return m


class WsSink(TcpSink):
    """Columnar frame egress over a WebSocket connection (the peer is
    a NetServer, which sniffs the upgrade on its one port)."""

    transport = "ws"

    def _open_locked(self) -> None:
        self._ws = WsFrameClient(self.host, self.tcp_port, self.stream_id,
                                 self._cols, app=self.rt.app.name,
                                 credit=False)
        self.sock = self._ws.sock
        try:
            table = self.enc.strings.all_strings()
            if table:
                self._wire_send_locked(fp.encode_strings(table, start_code=1))
        except BaseException:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
            raise
        self._peer_codes = len(self.enc.strings)
        self.reconnects += 1

    def _wire_send_locked(self, data: bytes) -> None:
        # each protocol frame rides its own ws message; a blob may hold
        # STRINGS + DATA — split on frame boundaries
        frames, rest = fp.parse_buffer(data)
        if rest:
            raise fp.FrameError("sink payload is not whole frames")
        for ftype, payload in frames:
            self._ws._send(fp.encode_frame(ftype, payload))


def register() -> None:
    from ..extension import Example, ExtensionMeta
    register_sink_type("tcp", TcpSink, meta=ExtensionMeta(
        name="tcp", namespace="sink",
        description="batched columnar frame egress over TCP "
                    "(docs/SERVING.md); rides the sink retry/breaker "
                    "machinery",
        examples=(Example(
            "@sink(type='tcp', host='10.0.0.2', port='8008', "
            "on.error='store') define stream Out (sym string, p double);",
            "one DATA frame per emitted batch; exhausted retries "
            "capture the frame for replay"),)))
    register_sink_type("ws", WsSink, meta=ExtensionMeta(
        name="ws", namespace="sink",
        description="batched columnar frame egress over WebSocket",
        examples=(Example(
            "@sink(type='ws', host='10.0.0.2', port='8008') "
            "define stream Out (sym string, p double);",
            "same frames as the tcp sink, wrapped in ws binary "
            "messages"),)))
