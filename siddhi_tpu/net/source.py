"""Network sources — `@source(type='tcp'|'ws'|'shm', ...)`.

    @source(type='tcp', port='0', rate.limit='50000',
            shed.policy='shed', max.pending='4 MB', credit='64',
            @map(type='passThrough'))
    define stream StockStream (symbol string, price double, volume int);

`tcp` starts a NetServer on `port` (0 = ephemeral; the bound port is
`source.port`) accepting BOTH raw-TCP frame streams and WebSocket
upgrades — `ws` is an alias kept so apps can document intent.  `shm`
creates a shared-memory frame ring (`ring.name` to pin the segment
name, else one derives from app/stream/pid and is exposed as
`source.ring_name`) and consumes it on a dedicated thread.

All of them register ONE AdmissionController per stream in
`rt.admission` — the rate limit/shed policy is global to the stream,
shared with the service front door (service.py) if the app is served.

The mapper SPI does not apply: frames ARE the columnar representation
(a `@map` annotation other than passThrough is rejected loudly rather
than silently ignored).
"""
from __future__ import annotations

import os
from typing import Optional

from ..core.io import PassThroughSourceMapper, Source, register_source_type
from ..core.planner import PlanError
from .admission import controller_from_options
from .ring import ShmRing
from .server import NetServer


class _NetSourceBase(Source):
    """Shared: admission registration + mapper validation."""

    def _check_mapper(self) -> None:
        if not isinstance(self.mapper, PassThroughSourceMapper):
            raise PlanError(
                f"@source(type={self.options.get('type')!r}) on "
                f"{self.stream_id!r}: the net plane is columnar — @map "
                f"is not applicable (frames are decoded straight into "
                f"arrays); remove the @map annotation")

    def _admission(self):
        ctrl = self.rt.admission.get(self.stream_id)
        if ctrl is None:
            ctrl = controller_from_options(self.stream_id, self.options,
                                           self.rt)
            self.rt.admission[self.stream_id] = ctrl
        return ctrl

    def _resolve(self, app: Optional[str], stream: str):
        if stream != self.stream_id:
            from .frame import FrameError
            raise FrameError(
                f"this endpoint serves stream {self.stream_id!r}, "
                f"not {stream!r}")
        return self.rt, self._admission()

    def net_metrics(self) -> dict:
        """Transport-level gauges merged into statistics()['net']."""
        return {}


class TcpSource(_NetSourceBase):
    """Frame server bound to one stream (raw TCP + WebSocket)."""

    def connect(self) -> None:
        self._check_mapper()
        self.server = NetServer(
            self._resolve,
            host=self.options.get("host", "127.0.0.1"),
            port=int(self.options.get("port", 0)),
            credit=int(self.options.get("credit", 64)),
            name=f"siddhi-net-{self.stream_id}")
        self._admission()               # register even before any frame
        self.server.start()
        self.port = self.server.port

    def disconnect(self) -> None:
        srv = getattr(self, "server", None)
        if srv is not None:
            srv.stop()
            # pending ('oldest') frames shed to the ErrorStore: nothing
            # admitted-but-unfed is silently lost at teardown
            ctrl = self.rt.admission.get(self.stream_id)
            if ctrl is not None:
                ctrl.flush_pending_to_store("source disconnected")

    def net_metrics(self) -> dict:
        srv = getattr(self, "server", None)
        return {"transport": "tcp", **srv.metrics()} if srv else {}


class ShmSource(_NetSourceBase):
    """Shared-memory ring consumer for co-located producers."""

    def connect(self) -> None:
        self._check_mapper()
        name = self.options.get("ring.name") or \
            f"sid_{self.rt.app.name[:12]}_{self.stream_id[:12]}_{os.getpid()}"
        self.ring = ShmRing.create(
            name=name,
            slots=int(self.options.get("slots", 64)),
            slot_size=int(self.options.get("slot.size", 256 << 10)))
        self.ring_name = self.ring.name
        # listener-less server: only the ring consumer thread and the
        # Connection/feed-gate machinery — no TCP socket is bound
        self.server = NetServer(self._resolve, listen=False,
                                name=f"siddhi-shm-{self.stream_id}")
        self._admission()
        self.server.attach_ring(self.ring)

    def disconnect(self) -> None:
        srv = getattr(self, "server", None)
        if srv is not None:
            srv.stop()
            ctrl = self.rt.admission.get(self.stream_id)
            if ctrl is not None:
                ctrl.flush_pending_to_store("source disconnected")

    def net_metrics(self) -> dict:
        srv = getattr(self, "server", None)
        return {"transport": "shm", **srv.metrics()} if srv else {}


def register() -> None:
    from ..extension import Example, ExtensionMeta
    register_source_type("tcp", TcpSource, meta=ExtensionMeta(
        name="tcp", namespace="source",
        description="columnar frame ingest over raw TCP or WebSocket "
                    "(zero per-event Python; docs/SERVING.md)",
        examples=(Example(
            "@source(type='tcp', port='0', rate.limit='50000', "
            "shed.policy='shed') define stream S (sym string, p double);",
            "frame server on an ephemeral port with a 50k eps "
            "admission limit shedding into the ErrorStore"),)))
    register_source_type("ws", TcpSource, meta=ExtensionMeta(
        name="ws", namespace="source",
        description="alias of the tcp frame source (the server sniffs "
                    "WebSocket upgrades on the same port)",
        examples=(Example(
            "@source(type='ws', port='8007') "
            "define stream S (sym string, p double);",
            "WebSocket producers connect to the same frame port"),)))
    register_source_type("shm", ShmSource, meta=ExtensionMeta(
        name="shm", namespace="source",
        description="shared-memory frame ring for co-located producers "
                    "(net/ring.py)",
        examples=(Example(
            "@source(type='shm', ring.name='ticks', slots='64') "
            "define stream S (sym string, p double);",
            "SPSC shm ring named 'ticks'; producers attach by name"),)))
