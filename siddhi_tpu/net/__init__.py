"""siddhi_tpu.net — the zero-copy serving data plane.

Columnar wire ingest (frame.py over TCP/WebSocket via server.py,
shared-memory rings via ring.py), admission control (admission.py),
batched sink egress (sink.py), and the producer client library
(client.py).  Importing this package registers the `tcp` / `ws` /
`shm` source types and `tcp` / `ws` sink types; `core.io.build_io`
imports it lazily the first time an app declares one, so apps that
never touch the network pay nothing.

See docs/SERVING.md for the wire format, ring layout, admission
semantics, and the ops runbook.
"""
from .admission import AdmissionController, TokenBucket
from .client import (FrameReceiver, NetClientError, RingProducer,
                     TcpFrameClient, WsFrameClient)
from .frame import FrameError
from .ring import ShmRing
from .server import NetServer
from . import sink as _sink
from . import source as _source

_source.register()
_sink.register()

__all__ = ["AdmissionController", "TokenBucket", "FrameError",
           "FrameReceiver", "NetClientError", "NetServer", "RingProducer",
           "ShmRing", "TcpFrameClient", "WsFrameClient"]
