"""WAL replication over the frame protocol: the primary-side shipper
and the standby-side receiver (core/replication.py holds the shared
brain; docs/RELIABILITY.md "High availability & failover" the contract).

A replication link is an ordinary frame connection that a standby
flips with REPL_SUBSCRIBE: from then on the primary's `WalShipper`
(its own thread, sharing the connection's write lock with the serve
loop) streams raw WAL records down it — byte-identical, so the
standby's log equals the primary's — and the standby's `WalReceiver`
streams append-acks back.  When the standby's watermark has fallen
behind a snapshot-barrier truncation, the shipper detects the gap
(WalTail.poll) and ships the persistence store's catch-up chain as
REPL_SNAPSHOT frames before resuming the record stream.

Fencing: every shipped frame is stamped with the primary's generation
(core/wal.py read_generation).  A promoted standby fences ABOVE the
highest generation it saw, so a deposed primary that comes back and
keeps shipping is rejected LOUDLY — the receiver captures to the
ErrorStore, counts `rejected_generation`, answers with an ERROR frame,
and drops the link (the split-brain chaos cell in bench.py pins this).

Failure handling rides the existing machinery: the receiver reconnects
under a BackoffPolicy behind a CircuitBreaker, and every non-clean
session end is captured to the standby's ErrorStore ('repl.receive').
"""
from __future__ import annotations

import pickle
import socket
import threading
import time
from typing import Callable, Optional

from ..core.faults import BackoffPolicy, CircuitBreaker
from ..core.persistence import _rev_time
from ..utils.locks import new_lock
from . import frame as fp


class ReplProtocolError(Exception):
    """Replication-level protocol violation (fencing, bad subscribe)."""


def catchup_revisions(store, app: str) -> list:
    """[(revision_id, blob, watermark|None)] the standby needs to
    reach the store's newest restorable state, oldest first — the same
    selection runtime.restore_last_state makes: the newest loadable
    full ('F-' or plain) plus every later 'I-' delta.  The watermark is
    each blob's own embedded per-stream WAL seq map."""
    if store is None or not hasattr(store, "revisions"):
        return []
    revs = store.revisions(app)
    fulls = [r for r in revs if not r.startswith("I-")]
    if not fulls:
        return []
    base = fulls[-1]
    chain = [base] + [r for r in revs
                      if r.startswith("I-") and _rev_time(r) > _rev_time(base)]
    out = []
    for rev in chain:
        try:
            blob = store.load(app, rev)
            body = pickle.loads(blob)
        except Exception:
            continue                    # corrupt: restore would skip it too
        wm = body.get("snapshot", {}).get("wal") \
            if isinstance(body, dict) and "table_deltas" in body \
            else (body.get("wal") if isinstance(body, dict) else None)
        out.append((rev, blob, wm))
    return out


# ---------------------------------------------------------------------------
# primary side
# ---------------------------------------------------------------------------

class WalShipper:
    """Streams one app's WAL down one replication link.  Runs on its
    own thread (the connection's serve loop keeps reading REPL_ACKs
    concurrently); `write` must already be serialized against the serve
    loop's replies by the connection's write lock."""

    POLL_RECORDS = 256
    IDLE_S = 0.02

    def __init__(self, rt, coord, write: Callable[[bytes], None],
                 subscribe: dict, stop: Callable[[], bool]):
        self.rt = rt
        self.coord = coord
        self.write = write
        self.stop = stop
        self.watermark = dict(subscribe.get("watermark") or {})
        self.standby_generation = int(subscribe.get("generation", 0))
        self._thread: Optional[threading.Thread] = None
        self._done = threading.Event()
        self.error: Optional[BaseException] = None

    def start(self) -> "WalShipper":
        self._thread = threading.Thread(
            target=self._run, name="siddhi-repl-ship", daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: float = 5.0) -> None:
        self._done.wait(timeout)

    def _run(self) -> None:
        self.coord.standby_attached()
        try:
            self._ship()
        except BaseException as e:      # surfaced on the connection
            self.error = e
            if not self.stop():
                try:
                    self.write(fp.encode_error(f"replication: {e}"))
                except OSError:
                    pass
        finally:
            self.coord.standby_detached()
            self._done.set()

    def _ship(self) -> None:
        rt, coord = self.rt, self.coord
        wal = getattr(rt, "wal", None)
        if wal is None:
            raise ReplProtocolError(
                f"app {rt.app.name!r} has no live WAL to replicate "
                f"(@app:durability required)")
        generation = wal.generation()
        if self.standby_generation > generation:
            # the subscriber has seen a NEWER primary: we are deposed —
            # refuse to serve rather than feed a stale timeline
            coord.rejected_generation += 1
            raise ReplProtocolError(
                f"fenced: subscriber at generation "
                f"{self.standby_generation} > ours ({generation}) — "
                f"this node was deposed")
        tail = wal.tail(self.watermark)
        hb_interval = coord.config.heartbeat_s
        last_hb = 0.0
        while not self.stop():
            records, gap = tail.poll(self.POLL_RECORDS)
            if records:
                nbytes = 0
                for stream, _seq, raw in records:
                    rt.inject("repl.ship", stream)
                    self.write(fp.encode_repl_record(generation, raw))
                    nbytes += len(raw)
                coord.note_shipped(len(records), nbytes)
            if gap:
                self._ship_catchup(tail, generation)
                continue
            coord.note_local(wal.watermark())
            now = time.monotonic()
            if now - last_hb >= hb_interval:
                last_hb = now
                self.write(fp.encode_repl_heartbeat(
                    generation, wal.watermark(), rt.now_ms()))
            if not records:
                # idle-poll, but wake instantly when a semi-sync barrier
                # needs its record on the wire (coord.wait_ack sets this)
                coord.ship_wake.wait(self.IDLE_S)
                coord.ship_wake.clear()

    def _ship_catchup(self, tail, generation: int) -> None:
        """The standby fell behind a snapshot-barrier truncation: ship
        the store's restore chain as REPL_SNAPSHOT frames, then advance
        the tail to the chain's watermark and resume streaming."""
        rt = self.rt
        store = rt.manager.persistence_store if rt.manager else None
        chain = catchup_revisions(store, rt.app.name)
        if not chain:
            raise ReplProtocolError(
                f"replication gap on {rt.app.name!r} with no snapshot "
                f"revision to catch up from (truncated WAL, empty "
                f"store)")
        final_wm = None
        for rev, blob, wm in chain:
            if wm is not None:
                final_wm = wm
        for i, (rev, blob, wm) in enumerate(chain):
            rt.inject("repl.ship", f"snapshot:{rev}")
            final = i == len(chain) - 1
            self.write(fp.encode_repl_snapshot(
                generation, rev, final_wm if final else None, blob,
                final=final))
        self.coord.shipped_snapshots += len(chain)
        tail.advance_to(final_wm)


# ---------------------------------------------------------------------------
# standby side
# ---------------------------------------------------------------------------

class WalReceiver:
    """Tails a primary's WAL into the standby's local log + store.
    One daemon thread: connect (BackoffPolicy under a CircuitBreaker),
    REPL_SUBSCRIBE from the local durable watermark, then apply frames
    as they arrive — records via wal.append_raw (byte-identical),
    snapshot revisions via store.save — acking each applied batch."""

    ACK_EVERY_S = 0.2

    def __init__(self, rt, coord, peer: str):
        host, _, port = str(peer).rpartition(":")
        if not host or not port.isdigit():
            raise ReplProtocolError(
                f"@app:replication peer {peer!r} is not 'host:port'")
        self.rt = rt
        self.coord = coord
        self.host, self.port = host, int(port)
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._lock = new_lock("WalReceiver._lock")
        self._thread = threading.Thread(
            target=self._run, name="siddhi-repl-recv", daemon=True)
        self.sessions = 0
        self.last_error: Optional[str] = None

    def start(self) -> "WalReceiver":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._lock:
            sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout)

    # -- the tailing loop ----------------------------------------------------

    def _run(self) -> None:
        breaker = CircuitBreaker(failure_threshold=5, reset_timeout_s=1.0)
        backoff = iter(())
        while not self._stop.is_set():
            if not breaker.allow():
                self._stop.wait(0.1)
                continue
            try:
                self._session()
                breaker.on_success()
                backoff = iter(())      # clean end: reset the schedule
            except Exception as e:
                breaker.on_failure()
                if self._stop.is_set():
                    return
                self.last_error = f"{type(e).__name__}: {e}"
                self.rt.error_store.add(
                    "_replication", "repl.receive", e, self.rt.now_ms())
                try:
                    delay = next(backoff)
                except StopIteration:
                    backoff = iter(BackoffPolicy(
                        max_tries=1 << 30, base_delay_s=0.05,
                        max_delay_s=2.0).delays())
                    delay = next(backoff)
                self._stop.wait(delay)

    def _session(self) -> None:
        rt, coord = self.rt, self.coord
        wal = rt.wal
        if wal is None:
            raise ReplProtocolError("standby has no open WAL")
        sock = socket.create_connection((self.host, self.port), timeout=5.0)
        try:
            # the append-ack is the primary's semi-sync barrier: a
            # Nagle-delayed ack frame stalls every producer barrier
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        with self._lock:
            self._sock = sock
        try:
            sock.settimeout(0.2)
            self.sessions += 1
            known_gen = max(wal.generation(), coord.source_generation())
            sock.sendall(fp.encode_repl_subscribe(
                rt.app.name, wal.watermark(), known_gen))
            buf = bytearray()
            applied = 0
            last_ack = time.monotonic()
            while not self._stop.is_set():
                frames = self._poll(sock, buf)
                for ftype, payload in frames:
                    if payload is None:     # CRC-rejected frame
                        raise fp.FrameDesync(
                            "checksum mismatch on replication link")
                    applied += self._on_frame(ftype, payload, sock)
                # ack as soon as a poll round applied anything: the
                # primary's semi-sync barrier is blocked on exactly this
                # (ACK_EVERY_S only throttles the idle re-ack cadence)
                now = time.monotonic()
                if applied or (now - last_ack >= self.ACK_EVERY_S
                               and frames):
                    self._ack(sock)
                    applied = 0
                    last_ack = now
        finally:
            with self._lock:
                self._sock = None
            try:
                sock.close()
            except OSError:
                pass

    def _poll(self, sock: socket.socket, buf: bytearray) -> list:
        frames = fp.parse_buffer_inplace(buf)
        if frames:
            return frames
        try:
            b = sock.recv(1 << 16)
        except socket.timeout:
            return []
        if not b:
            raise EOFError("replication link closed by primary")
        buf += b
        return fp.parse_buffer_inplace(buf)

    def _check_generation(self, gen: int) -> None:
        """Fencing: a frame stamped below OUR generation comes from a
        deposed primary — reject it loudly and kill the link."""
        coord, rt = self.coord, self.rt
        local = max(rt.wal.generation(), coord.source_generation())
        if gen < local:
            coord.rejected_generation += 1
            err = ReplProtocolError(
                f"fenced: record from deposed primary generation {gen} "
                f"< local {local} — rejected")
            rt.error_store.add("_replication", "repl.fence", err,
                               rt.now_ms())
            raise err
        coord.note_generation(gen)

    def _on_frame(self, ftype: int, payload: bytes,
                  sock: socket.socket) -> int:
        """-> number of applied records/snapshots (0 for control)."""
        rt, coord = self.rt, self.coord
        if ftype == fp.REPL_RECORD:
            gen, raw = fp.decode_repl_record(payload)
            self._check_generation(gen)
            stream, seq, applied = rt.wal.append_raw(raw)
            if applied:
                coord.note_applied(stream, seq, len(raw))
            return 1
        if ftype == fp.REPL_SNAPSHOT:
            gen, meta, blob = fp.decode_repl_snapshot(payload)
            self._check_generation(gen)
            store = rt.manager.persistence_store if rt.manager else None
            if store is None:
                raise ReplProtocolError(
                    "snapshot catch-up needs a persistence store on "
                    "the standby")
            store.save(rt.app.name, meta["revision"], blob)
            if meta.get("final"):
                wm = meta.get("watermark")
                coord.note_snapshot(wm)
                if wm:
                    # the shipped chain covers everything at-or-below
                    # its watermark: records resume strictly after it
                    rt.wal.floor_seqs(wm)
            else:
                coord.note_snapshot(None)
            return 1
        if ftype == fp.REPL_HEARTBEAT:
            st = fp.decode_repl_status(payload)
            self._check_generation(st["generation"])
            # answer immediately: heartbeats double as the semi-sync
            # liveness probe, and an ack carrying our unchanged
            # watermark is how the primary measures lag, not progress
            self._ack(sock)
            return 0
        if ftype == fp.ERROR:
            try:
                import json
                msg = json.loads(payload).get("error", "")
            except Exception:
                msg = payload.decode("utf-8", "replace")
            raise ReplProtocolError(f"primary rejected the link: {msg}")
        raise fp.FrameError(
            f"unexpected {fp.type_name(ftype)} frame on replication "
            f"link")

    def _ack(self, sock: socket.socket) -> None:
        rt, coord = self.rt, self.coord
        rt.inject("repl.ack", rt.app.name)
        gen = max(rt.wal.generation(), coord.source_generation())
        sock.sendall(fp.encode_repl_ack(gen, rt.wal.watermark()))
