"""Threaded frame server — the serving plane's ingest front door.

One `NetServer` accepts both raw-TCP frame streams and WebSocket
connections on the SAME port (the first bytes are sniffed: an HTTP
`GET ` upgrade request takes the RFC-6455 path, anything else is the
raw frame protocol), and can additionally consume shared-memory rings
(net/ring.py) — all three transports funnel through one per-connection
state machine:

    HELLO       -> resolve (app, stream), validate schema, HELLO_OK
    STRINGS     -> extend the connection's code remap (runtime lock)
    DATA        -> decode to numpy views, remap string codes (one
                   gather), admission-control, rt.send_columnar —
                   zero per-event Python on the admit path
    PING        -> feed+flush everything admitted, reply ACK (barrier)
    BYE / EOF   -> close

Admission decisions come from the per-stream AdmissionController
(net/admission.py) shared across every transport feeding the stream.
A 'block' decision stalls THIS reader thread — the socket stops
draining, which is kernel backpressure to the producer — and the
server stops granting CREDIT until feeding resumes.

Deploy/undeploy racing live ingest: `retire(app)` flips the runtime
into a parked state under the feed gate, so a frame is either fully
fed to the live runtime or captured whole into the app's ErrorStore
('net.undeployed') — never dropped, never half-delivered.
"""
from __future__ import annotations

import base64
import hashlib
import socket
import struct
import threading
import time
from typing import Callable, Optional

import numpy as np

from ..utils.locks import new_lock, new_rlock
from . import frame as fp
from .admission import ADMIT, AdmissionController, Work
from .ring import ShmRing

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


# ---------------------------------------------------------------------------
# byte-stream adapters
# ---------------------------------------------------------------------------

class SockStream:
    """Buffered reader with pushback over a socket, so protocol
    sniffing can un-read the bytes it peeked."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = bytearray()         # append-in-place: O(1) amortized

    def push_back(self, data: bytes) -> None:
        self._buf[:0] = data

    def read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            b = self.sock.recv(max(4096, n - len(self._buf)))
            if not b:
                raise EOFError("connection closed mid-frame")
            self._buf += b
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def read_line(self, limit: int = 8192) -> bytes:
        while b"\n" not in self._buf:
            if len(self._buf) > limit:
                raise fp.FrameError("oversized header line")
            b = self.sock.recv(4096)
            if not b:
                raise EOFError("connection closed in headers")
            self._buf += b
        i = self._buf.index(b"\n")
        line = bytes(self._buf[:i])
        del self._buf[:i + 1]
        return line.rstrip(b"\r")

    def write(self, data: bytes) -> None:
        self.sock.sendall(data)


class TcpWire:
    """Buffer-based frame receive over raw TCP: a read timeout mid-frame
    keeps the partial bytes in the buffer, so a slow producer can NEVER
    desync the stream (the old read_exact-per-frame approach discarded
    an already-consumed header when the payload stalled)."""

    def __init__(self, stream: SockStream):
        self.sock = stream.sock
        self._buf = stream._buf         # adopt any sniffed leftovers
        stream._buf = bytearray()

    def write(self, data: bytes) -> None:
        self.sock.sendall(data)

    def poll(self) -> list:
        """Complete frames available now (possibly []); raises
        EOFError/OSError when the connection dies.  Blocks at most one
        socket-timeout interval."""
        frames = fp.parse_buffer_inplace(self._buf)
        if frames:
            return frames
        try:
            b = self.sock.recv(1 << 16)
        except socket.timeout:
            return []
        if not b:
            raise EOFError("connection closed")
        self._buf += b
        return fp.parse_buffer_inplace(self._buf)


class WsWire:
    """RFC-6455 server side, buffer-based like TcpWire: complete ws
    messages are unwrapped into a byte stream, complete protocol frames
    parsed out of it; partial data at any layer just waits in its
    buffer.  Writes wrap each protocol frame in one unmasked binary
    message."""

    def __init__(self, stream: SockStream):
        self.sock = stream.sock
        self._ws_buf = stream._buf      # raw bytes (possibly mid-message)
        stream._buf = bytearray()
        self._stream_buf = bytearray()  # unwrapped protocol bytes

    def write_ws(self, opcode: int, payload: bytes) -> None:
        n = len(payload)
        if n < 126:
            hdr = bytes([0x80 | opcode, n])
        elif n < (1 << 16):
            hdr = bytes([0x80 | opcode, 126]) + struct.pack(">H", n)
        else:
            hdr = bytes([0x80 | opcode, 127]) + struct.pack(">Q", n)
        self.sock.sendall(hdr + payload)

    def write(self, data: bytes) -> None:
        self.write_ws(0x2, data)

    def _unwrap(self) -> None:
        while True:
            got = fp.parse_ws_frame_inplace(self._ws_buf)
            if got is None:
                return
            opcode, body = got
            if opcode == 0x8:                 # close
                raise EOFError("websocket closed")
            if opcode == 0x9:                 # ping -> pong
                self.write_ws(0xA, body)
            elif opcode != 0xA:               # binary/text/continuation
                self._stream_buf += body

    def poll(self) -> list:
        self._unwrap()
        frames = fp.parse_buffer_inplace(self._stream_buf)
        if frames:
            return frames
        try:
            b = self.sock.recv(1 << 16)
        except socket.timeout:
            return []
        if not b:
            raise EOFError("websocket closed")
        self._ws_buf += b
        self._unwrap()
        return fp.parse_buffer_inplace(self._stream_buf)


def ws_handshake(stream: SockStream, first_line: bytes) -> WsWire:
    """Complete the server side of an RFC-6455 upgrade; `first_line` is
    the already-read request line."""
    key = None
    while True:
        line = stream.read_line()
        if not line:
            break
        k, _, v = line.decode("latin1").partition(":")
        if k.strip().lower() == "sec-websocket-key":
            key = v.strip()
    if key is None:
        raise fp.FrameError("websocket upgrade without Sec-WebSocket-Key")
    accept = base64.b64encode(
        hashlib.sha1((key + _WS_GUID).encode()).digest()).decode()
    stream.write(
        b"HTTP/1.1 101 Switching Protocols\r\n"
        b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
        b"Sec-WebSocket-Accept: " + accept.encode() + b"\r\n\r\n")
    return WsWire(stream)


# ---------------------------------------------------------------------------
# per-connection state machine
# ---------------------------------------------------------------------------

class Connection:
    """One negotiated ingest connection (TCP, WS, or ring)."""

    def __init__(self, server: "NetServer", label: str,
                 send: Optional[Callable[[bytes], None]] = None):
        self.server = server
        self.label = label
        self.send = send                # None: no backchannel (ring)
        # replication link state: once REPL_SUBSCRIBE arrives the
        # connection is repl-dedicated — a WalShipper thread writes
        # records down it while the serve loop keeps reading acks, so
        # every write goes through _wlock
        self._wlock = new_lock("Connection._wlock")
        self.closed = False
        self._shipper = None
        self._repl_coord = None
        self.rt = None
        self.stream_id: Optional[str] = None
        self.schema = None
        self.ctrl: Optional[AdmissionController] = None
        self.remap = fp.StringRemap()
        # store-query egress dictionary (RESULT string columns): the
        # mirror of `remap` — codes WE assign, shipped to the peer as
        # STRINGS deltas ahead of each RESULT.  `_egress_synced` is the
        # first code the peer has NOT mapped yet; it advances only after
        # a successful encode, so a query that failed mid-encode re-ships
        # its orphaned registrations with the next result
        self._egress = fp.WireStringTable()
        self._egress_synced = 1
        self.credit_chunk = 0
        self._since_credit = 0
        self._str_cols: list = []
        self.frames = 0
        self.events = 0
        # producer-stamped trace context (TRACE frame) for the NEXT
        # DATA frame on this connection
        self._next_trace = None

    # -- frame dispatch -----------------------------------------------------

    def on_frame(self, ftype: int, payload: bytes) -> bool:
        """Handle one frame; returns False when the connection should
        close."""
        if ftype == fp.BYE:
            return False
        if ftype == fp.HELLO:
            self._on_hello(fp.decode_hello(payload))
            return True
        if ftype == fp.REPL_SUBSCRIBE:
            self._on_repl_subscribe(fp.decode_repl_subscribe(payload))
            return True
        if ftype in (fp.REPL_ACK, fp.REPL_HEARTBEAT):
            self._on_repl_status(fp.decode_repl_status(payload), ftype)
            return True
        if ftype == fp.QUERY:
            # dispatched BEFORE the rt-None check: a query-only
            # connection never HELLOs (it names its app in the frame)
            self._on_query(payload)
            return True
        if self.rt is None:
            raise fp.FrameError(
                f"{fp.type_name(ftype)} before HELLO on {self.label}")
        if ftype == fp.STRINGS:
            start, new = fp.decode_strings(payload)
            with self.rt._lock:         # StringTable writes are shared
                self.remap.extend(start, new, self.rt.strings)
            return True
        if ftype == fp.TRACE:
            # wire trace context: adopt the producer's id for the next
            # DATA frame (always traced, bypassing sampling)
            self._next_trace = fp.decode_trace(payload)
            return True
        if ftype == fp.DATA:
            self._on_data(payload)
            return True
        if ftype == fp.PING:
            token = fp.decode_u64(payload)
            self.pump()
            wal0 = getattr(self.rt, "wal", None)
            if wal0 is not None and self.ctrl is not None:
                # durable-ACK: frames parked by the 'oldest' policy (or
                # mid-feed on another thread) are memory-only — acking
                # past them would bound the producer's retransmit
                # buffer below data that can still vanish.  Wait for
                # the park to drain (token refills feed it; sheds land
                # accounted in the ErrorStore); shutdown mid-wait
                # closes WITHOUT acking.
                while self.ctrl.pending_count():
                    if self.server.stopping():
                        return False
                    time.sleep(0.005)
                    self.pump()
            self.rt.flush()
            # durable-ACK contract (docs/SERVING.md): under
            # @app:durability an ACK means every frame before the PING
            # is in the write-ahead log AND fsynced — the producer may
            # discard its retransmit buffer.  ('batch' policy frames
            # are flushed per append; this barrier is the fsync.)
            wal = getattr(self.rt, "wal", None)
            if wal is not None:
                try:
                    wal.barrier()
                except Exception as e:
                    # a failed barrier must NOT ack: the producer would
                    # discard frames the log cannot promise.  Fatal to
                    # the connection (like a desync) — the producer
                    # reconnects and retransmits from its last ACK.
                    raise fp.FrameDesync(
                        f"durability barrier failed: {e}") from e
                # semi-sync replication moves the durable-ACK barrier
                # to "local fsync + standby append-ack": the producer's
                # retransmit buffer may only be discarded once the
                # frames exist on BOTH machines.  A timeout (or no
                # standby, unless degrade='async') fails the barrier —
                # lying here would turn machine loss into silent loss.
                coord = getattr(self.rt, "replication", None)
                if coord is not None and coord.config.mode == "semi-sync" \
                        and coord.role == "primary":
                    if not coord.wait_ack(wal.watermark()):
                        raise fp.FrameDesync(
                            f"semi-sync barrier: no standby append-ack "
                            f"within {coord.config.ack_timeout_s}s "
                            f"({coord.standbys()} standby(s) attached)")
            self._reply(fp.encode_ack(token))
            return True
        raise fp.FrameError(
            f"unexpected {fp.type_name(ftype)} frame on {self.label}")

    def _on_hello(self, hello: dict) -> None:
        try:
            rt, ctrl = self.server.resolve(hello.get("app"), hello["stream"])
        except KeyError as e:
            # unknown app/stream: a protocol-level rejection (ERROR
            # frame + close), not a server-side crash
            raise fp.FrameError(str(e).strip("'\"")) from None
        schema = rt.schemas.get(hello["stream"])
        if schema is None:
            raise fp.FrameError(f"unknown stream {hello['stream']!r}")
        fp.validate_hello_schema(hello, schema)
        if self.rt is not None:
            # re-negotiation: the remap ties THIS connection's string
            # codes to the previously bound runtime's table, so it is
            # stale either way — the peer must re-ship its dictionary
            # (explicit start codes make the replay idempotent; a
            # continuation without one trips the delta-gap check loudly
            # instead of ingesting wrong strings), and credit
            # accounting restarts with the new negotiation
            self.remap = fp.StringRemap()
            self._since_credit = 0
        self.rt, self.schema, self.ctrl = rt, schema, ctrl
        self.stream_id = hello["stream"]
        from ..query.ast import AttrType
        self._str_cols = [a.name for a in schema.attributes
                          if a.type == AttrType.STRING]
        self.credit_chunk = self.server.credit if hello.get("credit") else 0
        self._reply(fp.encode_hello_ok(self.credit_chunk))

    # -- replication link (net/repl.py WalShipper) ---------------------------

    def _on_repl_subscribe(self, sub: dict) -> None:
        if self.send is None:
            raise fp.FrameError(
                "replication needs a duplex transport (not a ring)")
        if self._shipper is not None:
            raise fp.FrameError(
                f"duplicate REPL_SUBSCRIBE on {self.label}")
        try:
            rt = self.server.repl_resolve(sub["app"])
        except KeyError as e:
            raise fp.FrameError(str(e).strip("'\"")) from None
        if getattr(rt, "is_standby", lambda: False)():
            raise fp.FrameError(
                f"app {sub['app']!r} is itself a standby replica — "
                f"subscribe to the primary")
        coord = rt._ensure_replication(default=True)
        if coord is None or getattr(rt, "wal", None) is None:
            raise fp.FrameError(
                f"app {sub['app']!r} has no live WAL to replicate "
                f"(@app:durability required)")
        from .repl import WalShipper
        self.rt = rt                    # repl-dedicated binding
        self._repl_coord = coord
        self._shipper = WalShipper(
            rt, coord, self._reply, sub,
            stop=lambda: self.server.stopping() or self.closed).start()

    def _on_repl_status(self, status: dict, ftype: int) -> None:
        coord = self._repl_coord
        if coord is None:
            raise fp.FrameError(
                f"{fp.type_name(ftype)} before REPL_SUBSCRIBE on "
                f"{self.label}")
        wal = getattr(self.rt, "wal", None)
        if wal is not None and status["generation"] > wal.generation():
            # the standby has been promoted past us: we are deposed —
            # fatal, and every later local append is suspect
            coord.rejected_generation += 1
            raise fp.FrameDesync(
                f"fenced: standby at generation {status['generation']} "
                f"> ours ({wal.generation()}) — this node was deposed")
        if ftype == fp.REPL_ACK:
            coord.on_ack(status["watermark"])
        else:
            coord.on_heartbeat(status["watermark"])

    # -- store queries (QUERY -> STRINGS? + RESULT) ---------------------------

    def _on_query(self, payload: bytes) -> None:
        token, app, text = fp.decode_query(payload)
        if self.send is None:
            raise fp.FrameError(
                "QUERY needs a duplex transport (not a ring)")
        self.server._count(store_queries=1)
        try:
            rt = (self.server.query_resolve(app) if app is not None
                  else self.rt)
            if rt is None:
                raise fp.FrameError(
                    "QUERY names no app and no HELLO bound one")
            # compile (cached per query text in the runtime) + execute
            # under the feed gate — the result is a consistent snapshot
            # against every transport feeding this runtime
            schema, rows = rt.query_with_schema(text)
            blob = self._encode_result(token, schema, rows)
        except Exception as e:
            # compile/execute/resolve failures ride RESULT, not ERROR,
            # so the client correlates them by token — and a bad query
            # never costs the producer its ingest connection
            msg = str(e).strip("'\"") or type(e).__name__
            blob = fp.encode_result(token, {"error": msg})
        self._reply(blob)

    def _encode_result(self, token: int, schema, rows) -> bytes:
        """(optional STRINGS delta +) RESULT frame bytes for one store
        query's out_schema + rows.  Doubles ship float64 (exactness
        beats the ingest plane's f32 compaction here); numeric nulls
        encode NaN/0, string nulls code 0."""
        from ..core.schema import dtype_of
        from ..query.ast import AttrType
        meta_cols = [[a.name, a.type.name.lower()]
                     for a in schema.attributes]
        ts = np.fromiter((r[0] for r in rows), dtype=np.int64,
                         count=len(rows))
        cols = []
        for j, a in enumerate(schema.attributes):
            vals = [r[1][j] for r in rows]
            if a.type == AttrType.STRING:
                codes, _new = self._egress.encode_column(vals)
                cols.append(codes)
                continue
            dt = np.dtype(dtype_of(a.type, float64=True))
            if dt.kind == "O":
                raise fp.FrameError(
                    f"RESULT object column {a.name!r} cannot ride the "
                    f"wire")
            if dt.kind == "f":
                arr = np.array([np.nan if v is None else v for v in vals],
                               dtype=dt)
            else:
                arr = np.array([0 if v is None else v for v in vals],
                               dtype=dt)
            cols.append(arr)
        body = fp.encode_data_payload(ts, cols)
        out = []
        delta = self._egress.strings_from(self._egress_synced)
        if delta:
            out.append(fp.encode_strings(delta,
                                         start_code=self._egress_synced))
        self._egress_synced = len(self._egress)
        out.append(fp.encode_result(token, {"cols": meta_cols}, body))
        # one write: the delta can never arrive after the RESULT that
        # needs it, even with the WalShipper sharing this wire
        return b"".join(out)

    def _on_data(self, payload: bytes) -> None:
        rt = self.rt
        try:
            rt.inject("net.decode", self.stream_id)
        except Exception as e:
            # injected decode fault: connection-fatal like a corrupt
            # frame off the wire (faults.py POINTS) — mapped so the
            # serve loop accounts a protocol error instead of the
            # RuntimeError escaping and killing the thread unhandled
            raise fp.FrameDesync(f"decode fault: {e}") from e
        ts, cols = fp.decode_data(payload, self.schema)
        for name in self._str_cols:     # one gather per string column
            cols[name] = self.remap.apply(cols[name])
        n = int(ts.shape[0])
        self.frames += 1  # lint: unlocked-ok (single serve-thread writer; _wlock only serializes wire writes)
        self.events += n  # lint: unlocked-ok (single serve-thread writer; _wlock only serializes wire writes)
        # frame tracing: a producer-stamped id (TRACE frame) always
        # traces; otherwise the runtime tracer makes the sampling call.
        # The handle rides the Work so a parked ('oldest') frame fed
        # later on another thread keeps its tree.
        tc, self._next_trace = self._next_trace, None
        h = None
        tracer = getattr(rt, "tracing", None)
        if tracer is not None:
            h = tracer.begin_frame(
                self.stream_id, trace_id=None if tc is None else tc[0],
                parent=0 if tc is None else tc[1])
        work = self.server.make_work(rt, self.stream_id, self.schema,
                                     ts, cols, len(payload), trace=h)
        t0a = time.perf_counter() if h is not None else 0.0
        d = self.ctrl.submit(work, stop=self.server.stopping)
        if h is not None:
            # the admit span covers the admission decision including
            # any block-policy wait; a parked frame's queue time shows
            # as the gap between admit and its (later) wal.append
            h.mark("admit", t0a, time.perf_counter() - t0a,
                  action=d.action, events=n)
        for w in d.ready:
            # guarded: queued work is mixed-provenance (REST batches
            # share the controller and their feeds can raise, e.g. a
            # type-bad value surfacing at flush) — an exception here
            # must capture to the ErrorStore, not kill this connection
            self.ctrl.feed_safely(w)
        if d.action == ADMIT:
            work.feed()                 # our own make_work: self-captures
        self._grant_credit()

    def pump(self) -> None:
        """Feed any pending ('oldest' policy) work whose tokens
        refilled — called between frames and on idle ticks."""
        if self.ctrl is not None:
            for w in self.ctrl.pump():
                self.ctrl.feed_safely(w)

    def _grant_credit(self) -> None:
        # credit is granted AFTER the frame fed (the call site above) —
        # under @app:durability the feed path appended (and, for
        # 'fsync', synced) the WAL first, so credit never outruns the
        # log on the admit path.  The queued ('oldest') path can grant
        # before its park drains; ACK — the PING barrier — is the
        # durability signal producers must trust for retransmit.
        if self.send is None or not self.credit_chunk:
            return
        self._since_credit += 1  # lint: unlocked-ok (single serve-thread writer; _wlock only serializes wire writes)
        if self._since_credit >= max(1, self.credit_chunk // 2):
            self._reply(fp.encode_credit(self._since_credit))
            self.server._count(credit_granted=self._since_credit)
            self._since_credit = 0

    def _reply(self, data: bytes) -> None:
        # locked: on a replication link the WalShipper thread and the
        # serve loop both write to the same wire
        if self.send is not None:
            with self._wlock:
                self.send(data)


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------

class NetServer:
    """Threaded TCP/WS frame listener + shm-ring consumers, feeding one
    or many runtimes through `resolve_fn(app, stream) ->
    (rt, AdmissionController)`."""

    def __init__(self, resolve_fn: Callable, host: str = "127.0.0.1",
                 port: int = 0, credit: int = 64, name: str = "siddhi-net",
                 listen: bool = True,
                 repl_resolve: Optional[Callable] = None,
                 query_resolve: Optional[Callable] = None):
        """`listen=False` builds a listener-less server — no TCP socket
        at all — for transports that only need the connection/feed-gate
        machinery (shm-ring consumers via attach_ring).  `repl_resolve`
        maps an app name to its runtime for REPL_SUBSCRIBE links
        (raising KeyError rejects the subscription); None disables
        replication on this front door.  `query_resolve` maps an app
        name to its runtime for QUERY frames naming an app explicitly
        (the HELLO-bound runtime serves app-less queries either way);
        None restricts store queries to HELLO-bound connections."""
        self._resolve = resolve_fn
        self._repl_resolve = repl_resolve
        self._query_resolve = query_resolve
        self.credit = int(credit)
        self.name = name
        self._sock = None
        self.host, self.port = host, None
        if listen:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, int(port)))
            self._sock.listen(64)
            # a cross-thread close() does not reliably wake a blocking
            # accept() on Linux: poll with a short timeout instead, so
            # stop() always unblocks the accept loop promptly
            self._sock.settimeout(0.2)
            self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._threads: list = []
        self._conn_socks: list = []
        self._rings: list = []          # (ring, thread)
        self._lock = new_lock("NetServer._lock")
        # counters (server-level; per-stream counters live on the
        # AdmissionControllers)
        self.connections = 0
        self.open_connections = 0
        self.ws_connections = 0
        self.frames_in = 0
        self.events_in = 0
        self.bytes_in = 0
        self.credit_granted = 0
        self.protocol_errors = 0
        self.store_queries = 0

    # -- wiring -------------------------------------------------------------

    def resolve(self, app: Optional[str], stream: str):
        return self._resolve(app, stream)

    def repl_resolve(self, app: str):
        if self._repl_resolve is None:
            raise KeyError(
                f"replication is not enabled on this endpoint "
                f"(no repl_resolve for app {app!r})")
        return self._repl_resolve(app)

    def query_resolve(self, app: str):
        if self._query_resolve is None:
            raise KeyError(
                f"named-app store queries are not enabled on this "
                f"endpoint (no query_resolve for app {app!r}) — "
                f"HELLO-bind the connection instead")
        return self._query_resolve(app)

    def stopping(self) -> bool:
        return self._stop.is_set()

    def _gate_of(self, rt) -> threading.RLock:
        """The feed-vs-retire gate for ONE runtime.  It lives ON the
        runtime (like the retired mark) for two reasons: independent
        apps served by one front door must not serialize their ingest
        on a shared lock, and a runtime fed by SEVERAL servers (its own
        @source port plus the service front door) needs retire() to
        serialize against every feeder, not just this one."""
        gate = getattr(rt, "_net_gate", None)
        if gate is None:
            with self._lock:
                gate = getattr(rt, "_net_gate", None)
                if gate is None:
                    gate = rt._net_gate = new_rlock(
                        "SiddhiAppRuntime._net_gate")
        return gate

    def retire(self, rt) -> None:
        """Park a runtime (undeploy/redeploy): frames already admitted
        for THIS runtime land whole in its ErrorStore from now on.  The
        mark lives ON the runtime object (not in an id-keyed map — a
        collected runtime's id() could be recycled by a later deploy and
        silently divert ITS ingest), so a redeploy under the same name
        serves live through the new runtime while old connections'
        frames park instead of feeding the zombie.  Serialized against
        feeds by the runtime's gate — no frame is mid-feed when this
        returns."""
        with self._gate_of(rt):
            rt._net_retired_store = rt.error_store

    def make_work(self, rt, stream_id: str, schema, ts, cols,
                  nbytes: int, trace=None) -> Work:
        from ..core.batch import rows_of_columns
        gate = self._gate_of(rt)

        def _feed_inner(rt=rt, stream_id=stream_id, ts=ts, cols=cols):
            # sink deliveries staged by this feed are deferred past the
            # gate (runtime._flush_sink_outbox honors `defer_sink`): a
            # sink retry backoff sleeping under the gate would stall
            # retire()/undeploy for the whole backoff schedule
            tls = rt._trace_tls
            tls.defer_sink = getattr(tls, "defer_sink", 0) + 1
            try:
                with gate:
                    store = getattr(rt, "_net_retired_store", None)
                    if store is not None:
                        store.add(stream_id, "net.undeployed",
                                  "frame admitted before undeploy",
                                  rt.now_ms(),
                                  events=rows_of_columns(schema, ts, cols,
                                                         rt.strings))
                        return
                    try:
                        rt.inject("net.feed", stream_id)
                        rt.send_columnar(stream_id, cols, ts)
                    except Exception as e:
                        # an admitted frame must NEVER vanish: capture
                        # whole — unless the WAL append path already did
                        # (a second entry would double-ingest on replay)
                        if not getattr(e, "_wal_captured", False):
                            rt.error_store.add(
                                stream_id, "net.feed", e, rt.now_ms(),
                                events=rows_of_columns(schema, ts, cols,
                                                       rt.strings))
                        rt.stats.on_fault(stream_id, "net.feed")
            finally:
                tls.defer_sink -= 1
            rt._flush_sink_outbox()

        if trace is None:
            feed = _feed_inner
        else:
            def feed(rt=rt):
                # install the frame's trace handle on WHICHEVER thread
                # ends up feeding (connection, scheduler pump, another
                # connection's drain): runtime._freeze picks it up so
                # wal.append/freeze/dispatch spans join the same tree
                prev = rt._set_trace(trace)
                try:
                    _feed_inner()
                finally:
                    rt._trace_tls.handle = prev

        return Work(n=int(ts.shape[0]), nbytes=nbytes, feed=feed,
                    rows=lambda: rows_of_columns(schema, ts, cols,
                                                 rt.strings),
                    stream_id=stream_id, trace=trace)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "NetServer":
        if self._sock is not None:
            self._accept_thread = threading.Thread(
                target=self._accept_loop, name=f"{self.name}-accept",
                daemon=True)
            self._accept_thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            # snapshot sockets AND threads under the lock: the accept
            # loop rebuilds self._threads concurrently, and a join list
            # read outside the lock could miss the newest connection
            # thread (surfaced by the SL03 lockset self-analysis)
            socks = list(self._conn_socks)
            conn_threads = list(self._threads)
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        deadline = time.monotonic() + timeout
        threads = ([self._accept_thread] if self._accept_thread else []) \
            + [t for _, t in self._rings] + conn_threads
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        for ring, _ in self._rings:
            ring.close()
            if ring.owner:
                ring.unlink()
        self._rings.clear()

    # -- TCP/WS path --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._sock.accept()
            except socket.timeout:
                continue                # poll the stop flag
            except OSError:
                return                  # listener closed
            try:
                # barrier-critical small frames (durable ACKs, the
                # semi-sync replication handshake) must not sit out a
                # Nagle/delayed-ACK round trip
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            t = threading.Thread(
                target=self._serve_conn, args=(sock, addr),
                name=f"{self.name}-conn", daemon=True)
            with self._lock:
                self._conn_socks.append(sock)
                self._threads = [th for th in self._threads
                                 if th.is_alive()] + [t]
            t.start()

    def _count(self, **deltas) -> None:
        """Counter updates from connection/ring threads — locked, so
        concurrent producers never lose increments."""
        with self._lock:
            for key, d in deltas.items():
                setattr(self, key, getattr(self, key) + d)

    def _count_frame(self, ftype: int, payload) -> None:
        if payload is None:             # corrupt frame (CRC rejected)
            self._count(frames_in=1)
            return
        ev = struct.unpack_from("<I", payload, 0)[0] \
            if ftype == fp.DATA and len(payload) >= 4 else 0
        self._count(frames_in=1, bytes_in=len(payload), events_in=ev)

    HANDSHAKE_TIMEOUT_S = 10.0

    def _serve_conn(self, sock: socket.socket, addr) -> None:
        self._count(connections=1, open_connections=1)
        label = f"{addr[0]}:{addr[1]}"
        conn: Optional[Connection] = None
        try:
            # sniff + ws upgrade get a generous deadline (a pooled
            # producer may connect before it has data); the frame loop
            # then drops to short timeouts so idle ticks drive pump()
            sock.settimeout(self.HANDSHAKE_TIMEOUT_S)
            stream = SockStream(sock)
            wire = self._sniff(stream)
            sock.settimeout(0.2)
            conn = Connection(self, label, send=wire.write)
            while not self._stop.is_set():
                frames = wire.poll()    # buffer-based: a timeout mid-
                if not frames:          # frame can never desync
                    conn.pump()
                    continue
                for ftype, payload in frames:
                    self._count_frame(ftype, payload)
                    if payload is None:
                        # CRC failure: the frame was consumed whole by
                        # its length prefix, so the stream is still
                        # aligned — reject THIS frame, keep serving
                        self._count(protocol_errors=1)
                        try:
                            wire.write(fp.encode_error(
                                f"checksum mismatch on "
                                f"{fp.type_name(ftype)} frame (rejected)"))
                        except OSError:
                            pass
                        continue
                    try:
                        if not conn.on_frame(ftype, payload):
                            return
                    except fp.FrameDesync:
                        raise
                    except fp.FrameError as e:
                        self._count(protocol_errors=1)
                        try:
                            wire.write(fp.encode_error(str(e)))
                        except OSError:
                            pass
                        if conn.rt is None or ftype == fp.HELLO:
                            # no negotiated binding (or a rejected
                            # re-negotiation): nothing sound can follow
                            return
                        # payload-level error on a live binding
                        # (truncated DATA, bad STRINGS delta, ...):
                        # framing is intact — drop the frame, carry on
        except socket.timeout:
            pass                        # no HELLO within the handshake
        except (EOFError, ConnectionError, OSError):  # deadline
            pass                        # disconnects (mid-frame too) are
        except fp.FrameError:           # normal serving-plane weather
            self._count(protocol_errors=1)
        finally:
            if conn is not None:
                conn.closed = True      # stops a WalShipper on this link
                if conn._shipper is not None:
                    conn._shipper.join(timeout=2.0)
                try:
                    conn.pump()
                except Exception:
                    pass
            self._count(open_connections=-1)
            try:
                sock.close()
            except OSError:
                pass
            with self._lock:
                if sock in self._conn_socks:
                    self._conn_socks.remove(sock)

    def _sniff(self, stream: SockStream):
        head = stream.read_exact(4)
        if head == b"GET ":
            self._count(ws_connections=1)
            first = head + stream.read_line()
            return ws_handshake(stream, first)
        stream.push_back(head)
        return TcpWire(stream)

    # -- shm-ring path ------------------------------------------------------

    def attach_ring(self, ring: ShmRing, label: Optional[str] = None) -> None:
        """Consume a shared-memory ring on a dedicated thread.  The ring
        carries the same frames; there is no backchannel, so credit is
        the ring's own occupancy (a full ring blocks the producer)."""
        conn = Connection(self, label or f"shm:{ring.name}", send=None)

        def loop():
            while not self._stop.is_set():
                data = ring.pop(timeout=0.1)
                if data is None:
                    conn.pump()
                    continue
                try:
                    frames, rest = fp.parse_buffer(data)
                    if rest:
                        raise fp.FrameError(
                            "ring slot holds a truncated frame")
                    for ftype, payload in frames:
                        self._count_frame(ftype, payload)
                        if payload is None:     # CRC-rejected frame
                            self._count(protocol_errors=1)
                            continue
                        if not conn.on_frame(ftype, payload):
                            # BYE ends the PRODUCER, not the ring: the
                            # consumer outlives it so the next producer
                            # attaching to the same ring (it re-HELLOs
                            # to rebind) isn't left pushing into a ring
                            # nobody drains
                            conn.pump()
                except fp.FrameError:
                    self._count(protocol_errors=1)

        t = threading.Thread(target=loop, name=f"{self.name}-ring",
                             daemon=True)
        self._rings.append((ring, t))
        t.start()

    # -- telemetry ----------------------------------------------------------

    def metrics(self) -> dict:
        # wire_* are transport-level totals (control frames included);
        # the per-stream ingest counters live on the AdmissionControllers
        # under their own frames_in/events_in/bytes_in names
        m = {**({"port": self.port} if self.port is not None else {}),
             "connections": self.connections,
             "open_connections": self.open_connections,
             "ws_connections": self.ws_connections,
             "wire_frames": self.frames_in,
             "wire_events": self.events_in,
             "wire_bytes": self.bytes_in,
             "credit_granted": self.credit_granted,
             "protocol_errors": self.protocol_errors,
             "store_queries": self.store_queries}
        if self._rings:
            occ = [r.occupancy() for r, _ in self._rings]
            m["rings"] = len(self._rings)
            m["ring_occupancy"] = sum(u for u, _ in occ)
            m["ring_slots"] = sum(s for _, s in occ)
        return m
