"""siddhi_tpu — a TPU-native stream-processing / CEP framework.

A from-scratch re-design of the capabilities of Siddhi 4.x
(reference: /root/reference, single-JVM Java event-at-a-time engine) for
TPU hardware: queries compile to a small number of fused, batched JAX/XLA
array programs over columnar micro-batches; partitions and concurrent
queries become batch/shard axes over a `jax.sharding.Mesh`.

Public facade (mirrors reference core:SiddhiManager.java:45 /
core:SiddhiAppRuntime.java:93):

    from siddhi_tpu import SiddhiManager
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime('''
        define stream StockStream (symbol string, price double, volume int);
        @info(name='q1')
        from StockStream[price > 100] select symbol, price insert into OutStream;
    ''')
    rt.add_callback("OutStream", lambda events: ...)
    h = rt.input_handler("StockStream")
    rt.start()
    h.send(("IBM", 101.0, 5))
    rt.flush()          # drain micro-batch through the compiled kernels
"""

import os as _os


def _enable_kernel_cache() -> None:
    """Persistent kernel cache: query plans jit-compile sizeable XLA
    programs (~10 s each through a tunneled TPU); caching compiled
    executables on disk makes every later runtime (or process) building
    the same query shape start warm.  The directory is keyed by backend
    platform — artifacts AOT-compiled under one backend's flag set must
    not load under another's.  Set SIDDHI_JAX_CACHE=off to disable, or
    to a path to relocate (default ~/.cache/siddhi_tpu/jax-<platform>).
    Called lazily at SiddhiManager creation (the backend is decided by
    then)."""
    cache = _os.environ.get("SIDDHI_JAX_CACHE", "")
    if cache.lower() == "off":
        return
    try:
        import jax
        if jax.config.jax_compilation_cache_dir:
            return              # already configured (by us or the user)
        d = cache or _os.path.join(
            _os.path.expanduser("~"), ".cache", "siddhi_tpu",
            f"jax-{jax.default_backend()}")
        _os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
    except Exception:           # pragma: no cover - cache is best-effort
        pass

from .query import ast, parse, parse_expression, parse_query, parse_store_query
from .core.runtime import SiddhiAppRuntime, SiddhiManager
from .core.schema import StreamSchema
from .core.batch import EventBatch
from .core.io import (InMemoryBroker, Sink, Source, SinkMapper, SourceMapper,
                      register_sink_mapper, register_sink_type,
                      register_source_mapper, register_source_type)

__version__ = "0.2.0"

__all__ = [
    "SiddhiManager", "SiddhiAppRuntime", "StreamSchema", "EventBatch",
    "ast", "parse", "parse_query", "parse_store_query", "parse_expression",
    "InMemoryBroker", "Source", "Sink", "SourceMapper", "SinkMapper",
    "register_source_type", "register_sink_type",
    "register_source_mapper", "register_sink_mapper",
]
