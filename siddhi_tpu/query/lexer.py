"""Tokenizer for the SiddhiQL-compatible language.

Replaces the reference's ANTLR-generated lexer
(reference: modules/siddhi-query-compiler/src/main/antlr4/.../SiddhiQL.g4,
lexer rules near the bottom of the 918-line grammar).  Hand-rolled so the
framework has zero parser-generator dependencies.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional


class TokenType:
    IDENT = "IDENT"
    INT = "INT"          # 123
    LONG = "LONG"        # 123L / 123l
    FLOAT = "FLOAT"      # 1.2f
    DOUBLE = "DOUBLE"    # 1.2
    STRING = "STRING"
    OP = "OP"            # punctuation / operators
    EOF = "EOF"


@dataclass
class Token:
    type: str
    value: str
    pos: int
    line: int
    col: int

    def lower(self) -> str:
        return self.value.lower()


class LexError(Exception):
    pass


_TWO_CHAR_OPS = {"==", "!=", "<=", ">=", "->"}
_ONE_CHAR_OPS = set("()[]{}<>,.;:*/+-%=!@#?")


def tokenize(text: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(text)
    line, line_start = 1, 0

    def make(tt: str, val: str, start: int) -> Token:
        return Token(tt, val, start, line, start - line_start + 1)

    while i < n:
        c = text[i]
        # whitespace
        if c in " \t\r\n":
            if c == "\n":
                line += 1
                line_start = i + 1
            i += 1
            continue
        # comments: -- line, /* block */
        if c == "-" and i + 1 < n and text[i + 1] == "-":
            j = text.find("\n", i)
            i = n if j < 0 else j
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            if j < 0:
                raise LexError(f"unterminated block comment at line {line}")
            line += text.count("\n", i, j)
            i = j + 2
            continue
        # strings: '...' , "..." , """...""" (no escapes in SiddhiQL; '' not special)
        if c in "'\"":
            if c == '"' and text.startswith('"""', i):
                j = text.find('"""', i + 3)
                if j < 0:
                    raise LexError(f"unterminated triple-quoted string at line {line}")
                val = text[i + 3:j]
                toks.append(make(TokenType.STRING, val, i))
                line += text.count("\n", i, j)
                i = j + 3
                continue
            j = text.find(c, i + 1)
            if j < 0:
                raise LexError(f"unterminated string at line {line}")
            toks.append(make(TokenType.STRING, text[i + 1:j], i))
            line += text.count("\n", i, j)
            i = j + 1
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i
            is_float = False
            while j < n and (text[j].isdigit() or text[j] == "."):
                if text[j] == ".":
                    # ".." or ".ident" -> stop (attribute access like e1[0].p can't
                    # start with digit, but `1.0` is fine)
                    if j + 1 < n and not text[j + 1].isdigit():
                        break
                    is_float = True
                j += 1
            raw = text[i:j]
            if j < n and text[j] in "eE" and (j + 1 < n and (text[j + 1].isdigit() or text[j + 1] in "+-")):
                k = j + 2 if text[j + 1] in "+-" else j + 1
                while k < n and text[k].isdigit():
                    k += 1
                raw = text[i:k]
                j = k
                is_float = True
            if j < n and text[j] in "fF":
                toks.append(make(TokenType.FLOAT, raw, i))
                j += 1
            elif j < n and text[j] in "dD":
                toks.append(make(TokenType.DOUBLE, raw, i))
                j += 1
            elif j < n and text[j] in "lL":
                toks.append(make(TokenType.LONG, raw, i))
                j += 1
            elif is_float:
                toks.append(make(TokenType.DOUBLE, raw, i))
            else:
                toks.append(make(TokenType.INT, raw, i))
            i = j
            continue
        # identifiers / keywords (incl. `back-quoted`? SiddhiQL uses plain)
        if c.isalpha() or c == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            toks.append(make(TokenType.IDENT, text[i:j], i))
            i = j
            continue
        # operators
        if text[i:i + 2] in _TWO_CHAR_OPS:
            toks.append(make(TokenType.OP, text[i:i + 2], i))
            i += 2
            continue
        if c in _ONE_CHAR_OPS:
            toks.append(make(TokenType.OP, c, i))
            i += 1
            continue
        raise LexError(f"unexpected character {c!r} at line {line}")

    toks.append(Token(TokenType.EOF, "", n, line, 1))
    return toks
