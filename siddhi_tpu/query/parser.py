"""Recursive-descent parser: SiddhiQL text -> typed AST.

The TPU framework's analog of the reference's `siddhi-query-compiler`
(reference: SiddhiQL.g4 grammar — app structure :34-45, patterns :200-291,
sequences :291-340, query sections :360-415 — plus the 3,073-line
SiddhiQLBaseVisitorImpl.java AST builder).  One pass, no generated code.

Entry points mirror `SiddhiCompiler` (reference:
modules/siddhi-query-compiler/.../SiddhiCompiler.java:57-192):
  parse(text)              -> ast.SiddhiApp
  parse_query(text)        -> ast.Query
  parse_store_query(text)  -> ast.StoreQuery
  parse_expression(text)   -> ast.Expression
"""
from __future__ import annotations

from typing import Optional, Union

from . import ast
from .ast import AttrType, CompareOp, MathOp
from .lexer import Token, TokenType, tokenize


class ParseError(Exception):
    def __init__(self, msg: str, token: Optional[Token] = None):
        if token is not None:
            msg = f"{msg} (at line {token.line}:{token.col}, near {token.value!r})"
        super().__init__(msg)


_TIME_UNITS_MS = {
    "millisecond": 1, "milliseconds": 1, "millisec": 1, "ms": 1,
    "second": 1000, "seconds": 1000, "sec": 1000,
    "minute": 60_000, "minutes": 60_000, "min": 60_000,
    "hour": 3_600_000, "hours": 3_600_000,
    "day": 86_400_000, "days": 86_400_000,
    "week": 604_800_000, "weeks": 604_800_000,
    "month": 2_592_000_000, "months": 2_592_000_000,
    "year": 31_536_000_000, "years": 31_536_000_000,
}

_DURATIONS = {
    "sec": ast.Duration.SECONDS, "second": ast.Duration.SECONDS, "seconds": ast.Duration.SECONDS,
    "min": ast.Duration.MINUTES, "minute": ast.Duration.MINUTES, "minutes": ast.Duration.MINUTES,
    "hour": ast.Duration.HOURS, "hours": ast.Duration.HOURS,
    "day": ast.Duration.DAYS, "days": ast.Duration.DAYS,
    "week": ast.Duration.WEEKS, "weeks": ast.Duration.WEEKS,
    "month": ast.Duration.MONTHS, "months": ast.Duration.MONTHS,
    "year": ast.Duration.YEARS, "years": ast.Duration.YEARS,
}

_ATTR_TYPES = {
    "string": AttrType.STRING, "int": AttrType.INT, "long": AttrType.LONG,
    "float": AttrType.FLOAT, "double": AttrType.DOUBLE, "bool": AttrType.BOOL,
    "object": AttrType.OBJECT,
}

class Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = tokenize(text)
        self.i = 0

    # -- token helpers ------------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.type != TokenType.EOF:
            self.i += 1
        return t

    def at_kw(self, *kws: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.type == TokenType.IDENT and t.lower() in kws

    def at_op(self, *ops: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.type == TokenType.OP and t.value in ops

    def eat_kw(self, *kws: str) -> Token:
        if not self.at_kw(*kws):
            raise ParseError(f"expected {'/'.join(kws)}", self.peek())
        return self.next()

    def eat_op(self, op: str) -> Token:
        if not self.at_op(op):
            raise ParseError(f"expected {op!r}", self.peek())
        return self.next()

    def try_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def try_op(self, op: str) -> bool:
        if self.at_op(op):
            self.next()
            return True
        return False

    def ident(self) -> str:
        t = self.peek()
        if t.type != TokenType.IDENT:
            raise ParseError("expected identifier", t)
        self.next()
        return t.value

    # -- app ----------------------------------------------------------------

    def parse_app(self) -> ast.SiddhiApp:
        app_annotations: list[ast.Annotation] = []
        streams: dict = {}
        tables: dict = {}
        windows: dict = {}
        triggers: dict = {}
        functions: dict = {}
        aggregations: dict = {}
        elements: list = []

        while self.peek().type != TokenType.EOF:
            annotations = self.parse_annotations()
            # @app:* annotations always belong to the app, wherever they appear
            app_annotations.extend(a for a in annotations if a.name.startswith("app:"))
            annotations = [a for a in annotations if not a.name.startswith("app:")]
            t = self.peek()
            if t.type == TokenType.EOF:
                app_annotations.extend(annotations)
                break
            if self.at_kw("define"):
                d = self.parse_definition(tuple(annotations))
                if isinstance(d, ast.StreamDefinition):
                    streams[d.id] = d
                elif isinstance(d, ast.TableDefinition):
                    tables[d.id] = d
                elif isinstance(d, ast.WindowDefinition):
                    windows[d.id] = d
                elif isinstance(d, ast.TriggerDefinition):
                    triggers[d.id] = d
                    # triggers implicitly define a stream (triggered_time long)
                    streams.setdefault(d.id, ast.StreamDefinition(
                        d.id, (ast.Attribute("triggered_time", AttrType.LONG),)))
                elif isinstance(d, ast.FunctionDefinition):
                    functions[d.id] = d
                elif isinstance(d, ast.AggregationDefinition):
                    aggregations[d.id] = d
            elif self.at_kw("partition"):
                elements.append(self.parse_partition(tuple(annotations)))
            elif self.at_kw("from"):
                elements.append(self.parse_query_body(tuple(annotations)))
            else:
                # bare app-level annotations appear before any element
                if annotations:
                    app_annotations.extend(annotations)
                    continue
                raise ParseError("expected define/partition/from", t)
            self.try_op(";")

        # split app-level annotations: those that came before the first element
        # but apply to the app (@app:*) vs stray ones.
        return ast.SiddhiApp(
            annotations=tuple(app_annotations),
            stream_definitions=streams,
            table_definitions=tables,
            window_definitions=windows,
            trigger_definitions=triggers,
            function_definitions=functions,
            aggregation_definitions=aggregations,
            execution_elements=tuple(elements),
        )

    def parse_annotations(self) -> list[ast.Annotation]:
        """Annotations preceding an element; @app:* are collected too.

        A trailing annotation list followed by `define`/`from`/`partition`
        belongs to that element; `@app:...` ones belong to the app but we
        return them all — parse_app sorts out placement.
        """
        anns = []
        while self.at_op("@"):
            anns.append(self.parse_annotation())
        # @app:xxx annotations apply to the app; return all, caller decides
        return anns

    def parse_annotation(self) -> ast.Annotation:
        self.eat_op("@")
        name = self.ident()
        if self.try_op(":"):
            name = f"{name}:{self.ident()}"
        elements: list = []
        nested: list = []
        if self.try_op("("):
            if not self.at_op(")"):
                while True:
                    if self.at_op("@"):
                        nested.append(self.parse_annotation())
                    else:
                        t = self.peek()
                        if t.type == TokenType.IDENT and (
                                self.at_op("=", ahead=1)
                                or self.at_op(".", ahead=1)):
                            # dotted keys: @app:async(batch.size.max='4')
                            key = self.ident()
                            while self.try_op("."):
                                key += "." + self.ident()
                            self.eat_op("=")
                            elements.append((key, self._annotation_value()))
                        else:
                            elements.append((None, self._annotation_value()))
                    if not self.try_op(","):
                        break
            self.eat_op(")")
        return ast.Annotation(name.lower(), tuple(elements), tuple(nested))

    def _annotation_value(self) -> str:
        t = self.next()
        if t.type in (TokenType.STRING, TokenType.IDENT, TokenType.INT,
                      TokenType.LONG, TokenType.DOUBLE, TokenType.FLOAT):
            return t.value
        if t.type == TokenType.OP and t.value == "-":
            n = self.next()
            return "-" + n.value
        raise ParseError("expected annotation value", t)

    # -- definitions --------------------------------------------------------

    def parse_definition(self, annotations) -> ast.Definition:
        self.eat_kw("define")
        kind = self.ident().lower()
        if kind == "stream":
            name = self.ident()
            attrs = self.parse_attr_list()
            return ast.StreamDefinition(name, attrs, annotations)
        if kind == "table":
            name = self.ident()
            attrs = self.parse_attr_list()
            return ast.TableDefinition(name, attrs, annotations)
        if kind == "window":
            name = self.ident()
            attrs = self.parse_attr_list()
            # window spec: `length(5)` or `time(1 sec)` — optionally ns:name
            wname = self.ident()
            ns = None
            if self.try_op(":"):
                ns, wname = wname, self.ident()
            args = self.parse_call_args()
            out = ast.OutputEventsFor.ALL
            if self.try_kw("output"):
                out = self.parse_events_for()
            return ast.WindowDefinition(name, attrs, ast.WindowHandler(wname, args, ns),
                                        out, annotations)
        if kind == "trigger":
            name = self.ident()
            self.eat_kw("at")
            if self.try_kw("every"):
                millis = self.parse_time_value()
                return ast.TriggerDefinition(name, at_every_millis=millis,
                                             annotations=annotations)
            t = self.next()
            if t.type != TokenType.STRING:
                raise ParseError("expected 'start' or cron string after at", t)
            if t.value == "start":
                return ast.TriggerDefinition(name, at_start=True, annotations=annotations)
            return ast.TriggerDefinition(name, at_cron=t.value, annotations=annotations)
        if kind == "function":
            name = self.ident()
            self.eat_op("[")
            lang = self.ident()
            self.eat_op("]")
            self.eat_kw("return")
            rt = self._attr_type(self.ident())
            body = self._raw_braced_block()
            return ast.FunctionDefinition(name, lang, rt, body, annotations)
        if kind == "aggregation":
            return self.parse_aggregation_def(annotations)
        raise ParseError(f"unknown definition kind {kind!r}", self.peek())

    def _attr_type(self, name: str) -> AttrType:
        try:
            return _ATTR_TYPES[name.lower()]
        except KeyError:
            raise ParseError(f"unknown attribute type {name!r}", self.peek()) from None

    def parse_attr_list(self) -> tuple[ast.Attribute, ...]:
        self.eat_op("(")
        attrs = []
        while True:
            aname = self.ident()
            attrs.append(ast.Attribute(aname, self._attr_type(self.ident())))
            if not self.try_op(","):
                break
        self.eat_op(")")
        return tuple(attrs)

    def _raw_braced_block(self) -> str:
        start_tok = self.eat_op("{")
        # raw scan in source text from this position, balancing braces
        depth = 1
        j = start_tok.pos + 1
        while j < len(self.text) and depth:
            if self.text[j] == "{":
                depth += 1
            elif self.text[j] == "}":
                depth -= 1
            j += 1
        if depth:
            raise ParseError("unterminated { } block", start_tok)
        body = self.text[start_tok.pos + 1:j - 1]
        # resync token stream past j
        while self.peek().type != TokenType.EOF and self.peek().pos < j:
            self.next()
        return body

    def parse_aggregation_def(self, annotations) -> ast.AggregationDefinition:
        name = self.ident()
        self.eat_kw("from")
        inp = self.parse_single_input_stream()
        selector = self.parse_selector_block()
        by = None
        if self.try_kw("aggregate"):
            if self.try_kw("by"):
                by = self._parse_variable_ref()
            self.eat_kw("every")
        else:
            self.eat_kw("every")
        durations = [self.parse_duration()]
        if self.at_op("."):
            # range: `sec ... year`
            self.eat_op(".")
            self.eat_op(".")
            self.eat_op(".")
            last = self.parse_duration()
            o = ast.DURATION_ORDER
            durations = o[o.index(durations[0]): o.index(last) + 1]
        else:
            while self.try_op(","):
                durations.append(self.parse_duration())
        return ast.AggregationDefinition(name, inp, selector, by,
                                         tuple(durations), annotations)

    def parse_duration(self) -> ast.Duration:
        t = self.ident().lower()
        if t not in _DURATIONS:
            raise ParseError(f"unknown duration {t!r}", self.peek())
        return _DURATIONS[t]

    # -- queries ------------------------------------------------------------

    def parse_query_body(self, annotations) -> ast.Query:
        self.eat_kw("from")
        input_stream = self.parse_input_stream()
        selector = self.parse_selector_block()
        rate = self.parse_output_rate()
        output = self.parse_output_action()
        return ast.Query(input_stream, selector, output, rate, annotations)

    # -- input streams ------------------------------------------------------

    def parse_input_stream(self) -> ast.InputStream:
        # Decide: pattern/sequence vs join vs single.
        # Patterns start with `every`, `not`, `(`, or `ref=`; sequences are
        # pattern-like but use ',' chaining.  A plain stream id followed by
        # `join`/`left`/`right`/`full`/`inner`/`unidirectional` is a join.
        if (self.at_kw("every", "not")
                or self.at_op("(")
                or (self.peek().type == TokenType.IDENT and self.at_op("=", ahead=1))):
            return self.parse_state_stream()
        save = self.i
        first = self.parse_single_input_stream()
        if self.at_kw("join", "left", "right", "full", "inner", "unidirectional"):
            return self.parse_join_tail(first)
        if self.at_op("->") or self.at_op(","):
            # pattern/sequence whose first element had no ref (rare but legal)
            self.i = save
            return self.parse_state_stream()
        return first

    def parse_single_input_stream(self) -> ast.SingleInputStream:
        is_inner = bool(self.try_op("#"))
        is_fault = bool(self.try_op("!"))
        sid = self.ident()
        handlers: list[ast.StreamHandler] = []
        handlers.extend(self.parse_stream_handlers())
        ref = None
        if self.try_kw("as"):
            ref = self.ident()
        # `unidirectional` handled by join parser
        return ast.SingleInputStream(sid, ref, tuple(handlers), is_inner, is_fault)

    def parse_stream_handlers(self) -> list[ast.StreamHandler]:
        handlers: list[ast.StreamHandler] = []
        while True:
            if self.at_op("["):
                self.eat_op("[")
                handlers.append(ast.Filter(self.parse_expression()))
                self.eat_op("]")
            elif self.at_op("#"):
                self.eat_op("#")
                name = self.ident()
                ns = None
                if self.try_op(":"):
                    ns, name = name, self.ident()
                if ns is None and name.lower() == "window":
                    self.eat_op(".")
                    wname = self.ident()
                    wns = None
                    if self.try_op(":"):
                        wns, wname = wname, self.ident()
                    args = self.parse_call_args()
                    handlers.append(ast.WindowHandler(wname, args, wns))
                else:
                    args = self.parse_call_args()
                    handlers.append(ast.StreamFunction(name, args, ns))
            else:
                return handlers

    def parse_call_args(self) -> tuple[ast.Expression, ...]:
        if not self.try_op("("):
            return ()
        args = []
        if not self.at_op(")"):
            while True:
                args.append(self.parse_expression())
                if not self.try_op(","):
                    break
        self.eat_op(")")
        return tuple(args)

    # -- joins ---------------------------------------------------------------

    def parse_join_tail(self, left: ast.SingleInputStream) -> ast.JoinInputStream:
        trigger = "all"
        if self.try_kw("unidirectional"):
            trigger = "left"
        jt = ast.JoinType.INNER
        if self.try_kw("left"):
            self.eat_kw("outer")
            self.eat_kw("join")
            jt = ast.JoinType.LEFT_OUTER
        elif self.try_kw("right"):
            self.eat_kw("outer")
            self.eat_kw("join")
            jt = ast.JoinType.RIGHT_OUTER
        elif self.try_kw("full"):
            self.eat_kw("outer")
            self.eat_kw("join")
            jt = ast.JoinType.FULL_OUTER
        elif self.try_kw("inner"):
            self.eat_kw("join")
        else:
            self.eat_kw("join")
        right = self.parse_single_input_stream()
        if self.try_kw("unidirectional"):
            trigger = "right" if trigger == "all" else trigger
        on = None
        if self.try_kw("on"):
            on = self.parse_expression()
        within = None
        per = None
        if self.try_kw("within"):
            within = self.parse_within_value()
        if self.try_kw("per"):
            per = self.parse_expression()
        return ast.JoinInputStream(left, right, jt, on, within, per, trigger)

    def parse_within_value(self):
        # aggregation-join within accepts expressions (timestamps / strings),
        # possibly `within a, b`
        first = self._time_or_expr()
        if self.try_op(","):
            second = self._time_or_expr()
            return ast.FunctionCall("withinRange", (first, second))
        return first

    def _time_or_expr(self):
        if self.peek().type in (TokenType.INT, TokenType.LONG) and \
                self.peek(1).type == TokenType.IDENT and self.peek(1).lower() in _TIME_UNITS_MS:
            return ast.TimeConstant(self.parse_time_value())
        return self.parse_expression()

    # -- patterns / sequences -----------------------------------------------

    def parse_state_stream(self) -> ast.StateInputStream:
        elem, is_seq = self.parse_state_chain()
        within = None
        if self.try_kw("within"):
            within = ast.TimeConstant(self.parse_time_value())
        st = ast.StateType.SEQUENCE if is_seq else ast.StateType.PATTERN
        return ast.StateInputStream(st, elem, within)

    def parse_state_chain(self) -> tuple[ast.StateElement, bool]:
        """Parse `a -> b -> c` or `a, b, c`; returns (element, is_sequence)."""
        first = self.parse_state_unit()
        is_seq = False
        elems = [first]
        while True:
            if self.try_op("->"):
                elems.append(self.parse_state_unit())
            elif self.at_op(",") and self._comma_starts_state():
                self.eat_op(",")
                elems.append(self.parse_state_unit())
                is_seq = True
            else:
                break
        elem = elems[-1]
        for prev in reversed(elems[:-1]):
            elem = ast.NextStateElement(prev, elem)
        return elem, is_seq

    def _comma_starts_state(self) -> bool:
        """After a comma, does a new sequence element start? (vs select list etc.)"""
        t = self.peek(1)
        if t.type != TokenType.IDENT:
            return t.type == TokenType.OP and t.value == "("
        if t.lower() in ("every", "not"):
            return True
        t2 = self.peek(2)
        return t2.type == TokenType.OP and t2.value in ("=", "[", "+", "*", "?")

    def parse_state_unit(self) -> ast.StateElement:
        if self.try_kw("every"):
            if self.try_op("("):
                inner, _ = self.parse_state_chain()
                self.eat_op(")")
                within = None
                if self.try_kw("within"):
                    within = ast.TimeConstant(self.parse_time_value())
                return ast.EveryStateElement(inner, within)
            inner = self.parse_state_source()
            return ast.EveryStateElement(inner)
        if self.try_op("("):
            inner, _ = self.parse_state_chain()
            self.eat_op(")")
            within = None
            if self.try_kw("within"):
                within = ast.TimeConstant(self.parse_time_value())
            if within is not None:
                inner = _attach_within(inner, within)
            return inner
        return self.parse_state_source()

    def parse_state_source(self) -> ast.StateElement:
        """One pattern source: absent / logical / counting / plain."""
        if self.try_kw("not"):
            stream = self.parse_basic_state_stream()
            if self.try_kw("and"):
                right = self.parse_basic_state_stream()
                return ast.LogicalStateElement(
                    ast.AbsentStreamStateElement(stream),
                    "and", ast.StreamStateElement(right))
            self.eat_kw("for")
            wait = ast.TimeConstant(self.parse_time_value())
            absent = ast.AbsentStreamStateElement(stream, waiting_time=wait)
            # `not X for T and|or Y` — a timed absent as a logical side
            # (reference grammar: every_absent_logical_source)
            for op in ("and", "or"):
                if self.try_kw(op):
                    right = self.parse_basic_state_stream()
                    return ast.LogicalStateElement(
                        absent, op, ast.StreamStateElement(right))
            return absent
        stream = self.parse_basic_state_stream()
        # count: e1=S[...]<2:5>
        if self.at_op("<"):
            save = self.i
            self.eat_op("<")
            mn, mx = self._parse_collect()
            if mn is not None or mx is not None:
                self.eat_op(">")
                return ast.CountStateElement(
                    ast.StreamStateElement(stream),
                    mn if mn is not None else 1,
                    mx if mx is not None else ast.CountStateElement.ANY)
            self.i = save
        # sequence postfix +, *, ?
        if self.at_op("+"):
            self.eat_op("+")
            return ast.CountStateElement(ast.StreamStateElement(stream), 1,
                                         ast.CountStateElement.ANY)
        if self.at_op("*"):
            self.eat_op("*")
            return ast.CountStateElement(ast.StreamStateElement(stream), 0,
                                         ast.CountStateElement.ANY)
        if self.at_op("?"):
            self.eat_op("?")
            return ast.CountStateElement(ast.StreamStateElement(stream), 0, 1)
        for op in ("and", "or"):
            if self.try_kw(op):
                if self.try_kw("not"):
                    right = self.parse_basic_state_stream()
                    wait = None
                    if self.try_kw("for"):      # `Y and|or not X for T`
                        wait = ast.TimeConstant(self.parse_time_value())
                    return ast.LogicalStateElement(
                        ast.StreamStateElement(stream), op,
                        ast.AbsentStreamStateElement(right,
                                                     waiting_time=wait))
                right = self.parse_basic_state_stream()
                return ast.LogicalStateElement(
                    ast.StreamStateElement(stream), op,
                    ast.StreamStateElement(right))
        return ast.StreamStateElement(stream)

    def _parse_collect(self) -> tuple[Optional[int], Optional[int]]:
        """`<2:5>` `<2:>` `<:5>` `<3>` — returns (min, max); (None, None) if not a collect."""
        mn = mx = None
        if self.peek().type == TokenType.INT:
            mn = int(self.next().value)
            if self.try_op(":"):
                if self.peek().type == TokenType.INT:
                    mx = int(self.next().value)
            else:
                mx = mn
        elif self.at_op(":"):
            self.eat_op(":")
            if self.peek().type == TokenType.INT:
                mx = int(self.next().value)
                mn = 0
        return mn, mx

    def parse_basic_state_stream(self) -> ast.SingleInputStream:
        """`e1=Stream[filter]#fn(...)` — ref optional, no windows allowed."""
        ref = None
        if self.peek().type == TokenType.IDENT and self.at_op("=", ahead=1):
            ref = self.ident()
            self.eat_op("=")
        sid = self.ident()
        handlers = self.parse_stream_handlers()
        for h in handlers:
            if isinstance(h, ast.WindowHandler):
                raise ParseError("windows are not allowed inside pattern/sequence sources")
        return ast.SingleInputStream(sid, ref, tuple(handlers))

    # -- selector -----------------------------------------------------------

    def parse_selector_block(self) -> ast.Selector:
        select_all = False
        attributes: list[ast.OutputAttribute] = []
        if self.try_kw("select"):
            if self.try_op("*"):
                select_all = True
            else:
                while True:
                    expr = self.parse_expression()
                    rename = None
                    if self.try_kw("as"):
                        rename = self.ident()
                    attributes.append(ast.OutputAttribute(expr, rename))
                    if not self.try_op(","):
                        break
        else:
            select_all = True
        group_by: list[ast.Variable] = []
        if self.at_kw("group"):
            self.eat_kw("group")
            self.eat_kw("by")
            while True:
                group_by.append(self._parse_variable_ref())
                if not self.try_op(","):
                    break
        having = None
        if self.try_kw("having"):
            having = self.parse_expression()
        order_by: list[ast.OrderByAttribute] = []
        if self.at_kw("order"):
            self.eat_kw("order")
            self.eat_kw("by")
            while True:
                v = self._parse_variable_ref()
                d = ast.OrderDir.ASC
                if self.try_kw("asc"):
                    pass
                elif self.try_kw("desc"):
                    d = ast.OrderDir.DESC
                order_by.append(ast.OrderByAttribute(v, d))
                if not self.try_op(","):
                    break
        limit = offset = None
        if self.try_kw("limit"):
            limit = int(self.next().value)
        if self.try_kw("offset"):
            offset = int(self.next().value)
        return ast.Selector(select_all, tuple(attributes), tuple(group_by),
                            having, tuple(order_by), limit, offset)

    def _parse_variable_ref(self) -> ast.Variable:
        name = self.ident()
        if self.try_op("."):
            return ast.Variable(self.ident(), stream_ref=name)
        return ast.Variable(name)

    # -- output rate & action ------------------------------------------------

    def parse_output_rate(self) -> ast.OutputRate:
        if not self.at_kw("output"):
            return None
        # `output` may also start `output snapshot every..` — or the action
        # keyword sequence for window definitions is handled elsewhere.
        save = self.i
        self.eat_kw("output")
        rtype = ast.RateType.ALL
        if self.try_kw("snapshot"):
            self.eat_kw("every")
            return ast.SnapshotOutputRate(self.parse_time_value())
        if self.try_kw("first"):
            rtype = ast.RateType.FIRST
        elif self.try_kw("last"):
            rtype = ast.RateType.LAST
        elif self.try_kw("all"):
            rtype = ast.RateType.ALL
        if not self.try_kw("every"):
            self.i = save
            return None
        if self.peek().type in (TokenType.INT, TokenType.LONG):
            val = int(self.next().value)
            if self.at_kw("events"):
                self.eat_kw("events")
                return ast.EventOutputRate(val, rtype)
            unit = self.ident().lower()
            if unit not in _TIME_UNITS_MS:
                raise ParseError(f"expected time unit or 'events', got {unit!r}")
            ms = val * _TIME_UNITS_MS[unit]
            # allow compound `1 min 30 sec`
            while self.peek().type in (TokenType.INT, TokenType.LONG) and \
                    self.peek(1).type == TokenType.IDENT and self.peek(1).lower() in _TIME_UNITS_MS:
                v2 = int(self.next().value)
                ms += v2 * _TIME_UNITS_MS[self.ident().lower()]
            return ast.TimeOutputRate(ms, rtype)
        raise ParseError("expected count or time after 'every'", self.peek())

    def parse_events_for(self) -> ast.OutputEventsFor:
        if self.try_kw("current"):
            self.eat_kw("events")
            return ast.OutputEventsFor.CURRENT
        if self.try_kw("expired"):
            self.eat_kw("events")
            return ast.OutputEventsFor.EXPIRED
        if self.try_kw("all"):
            self.eat_kw("events")
            return ast.OutputEventsFor.ALL
        self.eat_kw("events")
        return ast.OutputEventsFor.CURRENT

    def parse_output_action(self) -> ast.OutputStreamAction:
        if self.try_kw("insert"):
            ef = ast.OutputEventsFor.CURRENT
            if self.at_kw("current", "expired", "all"):
                ef = self.parse_events_for()
            if self.try_kw("overwrite"):   # legacy `insert overwrite` -> update or insert
                self.eat_kw("into")
                target, is_fault, is_inner = self._output_target()
                on = None
                if self.try_kw("on"):
                    on = self.parse_expression()
                return ast.UpdateOrInsertTable(target, on or ast.Constant(True, AttrType.BOOL))
            self.eat_kw("into")
            target, is_fault, is_inner = self._output_target()
            return ast.InsertInto(target, ef, is_fault, is_inner)
        if self.try_kw("delete"):
            target, _, _ = self._output_target()
            ef = ast.OutputEventsFor.CURRENT
            if self.try_kw("for"):
                ef = self.parse_events_for()
            self.eat_kw("on")
            return ast.DeleteFrom(target, self.parse_expression(), ef)
        if self.try_kw("update"):
            if self.try_kw("or"):
                self.eat_kw("insert")
                self.eat_kw("into")
                target, _, _ = self._output_target()
                sets = self._parse_set_clauses()
                self.eat_kw("on")
                return ast.UpdateOrInsertTable(target, self.parse_expression(), sets)
            target, _, _ = self._output_target()
            ef = ast.OutputEventsFor.CURRENT
            if self.try_kw("for"):
                ef = self.parse_events_for()
            sets = self._parse_set_clauses()
            self.eat_kw("on")
            return ast.UpdateTable(target, self.parse_expression(), sets, ef)
        if self.try_kw("return"):
            ef = ast.OutputEventsFor.CURRENT
            if self.at_kw("current", "expired", "all"):
                ef = self.parse_events_for()
            return ast.ReturnAction(ef)
        raise ParseError("expected insert/delete/update/return", self.peek())

    def _output_target(self) -> tuple[str, bool, bool]:
        is_inner = bool(self.try_op("#"))
        is_fault = bool(self.try_op("!"))
        return self.ident(), is_fault, is_inner

    def _parse_set_clauses(self) -> tuple[ast.UpdateSetClause, ...]:
        if not self.try_kw("set"):
            return ()
        sets = []
        while True:
            var = self._parse_variable_ref()
            self.eat_op("=")
            sets.append(ast.UpdateSetClause(var, self.parse_expression()))
            if not self.try_op(","):
                break
        return tuple(sets)

    # -- partitions ----------------------------------------------------------

    def parse_partition(self, annotations) -> ast.Partition:
        self.eat_kw("partition")
        self.eat_kw("with")
        self.eat_op("(")
        keys = []
        while True:
            keys.append(self.parse_partition_key())
            if not self.try_op(","):
                break
        self.eat_op(")")
        self.eat_kw("begin")
        queries = []
        while not self.at_kw("end"):
            q_anns = self.parse_annotations()
            queries.append(self.parse_query_body(tuple(q_anns)))
            self.try_op(";")
        self.eat_kw("end")
        return ast.Partition(tuple(keys), tuple(queries), annotations)

    def parse_partition_key(self) -> ast.PartitionKey:
        expr = self.parse_expression()
        if self.try_kw("as"):
            # range partition: cond as 'label' [or cond as 'label']... of Stream
            t = self.next()
            ranges = [ast.RangePartitionCase(expr, t.value)]
            while self.try_kw("or"):
                cond = self.parse_expression()
                self.eat_kw("as")
                t = self.next()
                ranges.append(ast.RangePartitionCase(cond, t.value))
            self.eat_kw("of")
            sid = self.ident()
            return ast.PartitionKey(sid, None, tuple(ranges))
        self.eat_kw("of")
        sid = self.ident()
        return ast.PartitionKey(sid, expr)

    # -- store queries -------------------------------------------------------

    def parse_store_query(self) -> ast.StoreQuery:
        if self.try_kw("select"):
            # `select ... insert into T` without from — unsupported; rewind
            raise ParseError("store query must start with from", self.peek())
        self.eat_kw("from")
        is_inner = bool(self.try_op("#"))
        sid = self.ident()
        handlers = []
        within = per = None
        if self.try_kw("on"):
            handlers.append(ast.Filter(self.parse_expression()))
        if self.try_kw("within"):
            within = self.parse_within_value()
        if self.try_kw("per"):
            per = self.parse_expression()
        inp = ast.SingleInputStream(sid, None, tuple(handlers), is_inner)
        selector = self.parse_selector_block()
        action: Optional[ast.OutputStreamAction] = None
        if self.at_kw("insert", "update", "delete", "return"):
            action = self.parse_output_action()
        return ast.StoreQuery(inp, selector, action, within, per)

    # -- time ----------------------------------------------------------------

    def parse_time_value(self) -> int:
        total = 0
        seen = False
        while self.peek().type in (TokenType.INT, TokenType.LONG):
            val = int(self.next().value)
            unit = self.ident().lower()
            if unit not in _TIME_UNITS_MS:
                raise ParseError(f"unknown time unit {unit!r}", self.peek())
            total += val * _TIME_UNITS_MS[unit]
            seen = True
        if not seen:
            raise ParseError("expected time value", self.peek())
        return total

    # -- expressions ---------------------------------------------------------

    def parse_expression(self) -> ast.Expression:
        return self.parse_or()

    def parse_or(self) -> ast.Expression:
        left = self.parse_and()
        while self.at_kw("or"):
            # `or` inside partition-range / pattern contexts stops at `as`/`of`
            if self.at_kw("as", ahead=1):
                break
            self.eat_kw("or")
            left = ast.Or(left, self.parse_and())
        return left

    def parse_and(self) -> ast.Expression:
        left = self.parse_not()
        while self.try_kw("and"):
            left = ast.And(left, self.parse_not())
        return left

    def parse_not(self) -> ast.Expression:
        if self.try_kw("not"):
            return ast.Not(self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> ast.Expression:
        left = self.parse_additive()
        while True:
            if self.at_op("==") or self.at_op("!=") or self.at_op("<=") or \
                    self.at_op(">=") or self.at_op("<") or self.at_op(">"):
                op = self.next().value
                right = self.parse_additive()
                left = ast.Compare(left, CompareOp(op), right)
            elif self.at_kw("is") and self.at_kw("null", ahead=1):
                self.next()
                self.next()
                if isinstance(left, ast.Variable) and left.attribute is None:
                    left = ast.IsNull(stream_ref=left.stream_ref, index=left.index)
                else:
                    left = ast.IsNull(expr=left)
            elif self.at_kw("in") and not self.at_kw("insert", ahead=0):
                self.eat_kw("in")
                left = ast.In(left, self.ident())
            else:
                return left

    def parse_additive(self) -> ast.Expression:
        left = self.parse_multiplicative()
        while self.at_op("+") or self.at_op("-"):
            op = self.next().value
            right = self.parse_multiplicative()
            left = ast.Math(left, MathOp(op), right)
        return left

    def parse_multiplicative(self) -> ast.Expression:
        left = self.parse_unary()
        while self.at_op("*") or self.at_op("/") or self.at_op("%"):
            op = self.next().value
            right = self.parse_unary()
            left = ast.Math(left, MathOp(op), right)
        return left

    def parse_unary(self) -> ast.Expression:
        if self.at_op("-"):
            self.eat_op("-")
            inner = self.parse_unary()
            if isinstance(inner, ast.Constant) and inner.type in (
                    AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE):
                return ast.Constant(-inner.value, inner.type)
            return ast.Math(ast.Constant(0, AttrType.INT), MathOp.SUB, inner)
        if self.at_op("+"):
            self.eat_op("+")
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> ast.Expression:
        t = self.peek()
        if self.try_op("("):
            e = self.parse_expression()
            self.eat_op(")")
            return e
        if t.type == TokenType.STRING:
            self.next()
            return ast.Constant(t.value, AttrType.STRING)
        if t.type == TokenType.INT:
            self.next()
            # time constant: INT unit
            if self.peek().type == TokenType.IDENT and self.peek().lower() in _TIME_UNITS_MS \
                    and not self.at_op("(", ahead=1) and not self.at_op(".", ahead=1):
                total = int(t.value) * _TIME_UNITS_MS[self.ident().lower()]
                while self.peek().type == TokenType.INT and \
                        self.peek(1).type == TokenType.IDENT and self.peek(1).lower() in _TIME_UNITS_MS:
                    v = int(self.next().value)
                    total += v * _TIME_UNITS_MS[self.ident().lower()]
                return ast.TimeConstant(total)
            return ast.Constant(int(t.value), AttrType.INT)
        if t.type == TokenType.LONG:
            self.next()
            return ast.Constant(int(t.value), AttrType.LONG)
        if t.type == TokenType.FLOAT:
            self.next()
            return ast.Constant(float(t.value), AttrType.FLOAT)
        if t.type == TokenType.DOUBLE:
            self.next()
            return ast.Constant(float(t.value), AttrType.DOUBLE)
        if t.type == TokenType.IDENT:
            low = t.lower()
            if low == "true":
                self.next()
                return ast.Constant(True, AttrType.BOOL)
            if low == "false":
                self.next()
                return ast.Constant(False, AttrType.BOOL)
            return self.parse_name_expression()
        raise ParseError("expected expression", t)

    def parse_name_expression(self) -> ast.Expression:
        """ident-led expression: variable, dotted variable, function call,
        ns:function, e1[0].attr, stream-ref for `is null`."""
        name = self.ident()
        # ns:function(...)
        if self.at_op(":") and self.peek(1).type == TokenType.IDENT and \
                self.at_op("(", ahead=2):
            self.eat_op(":")
            fname = self.ident()
            args = self.parse_call_args()
            return ast.FunctionCall(fname, args, namespace=name)
        if self.at_op("("):
            args = self.parse_call_args()
            return ast.FunctionCall(name, args)
        index = None
        if self.at_op("["):
            # e1[0].attr or e1[last].attr
            save = self.i
            self.eat_op("[")
            if self.peek().type == TokenType.INT and self.at_op("]", ahead=1):
                index = int(self.next().value)
                self.eat_op("]")
            elif self.at_kw("last") and self.at_op("]", ahead=1):
                self.next()
                index = "last"
                self.eat_op("]")
            elif self.at_kw("last") and self.at_op("-", ahead=1):
                self.next()
                self.eat_op("-")
                off = int(self.next().value)
                index = f"last-{off}"
                self.eat_op("]")
            else:
                self.i = save  # not an index — it's a filter bracket upstream
        if self.try_op("."):
            attr = self.ident()
            if self.at_op("("):
                # method-style f(x).y() not supported
                raise ParseError("method call syntax not supported", self.peek())
            return ast.Variable(attr, stream_ref=name, index=index)
        if index is not None:
            return ast.Variable(None, stream_ref=name, index=index)  # e1[0] is null
        return ast.Variable(name)


def _attach_within(elem: ast.StateElement, within: ast.TimeConstant) -> ast.StateElement:
    import dataclasses as dc
    return dc.replace(elem, within=within)


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def parse(text: str) -> ast.SiddhiApp:
    return Parser(text).parse_app()


def parse_query(text: str) -> ast.Query:
    p = Parser(text)
    anns = p.parse_annotations()
    q = p.parse_query_body(tuple(anns))
    p.try_op(";")
    if p.peek().type != TokenType.EOF:
        raise ParseError("trailing input after query", p.peek())
    return q


def parse_store_query(text: str) -> ast.StoreQuery:
    p = Parser(text)
    sq = p.parse_store_query()
    p.try_op(";")
    if p.peek().type != TokenType.EOF:
        raise ParseError("trailing input after store query", p.peek())
    return sq


def parse_expression(text: str) -> ast.Expression:
    p = Parser(text)
    e = p.parse_expression()
    if p.peek().type != TokenType.EOF:
        raise ParseError("trailing input after expression", p.peek())
    return e


def parse_time(text: str) -> int:
    return Parser(text).parse_time_value()
