"""Typed AST / object model for the SiddhiQL-compatible query language.

This is the TPU framework's analog of the reference's `siddhi-query-api`
module (reference: modules/siddhi-query-api/.../definition/*.java,
execution/query/Query.java, expression/*.java).  Unlike the reference's
mutable POJOs + fluent builder, the AST here is plain frozen dataclasses:
the compiler consumes it immutably and lowering is purely functional.
"""
from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Any, Optional, Union


# ---------------------------------------------------------------------------
# Attribute types (reference: query-api definition/Attribute.java:105)
# ---------------------------------------------------------------------------

class AttrType(enum.Enum):
    STRING = "string"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    BOOL = "bool"
    OBJECT = "object"

    @property
    def is_numeric(self) -> bool:
        return self in (AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE)


@dataclass(frozen=True)
class Attribute:
    name: str
    type: AttrType


# ---------------------------------------------------------------------------
# Annotations  (reference: query-api annotation/Annotation.java)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Annotation:
    """``@name(key='value', 'indexed value', ...)`` — also nested annotations."""
    name: str                                   # lowercase, e.g. "app:name", "async"
    elements: tuple[tuple[Optional[str], str], ...] = ()   # (key or None, value)
    annotations: tuple["Annotation", ...] = ()  # nested (e.g. @map inside @source)

    def element(self, key: Optional[str] = None, default: Optional[str] = None) -> Optional[str]:
        for k, v in self.elements:
            if k == key or (key is None and k is None):
                return v
        if key is not None:
            # a lone positional value answers any key miss: @app:name('X')
            pos = self.positional()
            if len(pos) == 1:
                return pos[0]
        return default

    def positional(self) -> list[str]:
        return [v for k, v in self.elements if k is None]


def find_annotation(annotations, name: str) -> Optional[Annotation]:
    for a in annotations:
        if a.name.lower() == name.lower():
            return a
    return None


# ---------------------------------------------------------------------------
# Expressions (reference: query-api expression/**)
# ---------------------------------------------------------------------------

class Expression:
    """Marker base class."""
    __slots__ = ()


@dataclass(frozen=True)
class Constant(Expression):
    value: Any
    type: AttrType


@dataclass(frozen=True)
class TimeConstant(Expression):
    """A time literal like ``1 sec`` — value always milliseconds."""
    millis: int


@dataclass(frozen=True)
class Variable(Expression):
    """``price`` / ``StockStream.price`` / ``e1.price`` / ``e1[2].price``."""
    attribute: str
    stream_ref: Optional[str] = None     # stream id or pattern state ref (e1)
    index: Optional[Union[int, str]] = None  # e1[0].x, e1[last].x


class CompareOp(enum.Enum):
    LT = "<"
    LE = "<="
    GT = ">"
    GE = ">="
    EQ = "=="
    NEQ = "!="


@dataclass(frozen=True)
class Compare(Expression):
    left: Expression
    op: CompareOp
    right: Expression


@dataclass(frozen=True)
class And(Expression):
    left: Expression
    right: Expression


@dataclass(frozen=True)
class Or(Expression):
    left: Expression
    right: Expression


@dataclass(frozen=True)
class Not(Expression):
    expr: Expression


@dataclass(frozen=True)
class IsNull(Expression):
    expr: Optional[Expression] = None
    stream_ref: Optional[str] = None     # `e1 is null` inside patterns
    index: Optional[Union[int, str]] = None


@dataclass(frozen=True)
class In(Expression):
    expr: Expression
    table_id: str


class MathOp(enum.Enum):
    ADD = "+"
    SUB = "-"
    MUL = "*"
    DIV = "/"
    MOD = "%"


@dataclass(frozen=True)
class Math(Expression):
    left: Expression
    op: MathOp
    right: Expression


@dataclass(frozen=True)
class FunctionCall(Expression):
    """``ns:name(args...)`` — covers scalar functions and attribute aggregators."""
    name: str
    args: tuple[Expression, ...] = ()
    namespace: Optional[str] = None


# ---------------------------------------------------------------------------
# Stream handlers: filter / window / stream function
# ---------------------------------------------------------------------------

class StreamHandler:
    __slots__ = ()


@dataclass(frozen=True)
class Filter(StreamHandler):
    expr: Expression


@dataclass(frozen=True)
class WindowHandler(StreamHandler):
    name: str                              # "length", "time", "externalTimeBatch"...
    args: tuple[Expression, ...] = ()
    namespace: Optional[str] = None


@dataclass(frozen=True)
class StreamFunction(StreamHandler):
    name: str
    args: tuple[Expression, ...] = ()
    namespace: Optional[str] = None


# ---------------------------------------------------------------------------
# Input streams (reference: query-api execution/query/input/stream/*)
# ---------------------------------------------------------------------------

class InputStream:
    __slots__ = ()


@dataclass(frozen=True)
class SingleInputStream(InputStream):
    stream_id: str
    ref_id: Optional[str] = None          # `as X` alias / pattern event ref
    handlers: tuple[StreamHandler, ...] = ()
    is_inner: bool = False                # `#innerStream` inside partitions
    is_fault: bool = False                # `!faultStream`

    @property
    def alias(self) -> str:
        return self.ref_id or self.stream_id

    @property
    def window(self) -> Optional[WindowHandler]:
        for h in self.handlers:
            if isinstance(h, WindowHandler):
                return h
        return None

    @property
    def filters(self) -> tuple[Filter, ...]:
        return tuple(h for h in self.handlers if isinstance(h, Filter))


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"


@dataclass(frozen=True)
class JoinInputStream(InputStream):
    left: SingleInputStream
    right: SingleInputStream
    join_type: JoinType = JoinType.INNER
    on: Optional[Expression] = None
    within: Optional[Expression] = None           # aggregation join: within ...
    per: Optional[Expression] = None              # aggregation join: per ...
    trigger: str = "all"                          # "left"|"right"|"all" (unidirectional)


# --- pattern / sequence state elements (reference: execution/query/input/state/*)

class StateElement:
    __slots__ = ()


@dataclass(frozen=True)
class StreamStateElement(StateElement):
    stream: SingleInputStream              # carries ref (e1=) and filters
    within: Optional[TimeConstant] = None


@dataclass(frozen=True)
class AbsentStreamStateElement(StateElement):
    """``not Stream[filter] for 1 sec`` (waiting_time may be None when used
    with `and/or` against a present stream)."""
    stream: SingleInputStream
    waiting_time: Optional[TimeConstant] = None
    within: Optional[TimeConstant] = None


@dataclass(frozen=True)
class LogicalStateElement(StateElement):
    left: StateElement
    op: str                                # "and" | "or"
    right: StateElement
    within: Optional[TimeConstant] = None


@dataclass(frozen=True)
class CountStateElement(StateElement):
    stream: StreamStateElement
    min_count: int
    max_count: int                         # -1 == unbounded ("<2:>" etc.)
    within: Optional[TimeConstant] = None

    ANY = -1


@dataclass(frozen=True)
class NextStateElement(StateElement):
    state: StateElement
    next: StateElement
    within: Optional[TimeConstant] = None


@dataclass(frozen=True)
class EveryStateElement(StateElement):
    state: StateElement
    within: Optional[TimeConstant] = None


class StateType(enum.Enum):
    PATTERN = "pattern"    # skip-till-any-match (other events may interleave)
    SEQUENCE = "sequence"  # strict contiguity


@dataclass(frozen=True)
class StateInputStream(InputStream):
    type: StateType
    state: StateElement
    within: Optional[TimeConstant] = None


# ---------------------------------------------------------------------------
# Selector (reference: execution/query/selection/Selector.java)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class OutputAttribute:
    expr: Expression
    rename: Optional[str] = None

    @property
    def name(self) -> str:
        if self.rename:
            return self.rename
        if isinstance(self.expr, Variable):
            return self.expr.attribute
        raise ValueError(f"output attribute needs 'as' rename: {self.expr}")


class OrderDir(enum.Enum):
    ASC = "asc"
    DESC = "desc"


@dataclass(frozen=True)
class OrderByAttribute:
    var: Variable
    order: OrderDir = OrderDir.ASC


@dataclass(frozen=True)
class Selector:
    select_all: bool = False
    attributes: tuple[OutputAttribute, ...] = ()
    group_by: tuple[Variable, ...] = ()
    having: Optional[Expression] = None
    order_by: tuple[OrderByAttribute, ...] = ()
    limit: Optional[int] = None
    offset: Optional[int] = None


# ---------------------------------------------------------------------------
# Output streams & rate (reference: execution/query/output/stream/*)
# ---------------------------------------------------------------------------

class OutputEventsFor(enum.Enum):
    CURRENT = "current"
    EXPIRED = "expired"
    ALL = "all"


class OutputStreamAction:
    __slots__ = ()


@dataclass(frozen=True)
class InsertInto(OutputStreamAction):
    target: str
    events_for: OutputEventsFor = OutputEventsFor.CURRENT
    is_fault: bool = False
    is_inner: bool = False


@dataclass(frozen=True)
class UpdateSetClause:
    attribute: Variable                    # table column
    value: Expression


@dataclass(frozen=True)
class DeleteFrom(OutputStreamAction):
    target: str
    on: Expression
    events_for: OutputEventsFor = OutputEventsFor.CURRENT


@dataclass(frozen=True)
class UpdateTable(OutputStreamAction):
    target: str
    on: Expression
    set_clauses: tuple[UpdateSetClause, ...] = ()
    events_for: OutputEventsFor = OutputEventsFor.CURRENT


@dataclass(frozen=True)
class UpdateOrInsertTable(OutputStreamAction):
    target: str
    on: Expression
    set_clauses: tuple[UpdateSetClause, ...] = ()
    events_for: OutputEventsFor = OutputEventsFor.CURRENT


@dataclass(frozen=True)
class ReturnAction(OutputStreamAction):
    """`return` — results delivered only to query callback."""
    events_for: OutputEventsFor = OutputEventsFor.CURRENT


class RateType(enum.Enum):
    ALL = "all"
    FIRST = "first"
    LAST = "last"


@dataclass(frozen=True)
class EventOutputRate:
    """``output [all|first|last] every N events``"""
    count: int
    type: RateType = RateType.ALL


@dataclass(frozen=True)
class TimeOutputRate:
    """``output [all|first|last] every 1 sec``"""
    millis: int
    type: RateType = RateType.ALL


@dataclass(frozen=True)
class SnapshotOutputRate:
    """``output snapshot every 1 sec``"""
    millis: int


OutputRate = Union[EventOutputRate, TimeOutputRate, SnapshotOutputRate, None]


# ---------------------------------------------------------------------------
# Definitions
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class StreamDefinition:
    id: str
    attributes: tuple[Attribute, ...]
    annotations: tuple[Annotation, ...] = ()

    def attr_names(self) -> list[str]:
        return [a.name for a in self.attributes]


@dataclass(frozen=True)
class TableDefinition:
    id: str
    attributes: tuple[Attribute, ...]
    annotations: tuple[Annotation, ...] = ()

    def primary_keys(self) -> list[str]:
        a = find_annotation(self.annotations, "primarykey")
        return a.positional() if a else []

    def indexes(self) -> list[str]:
        a = find_annotation(self.annotations, "index")
        return a.positional() if a else []


@dataclass(frozen=True)
class WindowDefinition:
    """``define window W (a int) length(5) output all events``"""
    id: str
    attributes: tuple[Attribute, ...]
    window: WindowHandler
    output_events: OutputEventsFor = OutputEventsFor.ALL
    annotations: tuple[Annotation, ...] = ()


@dataclass(frozen=True)
class TriggerDefinition:
    """``define trigger T at every 5 sec | at 'cron expr' | at 'start'``"""
    id: str
    at_every_millis: Optional[int] = None
    at_cron: Optional[str] = None
    at_start: bool = False
    annotations: tuple[Annotation, ...] = ()


@dataclass(frozen=True)
class FunctionDefinition:
    """``define function f[lang] return type { body }`` (script functions)."""
    id: str
    language: str
    return_type: AttrType
    body: str
    annotations: tuple[Annotation, ...] = ()


class Duration(enum.Enum):
    SECONDS = "sec"
    MINUTES = "min"
    HOURS = "hour"
    DAYS = "day"
    WEEKS = "week"
    MONTHS = "month"
    YEARS = "year"

    @property
    def approx_millis(self) -> int:
        return _DURATION_MS[self]


_DURATION_MS = {
    Duration.SECONDS: 1_000,
    Duration.MINUTES: 60_000,
    Duration.HOURS: 3_600_000,
    Duration.DAYS: 86_400_000,
    Duration.WEEKS: 604_800_000,
    Duration.MONTHS: 2_592_000_000,   # 30 days (bucketing uses calendar)
    Duration.YEARS: 31_536_000_000,   # 365 days
}

DURATION_ORDER = [Duration.SECONDS, Duration.MINUTES, Duration.HOURS,
                  Duration.DAYS, Duration.WEEKS, Duration.MONTHS, Duration.YEARS]


@dataclass(frozen=True)
class AggregationDefinition:
    """``define aggregation A from S select ... group by ... aggregate by ts
    every sec...year`` (reference: AggregationDefinition.java + AggregationParser)."""
    id: str
    input: SingleInputStream
    selector: Selector
    by_attribute: Optional[Variable]      # aggregate by <ts attr>; None -> arrival time
    durations: tuple[Duration, ...] = ()
    annotations: tuple[Annotation, ...] = ()


Definition = Union[StreamDefinition, TableDefinition, WindowDefinition,
                   TriggerDefinition, FunctionDefinition, AggregationDefinition]


# ---------------------------------------------------------------------------
# Execution elements
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Query:
    input: InputStream
    selector: Selector
    output: OutputStreamAction
    rate: OutputRate = None
    annotations: tuple[Annotation, ...] = ()

    def name(self, default: str) -> str:
        a = find_annotation(self.annotations, "info")
        if a:
            v = a.element("name")
            if v:
                return v
        return default


@dataclass(frozen=True)
class RangePartitionCase:
    condition: Expression
    key: str                                # 'label' for matching events


@dataclass(frozen=True)
class PartitionKey:
    stream_id: str
    expr: Optional[Expression] = None        # value partition: `symbol of S`
    ranges: tuple[RangePartitionCase, ...] = ()  # range partition


@dataclass(frozen=True)
class Partition:
    keys: tuple[PartitionKey, ...]
    queries: tuple[Query, ...]
    annotations: tuple[Annotation, ...] = ()


ExecutionElement = Union[Query, Partition]


# ---------------------------------------------------------------------------
# Store (on-demand) queries
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class StoreQuery:
    """``from Table[on cond] select ...`` / update/delete store queries."""
    input: InputStream
    selector: Selector
    action: Optional[OutputStreamAction] = None   # None == find/select
    within: Optional[Expression] = None           # aggregation store query
    per: Optional[Expression] = None


# ---------------------------------------------------------------------------
# The app
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SiddhiApp:
    annotations: tuple[Annotation, ...] = ()
    stream_definitions: dict = field(default_factory=dict)
    table_definitions: dict = field(default_factory=dict)
    window_definitions: dict = field(default_factory=dict)
    trigger_definitions: dict = field(default_factory=dict)
    function_definitions: dict = field(default_factory=dict)
    aggregation_definitions: dict = field(default_factory=dict)
    execution_elements: tuple[ExecutionElement, ...] = ()

    @property
    def name(self) -> str:
        a = find_annotation(self.annotations, "app:name")
        if a:
            v = a.element(None) or a.element("name")
            if v:
                return v
        return "SiddhiApp"

    def annotation(self, name: str) -> Optional[Annotation]:
        return find_annotation(self.annotations, name)
