"""Language front end: SiddhiQL-compatible lexer/parser and typed AST."""
from . import ast
from .parser import (ParseError, parse, parse_expression, parse_query,
                     parse_store_query, parse_time)

__all__ = ["ast", "parse", "parse_query", "parse_store_query",
           "parse_expression", "parse_time", "ParseError"]
