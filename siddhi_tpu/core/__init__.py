"""Core runtime: schemas, columnar batches, expression compiler, plans."""
