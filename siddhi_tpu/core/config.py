"""Config provider SPI (reference: core:util/config/ConfigManager.java:33,
ConfigReader, InMemoryConfigManager): system-level settings for
extensions, resolved per (namespace, name) — the third config tier next
to SiddhiQL annotations and programmatic setters (SURVEY §5 config).
"""
from __future__ import annotations

from typing import Optional


class ConfigReader:
    """Per-extension view of the system configuration."""

    def __init__(self, configs: dict):
        self._configs = dict(configs)

    def read(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._configs.get(key, default)

    def all(self) -> dict:
        return dict(self._configs)


class ConfigManager:
    """SPI: yields a ConfigReader for one extension instance."""

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        return ConfigReader({})


class InMemoryConfigManager(ConfigManager):
    """Keys are '<namespace>.<name>.<key>' (reference
    InMemoryConfigManager semantics); bare '<key>' entries apply to every
    extension."""

    def __init__(self, configs: Optional[dict] = None):
        self._configs = dict(configs or {})

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        prefix = f"{namespace}.{name}."
        out = {k: v for k, v in self._configs.items() if "." not in k}
        out.update({k[len(prefix):]: v for k, v in self._configs.items()
                    if k.startswith(prefix)})
        return ConfigReader(out)
