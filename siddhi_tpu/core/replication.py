"""Hot-standby WAL replication: roles, the semi-sync ACK barrier, and
lag accounting (ROADMAP item 5 — "make it survive MACHINE loss").

The moving parts live where the data lives — `core/wal.py` owns the
byte-level tail/append/fencing, `net/repl.py` owns the wire (shipper on
the primary's connection, receiver on the standby) — so this module is
the app-level brain both sides share:

* parse `@app:replication('async'|'semi-sync', role=..., peer=...)`
  into a ReplicationConfig (validated against `@app:durability` — a
  log you never write cannot be shipped; analysis rule SA14 flags the
  same statically);
* on the PRIMARY, track each standby's acknowledged watermark so the
  durable-ACK barrier can extend from "local fsync" to "local fsync +
  standby append-ack" (`wait_ack`), and derive the lag gauges
  (`siddhi_tpu_repl_lag_records` / `_lag_seconds`) plus the
  `repl_lag_breach` flight-recorder trigger;
* on the STANDBY, track the applied watermark and the highest primary
  generation seen, so `promote()` can fence ABOVE it (core/wal.py
  write_generation) and the deposed primary's appends are rejected.

Semi-sync semantics (docs/RELIABILITY.md): the producer's PING→ACK
barrier succeeds only after the local fsync AND the standby confirms
the same watermark appended to ITS log.  No standby connected, or an
ack slower than `ack.timeout` -> the barrier FAILS (FrameDesync) and
the producer retransmits from its last ACK — the retransmit contract
is exactly what makes failover lossless, so degrading silently to
async would be lying about durability.  Opt into that trade
explicitly with `degrade='async'`.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..query import ast as qast
from ..utils.locks import new_lock

MODES = ("async", "semi-sync")
ROLES = ("primary", "standby")


class ReplicationError(Exception):
    pass


class ReplicationConfig:
    """Parsed `@app:replication(...)` (plan-time; immutable)."""

    def __init__(self, mode: str, role: str = "primary",
                 peer: Optional[str] = None,
                 ack_timeout_s: float = 5.0,
                 heartbeat_s: float = 1.0,
                 lag_records: int = 10_000,
                 lag_breach_s: float = 5.0,
                 degrade: Optional[str] = None):
        if mode not in MODES:
            raise ReplicationError(
                f"@app:replication({mode!r}): unknown mode "
                f"(have: async | semi-sync)")
        if role not in ROLES:
            raise ReplicationError(
                f"@app:replication(role={role!r}): unknown role "
                f"(have: primary | standby)")
        if role == "standby" and not peer:
            raise ReplicationError(
                "@app:replication(role='standby') requires peer="
                "'host:port' (the primary's frame endpoint to tail)")
        if degrade not in (None, "async"):
            raise ReplicationError(
                f"@app:replication(degrade={degrade!r}): the only "
                f"degradation is 'async' (barrier stops waiting for "
                f"the standby when none is connected)")
        self.mode = mode
        self.role = role
        self.peer = peer
        self.ack_timeout_s = float(ack_timeout_s)
        self.heartbeat_s = float(heartbeat_s)
        self.lag_records = int(lag_records)
        self.lag_breach_s = float(lag_breach_s)
        self.degrade = degrade

    def to_dict(self) -> dict:
        return {"mode": self.mode, "role": self.role, "peer": self.peer,
                "ack_timeout_s": self.ack_timeout_s,
                "heartbeat_s": self.heartbeat_s,
                "lag_records": self.lag_records,
                "lag_breach_s": self.lag_breach_s,
                "degrade": self.degrade}


def config_from_annotations(app) -> Optional[ReplicationConfig]:
    """`@app:replication('async'|'semi-sync', role=, peer=,
    ack.timeout=, heartbeat=, lag.records=, lag.breach=, degrade=)`
    -> ReplicationConfig, or None when the app is not replicated."""
    ann = qast.find_annotation(app.annotations, "app:replication")
    if ann is None:
        return None
    mode = (ann.element() or "async").lower()
    kw: dict = {}
    for k, v in ann.elements:
        if not k:
            continue
        key = k.lower()
        if key == "role":
            kw["role"] = v.lower()
        elif key == "peer":
            kw["peer"] = v
        elif key in ("ack.timeout", "ack.timeout.s"):
            kw["ack_timeout_s"] = _seconds(v)
        elif key in ("heartbeat", "heartbeat.s"):
            kw["heartbeat_s"] = _seconds(v)
        elif key == "lag.records":
            kw["lag_records"] = int(v)
        elif key in ("lag.breach", "lag.breach.s"):
            kw["lag_breach_s"] = _seconds(v)
        elif key == "degrade":
            kw["degrade"] = v.lower()
        else:
            raise ReplicationError(
                f"@app:replication: unknown option {k!r}")
    return ReplicationConfig(mode, **kw)


def _seconds(text) -> float:
    """'250 ms' | '5 sec' | '1.5' -> seconds."""
    s = str(text).strip().lower()
    for suffix, mult in (("ms", 1e-3), ("milliseconds", 1e-3),
                         ("millisecond", 1e-3), ("seconds", 1.0),
                         ("second", 1.0), ("sec", 1.0), ("s", 1.0),
                         ("minutes", 60.0), ("minute", 60.0),
                         ("min", 60.0)):
        if s.endswith(suffix):
            return float(s[:-len(suffix)].strip()) * mult
    return float(s)


class ReplicationCoordinator:
    """One app's replication state, shared by the runtime, the
    net-plane shipper/receiver, and the PING barrier.

    Primary side: `on_ack` folds each standby append-ack into the
    acknowledged watermark and wakes `wait_ack` sleepers (the semi-sync
    barrier).  Standby side: `note_applied` / `note_generation` track
    what the receiver has landed, so promote() knows what to fence
    above.  Either side: `metrics()` feeds
    statistics()["replication"] and the siddhi_tpu_repl_* series."""

    def __init__(self, config: ReplicationConfig,
                 on_lag_breach: Optional[Callable[[str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config
        self.role = config.role         # flips to "primary" at promote
        self.promoted = False
        self.clock = clock
        self.on_lag_breach = on_lag_breach
        self._lock = new_lock("ReplicationCoordinator._lock")
        self._ack_cv = threading.Condition(self._lock)
        # barrier sleepers poke the shipper so a semi-sync ACK is not
        # gated on the shipper's idle-poll cadence (~IDLE_S of latency)
        self.ship_wake = threading.Event()
        # primary side --------------------------------------------------
        self._acked: dict = {}          # stream -> standby-appended seq
        self._local: dict = {}          # stream -> local appended seq
        self._standbys = 0              # live subscriber connections
        self._last_ack_t: Optional[float] = None
        self._lag_breach_since: Optional[float] = None
        self._lag_breached = False
        self.shipped_records = 0
        self.shipped_bytes = 0
        self.shipped_snapshots = 0
        self.acks = 0
        self.heartbeats = 0
        self.rejected_generation = 0    # fenced-off appends we refused
        self.barrier_waits = 0
        self.barrier_timeouts = 0
        # standby side --------------------------------------------------
        self._applied: dict = {}        # stream -> seq landed in our log
        self._source_generation = 0     # highest primary gen seen
        self.applied_records = 0
        self.applied_bytes = 0
        self.applied_snapshots = 0
        self._last_record_t: Optional[float] = None

    # -- primary: standby tracking & the semi-sync barrier -------------------

    def standby_attached(self) -> None:
        with self._lock:
            self._standbys += 1

    def standby_detached(self) -> None:
        with self._ack_cv:
            self._standbys = max(0, self._standbys - 1)
            # wake barrier sleepers so a dead standby fails them at the
            # timeout (or immediately under degrade='async')
            self._ack_cv.notify_all()

    def standbys(self) -> int:
        with self._lock:
            return self._standbys

    def note_local(self, watermark: dict) -> None:
        """The primary's own appended watermark (lag's minuend)."""
        with self._lock:
            for s, v in (watermark or {}).items():
                if int(v) > self._local.get(s, 0):
                    self._local[s] = int(v)

    def note_shipped(self, records: int, nbytes: int) -> None:
        with self._lock:
            self.shipped_records += records
            self.shipped_bytes += nbytes

    def on_ack(self, watermark: dict) -> None:
        """A standby confirmed `watermark` appended to ITS log."""
        with self._lock:        # _ack_cv shares this lock: notify is legal
            self.acks += 1
            self._last_ack_t = self.clock()
            for s, v in (watermark or {}).items():
                if int(v) > self._acked.get(s, 0):
                    self._acked[s] = int(v)
            self._ack_cv.notify_all()
        self._check_lag()

    def on_heartbeat(self, watermark: dict) -> None:
        with self._lock:
            self.heartbeats += 1
            self._last_ack_t = self.clock()
        self._check_lag()

    def _acked_covers_locked(self, watermark: dict) -> bool:
        return all(self._acked.get(s, 0) >= int(v)
                   for s, v in watermark.items())

    def wait_ack(self, watermark: dict,
                 timeout_s: Optional[float] = None) -> bool:
        """Block until a standby has acknowledged every stream of
        `watermark`, or the timeout lapses — the semi-sync half of the
        durable-ACK barrier.  Returns False on timeout OR when no
        standby is connected (unless degrade='async', which waives the
        wait entirely): the caller MUST fail the barrier so the
        producer retransmits."""
        if not watermark:
            return True
        self.ship_wake.set()            # ship our tail NOW, not at poll
        deadline = self.clock() + (timeout_s if timeout_s is not None
                                   else self.config.ack_timeout_s)
        with self._ack_cv:
            self.barrier_waits += 1
            while not self._acked_covers_locked(watermark):
                if self._standbys == 0 and self.config.degrade == "async":
                    return True         # explicit opt-out: local-only
                remaining = deadline - self.clock()
                if remaining <= 0:
                    self.barrier_timeouts += 1
                    return False
                self._ack_cv.wait(min(remaining, 0.25))
            return True

    # -- standby: applied tracking -------------------------------------------

    def note_applied(self, stream: str, seq: int, nbytes: int) -> None:
        with self._lock:
            if int(seq) > self._applied.get(stream, 0):
                self._applied[stream] = int(seq)
            self.applied_records += 1
            self.applied_bytes += nbytes
            self._last_record_t = self.clock()

    def note_snapshot(self, watermark: Optional[dict]) -> None:
        with self._lock:
            self.applied_snapshots += 1
            for s, v in (watermark or {}).items():
                if int(v) > self._applied.get(s, 0):
                    self._applied[s] = int(v)
            self._last_record_t = self.clock()

    def note_generation(self, generation: int) -> None:
        with self._lock:
            if int(generation) > self._source_generation:
                self._source_generation = int(generation)

    def source_generation(self) -> int:
        with self._lock:
            return self._source_generation

    def applied_watermark(self) -> dict:
        with self._lock:
            return dict(self._applied)

    def mark_promoted(self, generation: int) -> None:
        with self._lock:        # _ack_cv shares this lock: notify is legal
            self.role = "primary"
            self.promoted = True
            self._source_generation = int(generation)
            self._ack_cv.notify_all()

    # -- lag -----------------------------------------------------------------

    def lag(self) -> tuple:
        """-> (lag_records, lag_seconds) from whichever side's books
        this node keeps (primary: local vs acked; standby: freshness of
        the last applied record)."""
        with self._lock:
            if self.role == "primary":
                rec = sum(max(0, v - self._acked.get(s, 0))
                          for s, v in self._local.items())
                sec = (self.clock() - self._last_ack_t) \
                    if self._last_ack_t is not None and rec else 0.0
            else:
                rec = 0
                sec = (self.clock() - self._last_record_t) \
                    if self._last_record_t is not None else 0.0
            return rec, max(0.0, sec)

    def _check_lag(self) -> None:
        """Sustained lag past BOTH thresholds fires `on_lag_breach`
        once per excursion (the repl_lag_breach flight-recorder
        trigger); recovery re-arms it."""
        cb = self.on_lag_breach
        if cb is None:
            return
        rec, sec = self.lag()
        now = self.clock()
        with self._lock:
            over = (rec > self.config.lag_records)
            if not over:
                self._lag_breach_since = None
                self._lag_breached = False
                return
            if self._lag_breach_since is None:
                self._lag_breach_since = now
            sustained = now - self._lag_breach_since
            if sustained < self.config.lag_breach_s or self._lag_breached:
                return
            self._lag_breached = True
        try:
            cb(f"replication lag {rec} records "
               f"(> {self.config.lag_records}) sustained "
               f"{sustained:.1f}s with {self.standbys()} standby(s)")
        except Exception:
            pass                        # observability must not fail the path

    # -- telemetry -----------------------------------------------------------

    def metrics(self) -> dict:
        rec, sec = self.lag()
        with self._lock:
            m = {"mode": self.config.mode,
                 "role": self.role,
                 "promoted": self.promoted,
                 "peer": self.config.peer,
                 "standbys": self._standbys,
                 "lag_records": rec,
                 "lag_seconds": round(sec, 3),
                 "shipped_records": self.shipped_records,
                 "shipped_bytes": self.shipped_bytes,
                 "shipped_snapshots": self.shipped_snapshots,
                 "acks": self.acks,
                 "heartbeats": self.heartbeats,
                 "rejected_generation": self.rejected_generation,
                 "barrier_waits": self.barrier_waits,
                 "barrier_timeouts": self.barrier_timeouts}
            if self.role != "primary" or self.promoted:
                m.update({"applied_records": self.applied_records,
                          "applied_bytes": self.applied_bytes,
                          "applied_snapshots": self.applied_snapshots,
                          "source_generation": self._source_generation,
                          "applied_watermark": dict(self._applied)})
            if self._acked:
                m["acked_watermark"] = dict(self._acked)
            return m
