"""Adaptive execution geometry: persistent autotuner + SLO batching control.

Every device plan family exposes geometry knobs — micro-batch/flush size,
`@app:devicePipeline` depth, NFA chunk-lane count, fused multi-query lane
packing — and they dominate performance the way kernel tile sizes do in an
inference stack: the chunking chosen for the hardware IS the performance
model (Simultaneous Finite Automata, arxiv 1405.0562; In-Memory Regular
Pattern Matching codesign, arxiv 2209.05686).  This module makes the
engine pick and adapt that geometry itself, in three cooperating parts:

  * `TuningCache` + `Autotuner` — offline/warmup sweep of a bounded
    candidate grid per app, scored with the telemetry latency histograms
    (`telemetry.Histogram` p99 + measured events/sec) over a synthetic or
    recorded sample tape.  Winners persist in an on-disk JSON cache keyed
    by (plan signature, device kind, JAX version), so later deploys of
    the same query shapes skip the sweep entirely.  The cache is surfaced
    via `GET /siddhi/artifact/tuning` and hit/miss gauges in
    `statistics()` / Prometheus; `python -m siddhi_tpu.core.autotune
    --lint` schema-checks a persisted cache (wired into
    scripts/smoke.sh so a malformed cache can never brick deploy — a
    corrupt file is also quarantined and ignored at load, never trusted).
  * `SLOController` — `@app:latencySLO('25ms')` adapts the runtime's
    micro-batch/flush cadence AIMD-style from the observed p99 of a
    rolling window (additive increase of the batch target while p99 sits
    below the hysteresis band, multiplicative decrease when the target is
    violated), with a telemetry-visible decision log.
    `@app:maxBatchLatency` rides the same controller in cadence-only
    (non-adaptive) mode, preserving its one-shot semantics exactly.
  * planner/runtime integration — plan constructors consult
    `pipeline_depth_for` / `chunk_lanes_for` / `fused_lane_pack_for`
    (annotation wins, then the tuning cache, then the built-in default);
    plans advertise a `regeometry(batch_hint, depth, ...)` hook; the
    runtime applies controller decisions at flush boundaries only and
    splits oversized batches with the PR-4 halving machinery
    (`faults.split_batch`), which already proves geometry splits are
    output-invariant — so outputs stay byte-identical to fixed geometry.
"""
from __future__ import annotations

import hashlib
import json
import math
import os
import time
import warnings
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from ..query import ast
from ..utils.locks import new_lock

CACHE_VERSION = 1
GEOMETRY_KEYS = ("batch", "pipeline_depth", "chunk_lanes", "lane_pack",
                 "plan_family", "agg_capacity")
PLAN_FAMILIES = ("filter", "window", "join", "pattern", "multi_query", "app")
# pattern-kernel execution families (docs/PERFORMANCE.md "Plan families"):
# seq = persistent sequential-in-T NFA scan, chunk = stateless chunked-halo
# lanes, scan = associative-scan SFA, dfa = bit-packed multi-stride hybrid
PATTERN_FAMILIES = ("seq", "chunk", "scan", "dfa")


class AutotuneError(Exception):
    pass


# ---------------------------------------------------------------------------
# geometry
# ---------------------------------------------------------------------------

@dataclass
class Geometry:
    """One point in the execution-geometry space.  None = knob not set
    (the consumer keeps its annotation/default)."""
    batch: Optional[int] = None             # micro-batch / flush size
    pipeline_depth: Optional[int] = None    # @app:devicePipeline depth
    chunk_lanes: Optional[int] = None       # chunked-NFA lane count K
    lane_pack: Optional[int] = None         # fused multi-query lanes/kernel
    plan_family: Optional[str] = None       # pattern family (PATTERN_FAMILIES)
    agg_capacity: Optional[int] = None      # device agg bucket-ring slots

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in GEOMETRY_KEYS
                if getattr(self, k) is not None}

    @classmethod
    def from_dict(cls, d: dict) -> "Geometry":
        out = {}
        for k in GEOMETRY_KEYS:
            if k not in d or d.get(k) is None:
                continue
            out[k] = str(d[k]) if k == "plan_family" else int(d[k])
        return cls(**out)

    def label(self) -> str:
        return ",".join(f"{k}={v}" for k, v in self.to_dict().items())


def device_kind() -> str:
    """Backend the tuned numbers were measured on — tunings for a
    tunneled TPU must not apply to a CPU run and vice versa."""
    try:
        import jax
        return str(jax.default_backend())
    except Exception:
        return "unknown"


def jax_version() -> str:
    try:
        import jax
        return str(jax.__version__)
    except Exception:
        return "none"


# ---------------------------------------------------------------------------
# plan signatures (cache keys)
# ---------------------------------------------------------------------------

def signature_of(family: str, payload) -> str:
    """Stable signature for one tuned shape: sha1 over the family plus a
    canonical text form of the query (its normalized AST repr — the
    dataclass reprs are deterministic).  The full cache key adds device
    kind + JAX version (see `cache_key`): a tuning measured on one
    backend/version never silently applies to another."""
    text = f"{family}|{payload!r}"
    return f"{family}:" + hashlib.sha1(text.encode()).hexdigest()[:20]


def family_of(plan) -> Optional[str]:
    cls = type(plan).__name__
    return {"FilterProjectPlan": "filter",
            "DeviceWindowAggPlan": "window",
            "DeviceJoinPlan": "join",
            "DevicePatternPlan": "pattern",
            "MultiQueryDevicePatternPlan": "multi_query"}.get(cls)


def plan_signature(plan) -> Optional[str]:
    """Signature of a BUILT plan (keyed off the normalized query AST the
    planner kept for the interpreter twin; fused multi-query plans key
    off their group shape signature — the same payload
    `fused_lane_pack_for` looks up at build time)."""
    fam = family_of(plan)
    if fam == "multi_query":
        gs = getattr(plan, "_group_sig", None)
        return signature_of(fam, gs) if gs is not None else None
    q = getattr(plan, "_q_ast", None)
    if fam is None or q is None:
        return None
    return signature_of(fam, q)


def app_signature(app) -> str:
    """App-level signature (batch-capacity entry): streams + queries."""
    payload = (tuple(sorted((sid, repr(sd)) for sid, sd in
                            app.stream_definitions.items())),
               tuple(repr(e) for e in app.execution_elements))
    return signature_of("app", payload)


def cache_key(sig: str, dev: Optional[str] = None,
              jaxv: Optional[str] = None) -> str:
    return f"{sig}|{dev or device_kind()}|jax{jaxv or jax_version()}"


# ---------------------------------------------------------------------------
# the on-disk tuning cache
# ---------------------------------------------------------------------------

def default_cache_path() -> str:
    env = os.environ.get("SIDDHI_TUNE_CACHE", "")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "siddhi_tpu",
                        "tuning.json")


def validate_cache_data(data) -> list:
    """Schema lint: list of problems (empty = valid).  The schema the
    smoke-test lint step enforces — a malformed persisted cache must be
    detected before it can brick a deploy."""
    probs: list = []
    if not isinstance(data, dict):
        return [f"top level must be an object, got {type(data).__name__}"]
    if data.get("version") != CACHE_VERSION:
        probs.append(f"version must be {CACHE_VERSION}, "
                     f"got {data.get('version')!r}")
    entries = data.get("entries")
    if not isinstance(entries, dict):
        return probs + ["'entries' must be an object"]
    for key, ent in entries.items():
        where = f"entry {key!r}"
        if not isinstance(key, str) or "|" not in key:
            probs.append(f"{where}: key must be 'sig|device|jaxver'")
        if not isinstance(ent, dict):
            probs.append(f"{where}: value must be an object")
            continue
        geo = ent.get("geometry")
        if not isinstance(geo, dict) or not geo:
            probs.append(f"{where}: 'geometry' must be a non-empty object")
        else:
            for k, v in geo.items():
                if k not in GEOMETRY_KEYS:
                    probs.append(f"{where}: unknown geometry knob {k!r}")
                elif k == "plan_family":
                    if v not in PATTERN_FAMILIES:
                        probs.append(f"{where}: plan_family must be one of "
                                     f"{PATTERN_FAMILIES}, got {v!r}")
                elif not isinstance(v, int) or isinstance(v, bool) \
                        or v < 0:
                    probs.append(f"{where}: knob {k!r} must be a "
                                 f"non-negative int, got {v!r}")
        fam = ent.get("family")
        if fam is not None and fam not in PLAN_FAMILIES:
            probs.append(f"{where}: unknown family {fam!r}")
        score = ent.get("score")
        if score is not None:
            if not isinstance(score, dict):
                probs.append(f"{where}: 'score' must be an object")
            else:
                for k, v in score.items():
                    if v is not None and not isinstance(v, (int, float)):
                        probs.append(f"{where}: score {k!r} not numeric")
    return probs


class TuningCache:
    """On-disk geometry winners, keyed `sig|device_kind|jaxVERSION`.

    Load is defensive by design: a corrupt/truncated file is quarantined
    (renamed `<path>.corrupt`, best-effort) and the cache starts empty —
    a bad persisted artifact degrades to a cold cache, never a failed
    deploy.  Writes are atomic (tmp + rename)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_cache_path()
        self.hits = 0
        self.misses = 0
        self.corrupt = False
        self._lock = new_lock("TuningCache._lock")
        self._data: Optional[dict] = None

    # -- persistence -----------------------------------------------------

    def _load_locked(self) -> dict:
        if self._data is not None:
            return self._data
        data = {"version": CACHE_VERSION, "entries": {}}
        if os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    loaded = json.load(f)
                probs = validate_cache_data(loaded)
                if probs:
                    raise ValueError("; ".join(probs[:3]))
                data = loaded
            except (OSError, ValueError) as e:
                self.corrupt = True
                warnings.warn(
                    f"tuning cache {self.path!r} is corrupt and was "
                    f"ignored ({type(e).__name__}: {e}); starting cold",
                    RuntimeWarning)
                try:                         # keep for postmortem, get it
                    os.replace(self.path, self.path + ".corrupt")
                except OSError:              # out of the load path
                    pass
        self._data = data
        return data

    def _save_locked(self) -> None:
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._data, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError as e:                 # read-only FS: stay in-memory
            warnings.warn(f"tuning cache {self.path!r} not persisted: {e}",
                          RuntimeWarning)

    # -- access ----------------------------------------------------------

    def entries(self) -> dict:
        with self._lock:
            return dict(self._load_locked()["entries"])

    def get(self, sig: str) -> Optional[dict]:
        """Entry for a plan signature under the CURRENT device/JAX key;
        counts the hit/miss gauges surfaced in statistics()."""
        with self._lock:
            ent = self._load_locked()["entries"].get(cache_key(sig))
            if ent is None:
                self.misses += 1
            else:
                self.hits += 1
            return ent

    def peek(self, sig: str) -> Optional[dict]:
        """get() without touching the hit/miss gauges."""
        with self._lock:
            return self._load_locked()["entries"].get(cache_key(sig))

    def put(self, sig: str, geometry: dict, family: Optional[str] = None,
            score: Optional[dict] = None) -> str:
        geometry = {k: (str(v) if k == "plan_family" else int(v))
                    for k, v in geometry.items()
                    if k in GEOMETRY_KEYS and v is not None}
        if not geometry:
            raise AutotuneError(f"empty geometry for {sig!r}")
        ent = {"geometry": geometry, "tuned_at_ms": int(time.time() * 1000)}
        if family:
            ent["family"] = family
        if score:
            ent["score"] = {k: v for k, v in score.items()
                            if isinstance(v, (int, float)) or v is None}
        with self._lock:
            data = self._load_locked()
            key = cache_key(sig)
            data["entries"][key] = ent
            self._save_locked()
        return key

    def metrics(self) -> dict:
        with self._lock:
            n = len(self._data["entries"]) if self._data is not None else None
        m = {"tuning_cache_hits": self.hits,
             "tuning_cache_misses": self.misses,
             "tuning_cache_path": self.path,
             "tuning_cache_corrupt": self.corrupt}
        if n is not None:
            m["tuning_cache_entries"] = n
        return m


_SHARED: dict = {}
_SHARED_LOCK = new_lock("autotune._SHARED_LOCK")


def shared_cache(path: Optional[str] = None) -> TuningCache:
    """Process-wide TuningCache per path (runtimes share the counters a
    /siddhi/artifact/tuning scrape reads)."""
    p = path or default_cache_path()
    with _SHARED_LOCK:
        c = _SHARED.get(p)
        if c is None:
            c = _SHARED[p] = TuningCache(p)
        return c


# ---------------------------------------------------------------------------
# runtime facade + planner consult helpers
# ---------------------------------------------------------------------------

class TunerRuntime:
    """Per-runtime view of the tuning cache, consulted by plan
    constructors at build time.  `@app:autotune('off')` disables the
    consult (annotations/defaults only); anything else — or no
    annotation — reads the shared on-disk cache."""

    def __init__(self, rt):
        self.rt = rt
        an = ast.find_annotation(rt.app.annotations, "app:autotune")
        self.mode = (an.element() or "cache").lower() if an is not None \
            else "cache"
        self.enabled = self.mode != "off"
        self.cache = shared_cache() if self.enabled else None
        self.hits = 0
        self.misses = 0
        self.resolved: dict = {}       # sig -> geometry dict (this build)

    def lookup(self, family: str, payload) -> Optional[Geometry]:
        if not self.enabled:
            return None
        sig = signature_of(family, payload)
        ent = self.cache.get(sig)
        if ent is None:
            self.misses += 1
            return None
        self.hits += 1
        g = Geometry.from_dict(ent.get("geometry", {}))
        self.resolved[sig] = g.to_dict()
        return g

    def batch_hint(self) -> Optional[int]:
        """App-level tuned micro-batch capacity (the `app` family)."""
        g = self.lookup("app", _app_payload(self.rt.app))
        return g.batch if g is not None else None

    def metrics(self) -> dict:
        m = {"cache_hits": self.hits, "cache_misses": self.misses,
             "mode": self.mode}
        if self.cache is not None:
            m.update(self.cache.metrics())
        if self.resolved:
            m["resolved"] = dict(self.resolved)
        return m


def _app_payload(app):
    return (tuple(sorted((sid, repr(sd)) for sid, sd in
                         app.stream_definitions.items())),
            tuple(repr(e) for e in app.execution_elements))


def pipeline_depth_for(rt, family: str, q=None) -> int:
    """Initial `@app:devicePipeline` depth for one plan: the annotation
    wins, then the tuning cache's persisted winner, then 0."""
    pl = ast.find_annotation(rt.app.annotations, "app:devicePipeline")
    if pl is not None:
        return int(pl.element())
    tn = getattr(rt, "tuner", None)
    if tn is not None and q is not None:
        g = tn.lookup(family, q)
        if g is not None and g.pipeline_depth is not None:
            return g.pipeline_depth
    return 0


def chunk_lanes_for(rt, q=None, default: int = 64) -> int:
    """Chunked-NFA lane count K: @app:deviceChunkLanes wins, then the
    tuning cache, then the built-in default."""
    an = ast.find_annotation(rt.app.annotations, "app:deviceChunkLanes")
    if an is not None:
        return int(an.element())
    tn = getattr(rt, "tuner", None)
    if tn is not None and q is not None:
        g = tn.lookup("pattern", q)
        if g is not None and g.chunk_lanes is not None:
            return g.chunk_lanes
    return default


def pattern_family_for(rt, q=None) -> Optional[str]:
    """Requested pattern execution family (seq|chunk|scan|dfa), or None
    for automatic selection: `@app:patternFamily` wins, then the tuning
    cache's persisted winner.  The plan only honors a family its
    eligibility analysis proved sound (DevicePatternPlan.families) —
    an ineligible request falls back with a warning, never silently
    changes semantics."""
    an = ast.find_annotation(rt.app.annotations, "app:patternFamily")
    if an is not None:
        fam = str(an.element()).lower()
        if fam in ("auto", ""):
            return None
        if fam not in PATTERN_FAMILIES:
            raise AutotuneError(
                f"@app:patternFamily({fam!r}): unknown family "
                f"(have {PATTERN_FAMILIES} or 'auto')")
        return fam
    tn = getattr(rt, "tuner", None)
    if tn is not None and q is not None:
        g = tn.lookup("pattern", q)
        if g is not None and g.plan_family is not None:
            return g.plan_family
    return None


def fused_lane_pack_for(rt, group_sig) -> int:
    """Fused multi-query lane packing: max query instances per fused
    kernel (0 = unbounded, the historical behavior).  @app:fusedLanes
    wins, then the tuning cache keyed on the group signature."""
    an = ast.find_annotation(rt.app.annotations, "app:fusedLanes")
    if an is not None:
        return max(0, int(an.element()))
    tn = getattr(rt, "tuner", None)
    if tn is not None:
        g = tn.lookup("multi_query", group_sig)
        if g is not None and g.lane_pack is not None:
            return g.lane_pack
    return 0


def agg_capacity_for(rt, payload=None, default: int = 1024) -> int:
    """Initial slot count of the device-resident aggregation bucket
    store, per duration (core/agg_device.py; the ring doubles on
    overflow so this is a starting geometry, not a bound).
    @app:aggCapacity wins, then the tuning cache, then the default —
    the same precedence every other geometry knob applies."""
    an = ast.find_annotation(rt.app.annotations, "app:aggCapacity")
    if an is not None:
        return max(8, int(an.element()))
    tn = getattr(rt, "tuner", None)
    if tn is not None and payload is not None:
        g = tn.lookup("app", payload)
        if g is not None and g.agg_capacity is not None:
            return max(8, g.agg_capacity)
    return default


# ---------------------------------------------------------------------------
# the online SLO controller
# ---------------------------------------------------------------------------

class SLOController:
    """AIMD micro-batch/flush-cadence controller behind
    `@app:latencySLO('25ms')`.

    The runtime feeds `observe()` one end-to-end latency sample per
    dispatched micro-batch (first-buffered-event -> batch processed) and
    calls `maybe_decide()` at flush boundaries.  Each decision window
    (>= `decide_every_s` elapsed AND >= `min_samples` observed) the
    controller reads the window's p99 from a telemetry Histogram and
    moves the batch target:

      p99 > target                      -> multiplicative decrease (x backoff)
      p99 < target * (1 - hysteresis)   -> additive increase (+ add_step)
      otherwise                         -> hold (the hysteresis band)

    Decisions are returned to the runtime, which applies them ONLY at a
    flush boundary (`_apply_batch_target`): batch boundaries move, but
    every event still flows through the same plans in the same order, so
    outputs are byte-identical to a fixed-geometry run (the PR-4 halving
    machinery proves batch splits are output-invariant; the differential
    suite asserts it per plan family).

    `@app:maxBatchLatency` constructs this same controller with
    `adaptive=False`: only the flush cadence (`flush_after_s`) is used,
    reproducing the original one-shot heuristic with no semantic change.

    A virtual clock (`maybe_decide(now_s)`) keeps the controller fully
    deterministic under test."""

    def __init__(self, target_s: Optional[float] = None, *,
                 initial_batch: int = 2048, min_batch: int = 32,
                 max_batch: int = 1 << 17, adaptive: bool = True,
                 flush_after_s: Optional[float] = None,
                 decide_every_s: float = 0.25, hysteresis: float = 0.3,
                 min_samples: int = 8, backoff: float = 0.5,
                 add_step: Optional[int] = None, log_capacity: int = 128):
        from .telemetry import Histogram
        if target_s is None and flush_after_s is None:
            raise AutotuneError("SLOController needs target_s or "
                                "flush_after_s")
        self.target_s = target_s
        self.adaptive = bool(adaptive) and target_s is not None
        # builders age out at half the target by default: the other half
        # is headroom for dispatch + device + materialization
        self.flush_after_s = flush_after_s if flush_after_s is not None \
            else target_s / 2.0
        self.min_batch = int(min_batch)
        self.max_batch = int(max_batch)
        self.batch_target = max(self.min_batch,
                                min(self.max_batch, int(initial_batch)))
        self.decide_every_s = float(decide_every_s)
        self.hysteresis = float(hysteresis)
        self.min_samples = int(min_samples)
        self.backoff = float(backoff)
        self.add_step = int(add_step) if add_step is not None \
            else max(32, self.min_batch)
        self._win = Histogram()
        # cumulative (never window-reset): the demo/report p99 over a
        # whole measured run, not just the last decision window
        self.total = Histogram()
        self._last_decide: Optional[float] = None
        self.last_p99_s: Optional[float] = None
        self.decisions: deque = deque(maxlen=log_capacity)
        self.counts = {"increase": 0, "decrease": 0, "hold": 0}
        # serving-plane admission throttle (net/admission.py token
        # buckets scale their refill rate by this): multiplicative
        # decrease with the batch target when p99 overshoots, additive
        # recovery back to 1.0 under the target — overload lowers
        # ADMISSION before engine latency collapses (ROADMAP item 3)
        self.admission_factor = 1.0
        self.admission_floor = 0.1
        # SLO-breach trace trigger (core/tracing.py): called with the
        # decision record whenever a window's p99 overshoots the target.
        # The runtime wires it to FrameTracer.trigger — nonblocking
        # enqueue, safe even though maybe_decide runs under the runtime
        # lock (the dump builds on the siddhi-trace-export thread)
        self.on_breach: Optional[Callable[[dict], None]] = None

    def observe(self, seconds: float) -> None:
        """One per-batch latency sample (first buffered event ->
        processed)."""
        self._win.record(seconds)
        self.total.record(seconds)

    def maybe_decide(self, now_s: Optional[float] = None) -> Optional[dict]:
        """Close the decision window if due; returns the decision record
        (also appended to the telemetry-visible log) or None."""
        if not self.adaptive:
            return None
        if now_s is None:
            now_s = time.perf_counter()
        if self._last_decide is None:
            self._last_decide = now_s
            return None
        if now_s - self._last_decide < self.decide_every_s \
                or self._win.count < self.min_samples:
            return None
        p99 = self._win.percentile(99)
        self.last_p99_s = p99
        old = self.batch_target
        if p99 > self.target_s:
            action = "decrease"
            new = max(self.min_batch, int(old * self.backoff))
            self.admission_factor = max(self.admission_floor,
                                        self.admission_factor * self.backoff)
        elif p99 < self.target_s * (1.0 - self.hysteresis):
            action = "increase"
            new = min(self.max_batch, old + self.add_step)
            self.admission_factor = min(1.0, self.admission_factor + 0.1)
        else:
            action = "hold"
            new = old
        self.batch_target = new
        self.counts[action] += 1
        dec = {"t_s": round(now_s, 4), "action": action,
               "p99_ms": round(p99 * 1e3, 3),
               "target_ms": round(self.target_s * 1e3, 3),
               "samples": self._win.count,
               "batch_from": old, "batch": new,
               "admission_factor": round(self.admission_factor, 4)}
        self.decisions.append(dec)
        if action == "decrease" and self.on_breach is not None:
            # a p99 breach IS the trigger the tracing plane retains a
            # dump for — the handler only enqueues, so firing under the
            # runtime lock (the _drain call site) is safe
            try:
                self.on_breach(dec)
            except Exception:
                pass
        self._win.reset()
        self._last_decide = now_s
        return dec

    def metrics(self) -> dict:
        m = {"adaptive": self.adaptive,
             "flush_after_ms": round(self.flush_after_s * 1e3, 3),
             "batch_target": self.batch_target,
             "admission_factor": round(self.admission_factor, 4),
             "decisions": dict(self.counts),
             "decision_log": list(self.decisions)[-16:]}
        if self.target_s is not None:
            m["target_ms"] = round(self.target_s * 1e3, 3)
        if self.last_p99_s is not None:
            m["window_p99_ms"] = round(self.last_p99_s * 1e3, 3)
        if self.total.count:
            m["observed_batches"] = self.total.count
            for p in (50, 99):
                v = self.total.percentile(p)
                if v is not None:
                    m[f"p{p}_ms"] = round(v * 1e3, 3)
        return m


# ---------------------------------------------------------------------------
# synthetic sample tapes
# ---------------------------------------------------------------------------

def synthetic_tape(schema, n_events: int, seed: int = 0, keys: int = 8,
                   dt_ms: int = 1, ts0: int = 1_700_000_000_000) -> tuple:
    """(cols, ts) columnar sample for one stream schema — the warmup
    tape the Autotuner sweeps when the caller records none.  Strings
    draw from `keys` symbols, numerics from quarter-rounded uniforms
    (exactly representable in f32, so device/host scoring tapes agree)."""
    rng = np.random.default_rng(seed)
    cols: dict = {}
    for a in schema.attributes:
        t = a.type
        if t == ast.AttrType.STRING:
            cols[a.name] = np.asarray(
                [f"K{i}" for i in rng.integers(0, keys, n_events)])
        elif t in (ast.AttrType.FLOAT, ast.AttrType.DOUBLE):
            cols[a.name] = np.round(
                rng.uniform(90.0, 130.0, n_events) * 4) / 4
        elif t == ast.AttrType.BOOL:
            cols[a.name] = rng.integers(0, 2, n_events).astype(bool)
        elif t == ast.AttrType.LONG:
            cols[a.name] = (ts0 + np.arange(n_events, dtype=np.int64)
                            * dt_ms)
        else:
            cols[a.name] = rng.integers(1, 1000, n_events).astype(np.int32)
    ts = ts0 + np.arange(n_events, dtype=np.int64) * dt_ms
    return cols, ts


def _slice_cols(cols: dict, ts, lo: int, hi: int) -> tuple:
    return {k: v[lo:hi] for k, v in cols.items()}, ts[lo:hi]


# ---------------------------------------------------------------------------
# the offline / warmup autotuner
# ---------------------------------------------------------------------------

class Autotuner:
    """Bounded-grid geometry sweep for one app.

    Each candidate builds a fresh runtime from the SAME app text, applies
    the geometry programmatically (batch capacity + `regeometry` on every
    plan — no annotation rewriting, so plan signatures stay stable),
    replays the sample tape, and scores with the telemetry latency
    histograms: events/sec over the timed window plus the per-stream
    dispatch-latency p99.  The winner maximizes eps (subject to `slo_ms`
    when given, with infeasible candidates falling back to lowest p99)
    and persists per-plan + app-level entries in the TuningCache.

    Every candidate must deliver the IDENTICAL output row sequence — the
    sweep double-checks the geometry-invariance contract (count + order-
    sensitive checksum) and raises AutotuneError on divergence rather
    than persist a geometry that changes results."""

    DEFAULT_BATCHES = (2048, 8192, 32768)
    DEFAULT_DEPTHS = (0, 2)

    def __init__(self, cache: Optional[TuningCache] = None):
        self.cache = cache or shared_cache()

    # -- grid ------------------------------------------------------------

    def default_grid(self, n_events: int, chunk_lanes=None,
                     plan_families=None) -> list:
        batches = [b for b in self.DEFAULT_BATCHES if b <= max(256,
                                                               n_events)]
        batches = batches or [min(2048, n_events)]
        lanes = list(chunk_lanes) if chunk_lanes else [None]
        fams = list(plan_families) if plan_families else [None]
        return [Geometry(batch=b, pipeline_depth=d, chunk_lanes=k,
                         plan_family=f)
                for b in batches for d in self.DEFAULT_DEPTHS
                for k in lanes for f in fams]

    # -- sweep -----------------------------------------------------------

    def tune(self, app_text: str, tapes: Optional[dict] = None,
             n_events: int = 1 << 14, grid: Optional[list] = None,
             slo_ms: Optional[float] = None, warm_events: int = 2048,
             persist: bool = True, force: bool = False,
             out_streams: Optional[tuple] = None,
             plan_families: Optional[tuple] = None,
             log: Optional[Callable] = None) -> dict:
        """Sweep `grid` (or the bounded default) over `app_text`.

        tapes: {stream_id: (cols, ts)} recorded sample; synthesized from
        the stream schemas when omitted.  Returns {"winner": geometry,
        "candidates": [scored...], "from_cache": bool, "keys": [...]}.
        With `force=False` a warm cache (an app-level entry for this app
        under the current device/JAX key) skips the sweep entirely."""
        from . import runtime as _rtmod
        app = _rtmod.parse(app_text)
        app_sig = signature_of("app", _app_payload(app))
        if not force:
            ent = self.cache.peek(app_sig)
            if ent is not None:
                return {"winner": dict(ent["geometry"]),
                        "from_cache": True, "candidates": [],
                        "keys": [cache_key(app_sig)],
                        "score": ent.get("score")}

        grid = list(grid) if grid is not None else \
            self.default_grid(n_events, plan_families=plan_families)
        if not grid:
            raise AutotuneError("empty candidate grid")
        results = []
        baseline_out = None
        for g in grid:
            if log is not None:
                log(f"autotune: measuring {g.label()}")
            res = self._measure(app_text, g, tapes, n_events, warm_events,
                                out_streams)
            if baseline_out is None:
                baseline_out = (res["matches"], res["out_crc"])
            elif (res["matches"], res["out_crc"]) != baseline_out:
                raise AutotuneError(
                    f"geometry {g.label()} changed outputs "
                    f"(matches {res['matches']} vs {baseline_out[0]}, "
                    f"crc {res['out_crc']:#x} vs {baseline_out[1]:#x}) — "
                    f"geometry must be output-invariant")
            results.append({"geometry": g.to_dict(), "eps": res["eps"],
                            "p99_ms": res["p99_ms"],
                            "matches": res["matches"]})
        winner_i = self._pick(results, slo_ms)
        winner = results[winner_i]
        keys = []
        if persist:
            keys = self._persist(app_text, grid[winner_i], winner)
        return {"winner": dict(winner["geometry"]), "from_cache": False,
                "candidates": results, "keys": keys,
                "score": {"eps": winner["eps"],
                          "p99_ms": winner["p99_ms"]}}

    @staticmethod
    def _pick(results: list, slo_ms: Optional[float]) -> int:
        idx = range(len(results))
        if slo_ms is not None:
            ok = [i for i in idx
                  if results[i]["p99_ms"] is not None
                  and results[i]["p99_ms"] <= slo_ms]
            if ok:
                return max(ok, key=lambda i: results[i]["eps"])
            # nothing meets the SLO: least-bad latency wins
            return min(idx, key=lambda i: (results[i]["p99_ms"]
                                           if results[i]["p99_ms"]
                                           is not None else math.inf))
        return max(idx, key=lambda i: results[i]["eps"])

    def _persist(self, app_text: str, g: Geometry, winner: dict) -> list:
        """Write the winner: one entry per device plan signature (with
        the family-relevant knobs) + the app-level batch entry."""
        from . import runtime as _rtmod
        score = {"eps": winner["eps"], "p99_ms": winner["p99_ms"]}
        mgr = _rtmod.SiddhiManager()
        keys = []
        try:
            rt = mgr.create_app_runtime(app_text)
            app_sig = signature_of("app", _app_payload(rt.app))
            keys.append(self.cache.put(app_sig, {"batch": g.batch},
                                       family="app", score=score))
            for plan in rt._plans:
                fam = family_of(plan)
                sig = plan_signature(plan)
                if fam is None or sig is None:
                    continue
                geo = {"batch": g.batch, "pipeline_depth": g.pipeline_depth}
                if fam == "pattern" and g.chunk_lanes is not None:
                    geo["chunk_lanes"] = g.chunk_lanes
                if fam == "pattern" and g.plan_family is not None:
                    geo["plan_family"] = g.plan_family
                if fam == "multi_query" and g.lane_pack is not None:
                    geo["lane_pack"] = g.lane_pack
                keys.append(self.cache.put(sig, geo, family=fam,
                                           score=score))
        finally:
            mgr.shutdown()
        return keys

    # -- one candidate ---------------------------------------------------

    def _measure(self, app_text: str, g: Geometry, tapes: Optional[dict],
                 n_events: int, warm_events: int,
                 out_streams: Optional[tuple]) -> dict:
        import zlib
        from . import runtime as _rtmod
        mgr = _rtmod.SiddhiManager()
        try:
            rt = mgr.create_app_runtime(app_text)
            if g.batch:
                rt.batch_capacity = int(g.batch)
            for plan in rt._plans:
                rg = getattr(plan, "regeometry", None)
                if rg is not None:
                    rg(batch_hint=g.batch, depth=g.pipeline_depth,
                       chunk_lanes=g.chunk_lanes,
                       plan_family=g.plan_family)
            rt.enable_stats(True)
            if out_streams is None:
                # every insert-into stream target — from the AST, not the
                # plans (partition groups and fused multi-query plans
                # route per inner query and report no output_target)
                tgts: set = set()
                for elem in rt.app.execution_elements:
                    qs = elem.queries if isinstance(elem, ast.Partition) \
                        else (elem,)
                    for q in qs:
                        t = getattr(q.output, "target", None)
                        if t is not None and t not in rt.tables \
                                and t not in rt.named_windows:
                            tgts.add(t)
                out_streams = tuple(sorted(tgts))
            crc = [0]
            count = [0]

            def on_batch(b, _crc=crc, _n=count):
                _n[0] += b.n
                for row in b.rows(rt.strings):
                    _crc[0] = zlib.crc32(repr(row).encode(), _crc[0])
            for s in out_streams:
                rt.add_batch_callback(s, on_batch)
            rt.start()
            feeds = self._feeds(rt, tapes, n_events)
            bsz = int(g.batch or rt.batch_capacity)
            total = min(len(ts) for _h, _c, ts in feeds)
            warm = min(max(warm_events, bsz), max(total - bsz, 0))
            if warm < bsz:
                # the tape is too short to warm one full batch of this
                # geometry: its compiles land inside the timed window
                # and the score under-reads steady state.  Size tapes
                # >= 2x the largest candidate batch (bench --autotune
                # does) to keep the sweep compile-free.
                warnings.warn(
                    f"autotune: candidate {g.label()} cannot warm a "
                    f"full batch ({warm} warm events < batch {bsz}); "
                    f"its timed window includes compile time",
                    RuntimeWarning)
            for h, cols, ts in feeds:           # warm: compiles + growth
                for lo in range(0, warm, bsz):
                    c, t = _slice_cols(cols, ts, lo, min(lo + bsz, warm))
                    h.send_batch(c, t)
            rt.flush()
            rt.stats.reset()
            n_timed = 0
            t0 = time.perf_counter()
            for lo in range(warm, total, bsz):
                hi = min(lo + bsz, total)
                for h, cols, ts in feeds:
                    c, t = _slice_cols(cols, ts, lo, hi)
                    h.send_batch(c, t)
                    n_timed += hi - lo
            rt.flush()
            dt = time.perf_counter() - t0
            # score with the PR-1 telemetry histograms: per-stream
            # dispatch-latency p99 over the timed (compile-free) window
            p99s = [trk.hist.percentile(99)
                    for trk in rt.stats.stream_in.values()
                    if trk.hist.count]
            p99_ms = round(max(p99s) * 1e3, 3) if p99s else None
            return {"eps": round(n_timed / dt) if dt > 0 else 0,
                    "p99_ms": p99_ms, "matches": count[0],
                    "out_crc": crc[0] & 0xFFFFFFFF}
        finally:
            mgr.shutdown()

    @staticmethod
    def _feeds(rt, tapes: Optional[dict], n_events: int) -> list:
        """[(handler, cols, ts)] for every feedable input stream."""
        feeds = []
        input_ids = sorted({sid for sid, subs in rt._subscribers.items()
                            for _p in subs
                            if sid in rt.schemas
                            and not sid.startswith("!")
                            and sid not in rt.named_windows
                            and sid not in rt.tables})
        if tapes:
            input_ids = [s for s in input_ids if s in tapes]
        for i, sid in enumerate(input_ids):
            if tapes and sid in tapes:
                cols, ts = tapes[sid]
            else:
                cols, ts = synthetic_tape(rt.schemas[sid], n_events,
                                          seed=i)
            feeds.append((rt.input_handler(sid), cols, ts))
        if not feeds:
            raise AutotuneError("app has no feedable input stream")
        return feeds


# ---------------------------------------------------------------------------
# CLI: cache lint / show (wired into scripts/smoke.sh)
# ---------------------------------------------------------------------------

def lint_path(path: Optional[str] = None) -> tuple:
    """(ok, problems) for a persisted cache file; a missing file is OK
    (cold cache)."""
    p = path or default_cache_path()
    if not os.path.exists(p):
        return True, [f"{p}: no cache file (cold cache) — OK"]
    try:
        with open(p) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return False, [f"{p}: unreadable ({type(e).__name__}: {e})"]
    probs = validate_cache_data(data)
    if probs:
        return False, [f"{p}: {m}" for m in probs]
    n = len(data.get("entries", {}))
    return True, [f"{p}: valid (version {data.get('version')}, "
                  f"{n} entries)"]


def _main(argv) -> int:
    import sys
    path = None
    rest = [a for a in argv if not a.startswith("--")]
    if rest:
        path = rest[0]
    if "--show" in argv:
        p = path or default_cache_path()
        c = TuningCache(p)
        print(json.dumps({"path": p, "entries": c.entries()}, indent=1))
        return 0
    # default action: lint
    ok, msgs = lint_path(path)
    for m in msgs:
        print(("OK: " if ok else "LINT: ") + m,
              file=sys.stdout if ok else sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(_main(sys.argv[1:]))
