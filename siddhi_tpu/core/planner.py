"""Query planner: AST Query -> executable plan over columnar batches.

The TPU analog of the reference's parser layer (reference:
core:util/parser/QueryParser.java:81, SingleInputStreamParser.java:94,
SelectorParser.java, OutputParser.java) — but instead of assembling a
linked chain of per-event Processor objects, each query lowers to ONE
jitted array program `step(state, env) -> (state, mask, out_cols)` plus a
thin host wrapper that routes compacted outputs.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..query import ast
from ..query.ast import AttrType
from .batch import EventBatch
from .expr import (CompiledExpr, ExprError, MultiStreamContext,
                   SingleStreamContext, compile_expression, jnp_dtype)
from .schema import TIMESTAMP_DTYPE, StreamSchema, StringTable, dtype_of

# aggregator function names recognized in selectors (reference:
# core:query/selector/attribute/aggregator/*)
AGGREGATOR_NAMES = {
    "sum", "avg", "count", "min", "max", "minforever", "maxforever",
    "stddev", "distinctcount", "and", "or", "unionset",
}


def mesh_for(rt, axis: str):
    """Opt-in execution mesh for the batch-sharded kernels (window-agg,
    incremental agg): @app:deviceMesh('always') with a power-of-two
    device count; returns None otherwise.  (Pattern plans have their own
    auto policy keyed on partition count.)"""
    if str(getattr(rt, "device_mesh", "auto")).lower() != "always":
        return None
    ndev = len(jax.devices())
    if ndev <= 1 or ndev & (ndev - 1):
        return None
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()), (axis,))


class PlanError(Exception):
    pass


def selector_has_aggregators(selector: ast.Selector) -> bool:
    def walk(e) -> bool:
        if isinstance(e, ast.FunctionCall):
            if e.namespace is None and e.name.lower() in AGGREGATOR_NAMES:
                return True
            return any(walk(a) for a in e.args)
        if isinstance(e, (ast.Math, ast.Compare, ast.And, ast.Or)):
            return walk(e.left) or walk(e.right)
        if isinstance(e, ast.Not):
            return walk(e.expr)
        return False
    return any(walk(a.expr) for a in selector.attributes)


@dataclass
class CompiledSelector:
    """Projection part of a selector (no aggregators)."""
    names: list
    types: list
    fns: list                      # each: env -> column
    having: Optional[CompiledExpr]
    # env key when the output is a plain variable — read host column directly,
    # skipping the device round-trip (zero-copy passthrough)
    passthrough: list = None
    # per-output read-sets, parallel to fns (the bare fns carry no
    # metadata; reading .reads off them silently demoted every computed
    # column to the interpreter path)
    reads: list = None

    def out_schema(self, stream_id: str) -> StreamSchema:
        return StreamSchema(stream_id, tuple(
            ast.Attribute(n, t) for n, t in zip(self.names, self.types)))


def compile_selector(selector: ast.Selector, ctx, in_schema: Optional[StreamSchema],
                     extra_names: Optional[dict] = None) -> CompiledSelector:
    """Compile projection expressions. select * requires in_schema."""
    names, types, fns, passthrough, reads = [], [], [], [], []
    if selector.select_all:
        if in_schema is None:
            raise PlanError("select * not supported for this input type")
        out_attrs = [(a.name, ast.Variable(a.name)) for a in in_schema.attributes]
    else:
        out_attrs = [(oa.name, oa.expr) for oa in selector.attributes]
    for nm, expr in out_attrs:
        ce = compile_expression(expr, ctx)
        names.append(nm)
        types.append(ce.type)
        fns.append(ce.fn)
        reads.append(frozenset(ce.reads))
        if isinstance(expr, ast.Variable):
            key, _ = ctx.resolve(expr)
            passthrough.append(key)
        else:
            passthrough.append(None)
    having = None
    if selector.having is not None:
        # having may reference output attribute names
        extra = {n: (n, t) for n, t in zip(names, types)}
        hctx = _with_extra(ctx, extra)
        having = compile_expression(selector.having, hctx)
        if having.type != AttrType.BOOL:
            raise PlanError("having must be boolean")
    return CompiledSelector(names, types, fns, having, passthrough, reads)


def _with_extra(ctx, extra: dict):
    import copy
    c = copy.copy(ctx)
    c.extra = {**getattr(ctx, "extra", {}), **extra}
    return c


# ---------------------------------------------------------------------------
# Output routing descriptor
# ---------------------------------------------------------------------------

@dataclass
class OutputBatch:
    """A produced batch plus where it should go."""
    target: Optional[str]          # stream id, or None for `return`
    batch: EventBatch
    is_expired: bool = False       # expired-events output (timestamp = expiry)
    is_signal: bool = False        # zero-event control signal (window reset):
                                   # must be dispatched despite n == 0


class QueryPlan:
    """Base: stateful executable for one query."""

    name: str
    input_streams: tuple          # stream ids this plan subscribes to
    output_target: Optional[str]
    out_schema: Optional[StreamSchema]
    table_writer = None           # set when output_target is a table
    _pipe = None                  # DispatchPipeline when the plan defers
                                  # D2H pulls (pipeline.py)
    rt = None                     # owning runtime (set by _register_plan
                                  # when the plan doesn't hold it already)
    _q_ast = None                 # normalized source Query AST (set by
                                  # build.attach_table_writer; enables the
                                  # interpreter-quarantine twin)
    # graceful-degradation contract (core/faults.py ladder):
    # retryable_process: process() leaves plan state untouched when the
    # device dispatch raises, so the runtime may retry with a split batch.
    # retryable_finalize: finalize() restores its input buffer
    # (self._buffered) when the dispatch raises, so the runtime may retry
    # with a halved flush; _finalize_retry_ok goes False once a flush
    # passed its point of no return (e.g. join mirrors advanced).
    retryable_process = False
    retryable_finalize = False
    _finalize_retry_ok = True
    batch_hint = None             # SLO controller's current batch target
    pipeline_depth = 0

    def process(self, stream_id: str, batch: EventBatch) -> list:
        raise NotImplementedError

    def regeometry(self, batch_hint=None, depth=None, **knobs) -> None:
        """Adaptive-geometry hook (core/autotune.py): the tuner applies a
        cached winner here after build, and the SLO controller applies
        batch decisions at flush boundaries.  Every plan family derives
        its device geometry (pad grids, chunk sizes) from batch.n at
        dispatch, so a new hint only changes FUTURE dispatch shapes —
        batches already in flight are untouched, and batch-boundary moves
        are output-invariant (the PR-4 halving machinery's parity
        argument; asserted by the geometry differentials)."""
        if batch_hint is not None:
            self.batch_hint = int(batch_hint)
        if depth is not None and getattr(self, "_can_pipeline", True):
            # _can_pipeline: a plan that must sync per flush (join side
            # filters feed the mirror update) pins depth 0 — geometry
            # hints never override a correctness constraint.  The depth
            # is recorded even without a live pipeline: a later
            # plan-family switch (pattern plans) builds its pipeline
            # from self.pipeline_depth and must not lose the knob.
            self.pipeline_depth = int(depth)
            if self._pipe is not None:
                self._pipe.set_depth(int(depth))

    def on_timer(self, now_ms: int) -> list:
        """Called by the scheduler tick (time windows, absent patterns...)."""
        return []

    def next_wakeup(self):
        """Next timestamp (ms) this plan needs a timer callback, or None."""
        return None

    def flush_pending(self) -> list:
        """Deliver any device results still in flight (pipelined plans
        defer materialization by up to @app:devicePipeline batches); the
        runtime calls this at its flush barrier."""
        if self._pipe is not None:
            return self._pipe.drain()
        return []

    # -- dispatch-round overlap (runtime._drain) -------------------------
    #
    # The runtime opens a dispatch round over every plan touched by a
    # batch (or finalize pass), calls process/finalize on each — which
    # dispatch device work but defer the blocking D2H pull — then
    # collects.  N device plans therefore overlap on device instead of
    # running build -> compute -> readback serially per plan.

    def begin_dispatch_round(self) -> None:
        if self._pipe is not None:
            self._pipe.hold()

    def collect_ready(self) -> list:
        if self._pipe is not None:
            return self._pipe.collect()
        return []

    def finalize(self) -> list:
        """Called when a drain round settles; multi-input plans flush their
        seq-merged buffers here. Returns OutputBatches."""
        return []

    # checkpoint hooks (reference: core:util/snapshot/Snapshotable.java)
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, d: dict) -> None:
        pass


# ---------------------------------------------------------------------------
# Filter/project plan — the minimum end-to-end slice
# ---------------------------------------------------------------------------

class FilterProjectPlan(QueryPlan):
    """`from S[p>100] select a, b+1 as c insert into O` — stateless.

    Reference equivalents: FilterProcessor.java:55 loop + QuerySelector
    projection; here: one fused jit over whole columns.
    """

    retryable_process = True        # stateless: safe to re-dispatch splits

    def __init__(self, name: str, in_schema: StreamSchema, alias: str,
                 filters: list, selector: ast.Selector,
                 strings: StringTable, output_target: Optional[str],
                 limit: Optional[int] = None, offset: Optional[int] = None,
                 events_for: ast.OutputEventsFor = ast.OutputEventsFor.CURRENT,
                 pipeline_depth: int = 0):
        from .pipeline import DispatchPipeline
        self.name = name
        self.pipeline_depth = pipeline_depth
        self._pipe = DispatchPipeline(
            name, lambda e: self._materialize(*e), depth=pipeline_depth)
        # a stateless query never expires events; `insert expired events into`
        # therefore emits nothing (matches reference semantics)
        self.emits_nothing = events_for == ast.OutputEventsFor.EXPIRED
        self.in_schema = in_schema
        self.input_streams = (in_schema.id,)
        self.output_target = output_target
        ctx = SingleStreamContext(in_schema, strings, alias)
        self._filter = None
        if filters:
            f = filters[0]
            for g in filters[1:]:
                f = ast.And(f, g)
            self._filter = compile_expression(f, ctx)
            if self._filter.type != AttrType.BOOL:
                raise PlanError(f"filter must be boolean in query {name!r}")
        self._sel = compile_selector(selector, ctx, in_schema)
        self.out_schema = self._sel.out_schema(output_target or f"#{name}")
        self.limit, self.offset = limit, offset
        # upload ONLY the columns the device program reads (the tunnel
        # pays per byte both ways): filter reads + computed-output reads +
        # having reads (incl. pass-through sources having renames)
        need: set = set()
        if self._filter is not None:
            need |= set(self._filter.reads)
        for rd, pt in zip(self._sel.reads, self._sel.passthrough):
            if pt is None:
                need |= set(rd)
        if self._sel.having is not None:
            h_reads = set(self._sel.having.reads)
            need |= h_reads - set(self._sel.names)
            for nm, pt in zip(self._sel.names, self._sel.passthrough):
                if pt is not None and nm in h_reads:
                    need.add(pt)
        if not need:
            # constant filter / constant computed column: no data reads,
            # but the step still needs one column for the batch length
            need = {"__timestamp__"}
        self._need = need
        self._step = jax.jit(self._make_step())
        # first real dispatch pays trace+XLA compile: the device-time
        # profiler must not fold that into its kernel_compute estimate
        self._warm = False

    def _make_step(self):
        filt, sel = self._filter, self._sel

        def step(env):
            n = next(iter(env.values())).shape[0]
            mask = (jnp.broadcast_to(filt.fn(env), (n,))  # 0-d if constant
                    if filt is not None else jnp.ones(n, dtype=bool))
            outs = [None if pt is not None else fn(env)
                    for fn, pt in zip(sel.fns, sel.passthrough)]
            if sel.having is not None:
                henv = dict(env)
                h_reads = set(sel.having.reads)
                for nm, col, pt in zip(sel.names, outs, sel.passthrough):
                    if nm not in h_reads:
                        continue        # env is pruned: only map names read
                    henv[nm] = env[pt] if pt is not None else col
                mask = mask & sel.having.fn(henv)
            # the mask travels bit-packed: the tunnel pays per byte, and
            # the bool row is 8x the packed words
            pad = -(-n // 32) * 32
            if pad != n:
                mask = jnp.concatenate([mask, jnp.zeros(pad - n, bool)])
            words = (mask.reshape(-1, 32).astype(jnp.uint32)
                     << jnp.arange(32, dtype=jnp.uint32)[None, :]) \
                .sum(axis=1).astype(jnp.uint32)   # sum may promote to u64
            return jax.lax.bitcast_convert_type(words, jnp.int32), \
                [o for o in outs if o is not None]
        return step

    def process(self, stream_id: str, batch: EventBatch) -> list:
        if batch.n == 0 or self.emits_nothing:
            return []
        host_env = {a.name: batch.columns[a.name] for a in self.in_schema.attributes}
        host_env["__timestamp__"] = batch.timestamps
        if self._filter is None and self._sel.having is None \
                and all(pt is not None for pt in self._sel.passthrough):
            # pure pass-through (no filter/having/computed column): nothing
            # for the device to do — emit the batch directly (NOTE: keyed
            # on plan shape, not on the read-set — constant filters and
            # constant columns have empty reads but still must evaluate)
            mask = np.ones(batch.n, dtype=bool)
            return self._pipe.push((None, [], host_env, batch, mask))
        env = {k: host_env[k] for k in sorted(self._need)
               if k in host_env and host_env[k].dtype != np.dtype(object)}
        if self.rt is not None:
            self.rt.inject("dispatch", self.name)
        prof = None if self.rt is None else self.rt.profiler
        if prof is not None:
            from .telemetry import env_nbytes
            prof.note_bytes(self.name, "h2d", env_nbytes(env))
            mask_w, outs = prof.run_kernel(self._step, (env,),
                                           cache_hit=self._warm)
        else:
            mask_w, outs = self._step(env)
        self._warm = True
        from .pipeline import start_d2h
        start_d2h([mask_w] + list(outs))    # pulls overlap device compute
        return self._pipe.push((mask_w, outs, host_env, batch, None))

    def _materialize(self, mask_w, outs, host_env, batch, mask) -> list:
        if mask is None:
            words = np.asarray(mask_w)
            mask = ((words.view(np.uint32)[:, None]
                     >> np.arange(32, dtype=np.uint32)) & 1
                    ).astype(bool).reshape(-1)[:batch.n]
        if not mask.any():
            return []
        ts = batch.timestamps[mask]
        cols = {}
        outs = iter(outs)
        for nm, t, pt in zip(self._sel.names, self._sel.types, self._sel.passthrough):
            if pt is not None:
                cols[nm] = host_env[pt][mask]
            else:
                arr = np.asarray(next(outs))
                if arr.ndim == 0:       # constant column: 0-d on device
                    arr = np.broadcast_to(arr, (batch.n,))
                cols[nm] = arr[mask].astype(dtype_of(t))
        if self.offset:
            ts = ts[self.offset:]
            cols = {k: v[self.offset:] for k, v in cols.items()}
        if self.limit is not None:
            ts = ts[:self.limit]
            cols = {k: v[:self.limit] for k, v in cols.items()}
        out = EventBatch(self.out_schema, ts, cols, len(ts))
        return [OutputBatch(self.output_target, out)]


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def output_target_of(q: ast.Query) -> Optional[str]:
    if isinstance(q.output, ast.InsertInto):
        if q.output.is_fault:
            return "!" + q.output.target
        return q.output.target
    if isinstance(q.output, ast.ReturnAction):
        return None
    if isinstance(q.output, (ast.UpdateTable, ast.DeleteFrom, ast.UpdateOrInsertTable)):
        return q.output.target
    raise PlanError(f"unsupported output action {type(q.output).__name__}")
