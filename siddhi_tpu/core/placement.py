"""Placement accounting: no silent demotions.

The engine's single worst historical bug class is *silent placement*: a
query that should run on the device path quietly landing on the host
interpreter because some lowering step swallowed an exception (PR 5
found a whole query class demoted that way).  This module makes every
placement decision a first-class record:

  * every interpreter fallback (and every rejected plan family) in the
    build path calls ``rt.placement.demote(...)`` with a machine-readable
    ``Demotion(query, rule_id, reason, cause)`` — the self-lint
    (``python -m siddhi_tpu.analysis --self``) fails CI on any swallow
    site in a plan-lowering file that records nothing;
  * ``rt.explain()`` (also ``GET /siddhi/artifact/explain`` and the
    ``python -m siddhi_tpu.analysis`` CLI) reports, per query: the chosen
    execution path (device family vs interpreter), the chosen pattern
    plan family, where each geometry knob came from
    (annotation / tuning-cache / default), and the full reason chain for
    every rejected alternative;
  * ``statistics()["placement"]`` + the ``siddhi_tpu_interp_demotions``
    Prometheus series keep the counts scrapeable, so a future silent
    demotion shows up in the bench trajectory (bench.py summary carries
    a ``placement`` field per config).

Demotion rule ids (docs/ANALYSIS.md "Demotion records"):

  D-FILTER      device filter/projection lowering raised; interpreter path
  D-WINDOW      device window-aggregation shape unsupported
  D-JOIN        device join shape unsupported
  D-PATTERN     device pattern kernel unsupported (prefer mode)
  D-SHAPE       no device plan family covers this query shape
  D-POLICY      an annotation/env opt-out chose the host path
  D-FUSED       fused multi-query lane kernel unavailable for a group
  D-PARTITION   partitioned pattern fell back to per-key host clones
  D-FAMILY      a pattern plan family was rejected (forced-but-ineligible
                or failed build validation) in favor of another family
  D-QUARANTINE  the runtime degradation ladder swapped a device plan for
                its interpreter twin after consecutive dispatch failures
  D-AGG         an incremental aggregation stayed on the host reduce path
                instead of the device-resident bucket store (calendar
                durations, explicit opt-out, or jax unavailable)
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..utils.locks import new_lock

DEMOTION_RULES = {
    "D-FILTER": "device filter/projection lowering failed",
    "D-WINDOW": "device window-aggregation shape unsupported",
    "D-JOIN": "device join shape unsupported",
    "D-PATTERN": "device pattern kernel unsupported",
    "D-SHAPE": "no device plan family covers this query shape",
    "D-POLICY": "annotation/env opt-out chose the host path",
    "D-FUSED": "fused multi-query lane kernel unavailable",
    "D-PARTITION": "partitioned pattern fell back to host clones",
    "D-FAMILY": "pattern plan family rejected",
    "D-QUARANTINE": "runtime ladder quarantined the plan",
    "D-AGG": "aggregation stayed on the host reduce path",
}

# rule ids whose records mean the query itself left (or never reached)
# the device path — D-FAMILY keeps the query on device under another
# family, D-FUSED only rejects the fused-lane packing (the query may
# still plan onto the device individually), and D-AGG concerns the
# aggregation state plane, not a query's execution path — so none of
# the three counts toward `interp_demotions`
_INTERP_RULES = frozenset(DEMOTION_RULES) - {"D-FAMILY", "D-FUSED", "D-AGG"}


@dataclass
class Demotion:
    """One recorded placement downgrade.  `cause` carries the swallowed
    exception (as ``TypeName: message``) when the demotion was
    exception-driven; `alternative` names the execution path that was
    rejected or lost (``device-filter``, ``scan``, ...)."""
    query: str
    rule_id: str
    reason: str
    cause: Optional[str] = None
    alternative: str = "device"

    def to_dict(self) -> dict:
        d = {"query": self.query, "rule_id": self.rule_id,
             "reason": self.reason, "alternative": self.alternative}
        if self.cause is not None:
            d["cause"] = self.cause
        return d


class PlacementLog:
    """Per-runtime collector of Demotion records.  Build-time demotions
    arrive on the constructing thread; runtime quarantines arrive on the
    dispatch thread — appends are lock-guarded, reads snapshot."""

    def __init__(self):
        self._lock = new_lock("PlacementLog._lock")
        self._demotions: list = []

    def demote(self, query: str, rule_id: str, reason: str,
               cause: Optional[BaseException] = None,
               alternative: str = "device") -> Demotion:
        if rule_id not in DEMOTION_RULES:
            raise ValueError(f"unknown demotion rule id {rule_id!r} "
                             f"(have {sorted(DEMOTION_RULES)})")
        d = Demotion(query, rule_id, str(reason),
                     f"{type(cause).__name__}: {cause}"
                     if cause is not None else None,
                     alternative)
        with self._lock:
            # idempotent per (query, rule, alternative): partition groups
            # re-plan the same query lazily per key — the first record
            # carries the reason; repeats must not grow without bound
            for prev in self._demotions:
                if (prev.query, prev.rule_id, prev.alternative) == \
                        (d.query, d.rule_id, d.alternative):
                    return prev
            self._demotions.append(d)
        return d

    def records(self) -> list:
        with self._lock:
            return list(self._demotions)

    def for_query(self, name: str) -> list:
        with self._lock:
            return [d for d in self._demotions if d.query == name]

    def interp_demotions(self) -> int:
        with self._lock:
            return sum(1 for d in self._demotions
                       if d.rule_id in _INTERP_RULES)

    def __len__(self) -> int:
        with self._lock:
            return len(self._demotions)


# ---------------------------------------------------------------------------
# EXPLAIN: per-query placement + geometry provenance + rejection chains
# ---------------------------------------------------------------------------

_QUERY_PLAN_KINDS = {
    "FilterProjectPlan": ("device", "filter"),
    "DeviceWindowAggPlan": ("device", "window"),
    "DeviceJoinPlan": ("device", "join"),
    "DevicePatternPlan": ("device", "pattern"),
    "MultiQueryDevicePatternPlan": ("device", "multi_query"),
    "InterpSingleQueryPlan": ("interpreter", "single"),
    "InterpJoinQueryPlan": ("interpreter", "join"),
    "InterpPatternQueryPlan": ("interpreter", "pattern"),
    "PartitionGroup": ("interpreter", "partition-group"),
}


def _knob(value, source: str) -> dict:
    return {"value": value, "source": source}


def _geometry_entry(rt, plan, kind: str) -> dict:
    """Each geometry knob the plan consulted at build, with its
    provenance: annotation > tuning-cache > default (the same precedence
    autotune.pipeline_depth_for & friends apply).  Uses the tuning
    cache's peek() so an EXPLAIN scrape never skews hit/miss gauges."""
    from ..query import ast as qast
    from .autotune import signature_of
    tn = getattr(rt, "tuner", None)
    q = getattr(plan, "_q_ast", None)

    def cached(family, payload):
        if tn is None or not tn.enabled or payload is None:
            return None
        ent = tn.cache.peek(signature_of(family, payload))
        if ent is None:
            return None
        from .autotune import Geometry
        return Geometry.from_dict(ent.get("geometry", {}))

    def source_of(ann_name, geo_attr, family, payload):
        if qast.find_annotation(rt.app.annotations, ann_name) is not None:
            return "annotation"
        g = cached(family, payload)
        if g is not None and getattr(g, geo_attr, None) is not None:
            return "tuning-cache"
        return "default"

    geo: dict = {}
    fam_for_cache = "pattern" if kind in ("pattern", "multi_query") else kind
    if hasattr(plan, "pipeline_depth"):
        geo["pipeline_depth"] = _knob(
            int(getattr(plan, "pipeline_depth", 0) or 0),
            source_of("app:devicePipeline", "pipeline_depth",
                      fam_for_cache, q))
    if kind == "pattern":
        geo["chunk_lanes"] = _knob(
            int(getattr(plan, "_stateless_lanes", 0) or 0),
            source_of("app:deviceChunkLanes", "chunk_lanes", "pattern", q))
        geo["plan_family"] = _knob(
            getattr(plan, "family", None),
            source_of("app:patternFamily", "plan_family", "pattern", q))
    if kind == "multi_query":
        gs = getattr(plan, "_group_sig", None)
        geo["lane_pack"] = _knob(
            int(getattr(plan, "lane_pack", 0) or 0) or None,
            source_of("app:fusedLanes", "lane_pack", "multi_query", gs))
    return geo


def _agg_name(plan) -> str:
    """Aggregation key for a plan: per-key partition clone instances
    (`<base>#<inst>`, partition.py) collapse onto their base query name
    — placement is per QUERY, never per partition key, or the counts
    (and the per-query Prometheus label set) would scale with key
    cardinality."""
    name = plan.name
    if "#" in name and not name.startswith("#"):
        return name.split("#", 1)[0]
    return name


def _query_entry(rt, plan) -> Optional[dict]:
    cls = type(plan).__name__
    if cls not in _QUERY_PLAN_KINDS:
        return None          # named windows, triggers, aggregations...
    path, kind = _QUERY_PLAN_KINDS[cls]
    lad = getattr(rt, "_ladders", {}).get(plan.name)
    quarantined = bool(lad is not None and getattr(lad, "quarantined", False))
    ent: dict = {"path": "interpreter" if quarantined else path,
                 "plan": cls, "kind": kind}
    fam = getattr(plan, "family", None)
    if kind == "pattern" and fam is not None:
        ent["family"] = fam
        families = getattr(plan, "families", None)
        if families:
            rejected = {f: r for f, r in sorted(families.items())
                        if r is not True}
            if rejected:
                ent["rejected"] = rejected
    if kind == "partition-group":
        ent["queries"] = sorted(
            q.name(f"query_p{plan.index}_{qi}")
            for qi, q in enumerate(getattr(plan, "clone_queries", ())))
    if path == "device":     # interpreter plans hold no device geometry
        geo = _geometry_entry(rt, plan, kind)
        if geo:
            ent["geometry"] = geo
    dems = [d.to_dict() for d in rt.placement.for_query(_agg_name(plan))]
    if dems:
        ent["demotions"] = dems
    return ent


def explain(rt) -> dict:
    """The EXPLAIN plane: placement + reason chains for every query of a
    built runtime.  Deterministically ordered and JSON-safe — the
    service endpoint serves exactly this dict, and the test suite holds
    `GET /siddhi/artifact/explain` byte-for-byte equal to it."""
    queries: dict = {}
    for plan in list(getattr(rt, "_plans", ())):
        ent = _query_entry(rt, plan)
        if ent is None:
            continue
        base = _agg_name(plan)
        prev = queries.get(base)
        if prev is None:
            queries[base] = ent
        else:                # another per-key clone of the same query
            prev["instances"] = prev.get("instances", 1) + 1
    # the queryable-state plane: per-aggregation placement (device-
    # resident vs host), retention/eviction accounting, and the D-AGG
    # reason chain for anything that stayed on the host reduce path
    aggs: dict = {}
    for an, a in sorted(getattr(rt, "aggregations", {}).items()):
        ent = {"path": ("device-resident"
                        if getattr(a, "device_plan", None) is not None
                        else "device-batch" if getattr(a, "device", False)
                        else "host"),
               "durations": [d.name for d in a.durations]}
        ret = getattr(a, "retention_ms", None)
        if ret:
            ent["retention_ms"] = {d.name: v for d, v in sorted(
                ret.items(), key=lambda kv: kv[0].approx_millis)}
        ev = getattr(a, "evicted", None)
        if ev and any(ev.values()):
            ent["evicted"] = {d.name: n for d, n in ev.items() if n}
        dems = [d.to_dict() for d in rt.placement.for_query(an)]
        if dems:
            ent["demotions"] = dems
        aggs[an] = ent
    # demotions whose query never produced a plan entry (fused-group
    # probes keyed by candidate names, partition clones not yet
    # instantiated) still surface at the top level
    return {
        "app": rt.app.name,
        "queries": {k: queries[k] for k in sorted(queries)},
        **({"aggregations": aggs} if aggs else {}),
        "demotions": [d.to_dict() for d in rt.placement.records()],
        "placement": summary(rt),
        # the durability plane's EXPLAIN entry: the SAME block
        # statistics() serves (rt.durability_report — one builder, so
        # the two observability surfaces can never disagree)
        "durability": rt.durability_report()
        if hasattr(rt, "durability_report")
        else {"policy": getattr(rt, "durability", "off")},
    }


def summary(rt) -> dict:
    """Compact placement accounting for statistics()/Prometheus/bench:
    device vs interpreter query counts + the demotion tally."""
    device = interp = 0
    queries: dict = {}
    for plan in list(getattr(rt, "_plans", ())):
        cls = type(plan).__name__
        if cls not in _QUERY_PLAN_KINDS:
            continue
        path, kind = _QUERY_PLAN_KINDS[cls]
        lad = getattr(rt, "_ladders", {}).get(plan.name)
        if lad is not None and getattr(lad, "quarantined", False):
            path = "interpreter"
        base = _agg_name(plan)
        prev = queries.get(base)
        if prev is not None:     # per-key clone: count the QUERY once
            prev["instances"] = prev.get("instances", 1) + 1
            continue
        if path == "device":
            device += 1
        else:
            interp += 1
        qent = {"path": path, "kind": kind}
        fam = getattr(plan, "family", None)
        if kind == "pattern" and fam is not None:
            qent["family"] = fam
        nd = len(rt.placement.for_query(base))
        if nd:
            qent["demotions"] = nd
        queries[base] = qent
    return {"device": device, "interpreter": interp,
            "interp_demotions": rt.placement.interp_demotions(),
            "demotions": len(rt.placement),
            "queries": {k: queries[k] for k in sorted(queries)}}
