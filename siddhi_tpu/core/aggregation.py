"""Incremental (multi-granularity) aggregation:
`define aggregation A from S select sum(price) as total group by sym
 aggregate by ts every sec ... year`.

Reference: core:aggregation/IncrementalExecutor.java:45-133 (per-duration
tumbling-bucket executor chain: seconds feed minutes feed hours ...),
AggregationRuntime.java:65-105 (duration->executor + duration->table maps),
AggregationParser.java:87, IncrementalAggregateCompileCondition.java:277
(within/per join selection), Incremental*AttributeAggregator (avg ->
(sum,count) decomposition).

TPU-first reformulation (SURVEY §5 "maps to parallel-prefix"): the chain
is replaced by **independent per-duration segmented reductions** — every
micro-batch computes (bucket, group) segment ids and reduces all base
fields with vectorized scatter-reductions (bincount / ufunc.at), then
merges the few unique segments into per-duration bucket stores.  Because
sum/count/min/max bases are associative, reducing raw events per duration
equals the reference's bucket-of-buckets cascade, with no sequential
dependency between levels — each duration is one data-parallel reduction.

Buckets are never "finalized": within/per queries read running and past
buckets uniformly (the reference merges in-memory + table state the same
way: IncrementalDataAggregator).
"""
from __future__ import annotations

import datetime as _dt
from typing import Callable, Optional

import numpy as np

from ..query import ast
from ..query.ast import AttrType, Duration
from .batch import EventBatch
from .planner import OutputBatch, PlanError, QueryPlan
from .schema import StreamSchema, StringTable, dtype_of

AGG_TIMESTAMP = "AGG_TIMESTAMP"

# base-field decomposition (reference: aggregator/incremental/
# Incremental{Sum,Count,Avg,Min,Max}AttributeAggregator)
_BASES = {
    "sum": ("sum",),
    "count": ("count",),
    "avg": ("sum", "count"),
    "min": ("min",),
    "max": ("max",),
}

_DUR_NAMES = {
    "sec": Duration.SECONDS, "seconds": Duration.SECONDS,
    "min": Duration.MINUTES, "minutes": Duration.MINUTES,
    "hour": Duration.HOURS, "hours": Duration.HOURS,
    "day": Duration.DAYS, "days": Duration.DAYS,
    "week": Duration.WEEKS, "weeks": Duration.WEEKS,
    "month": Duration.MONTHS, "months": Duration.MONTHS,
    "year": Duration.YEARS, "years": Duration.YEARS,
}


def duration_of(name: str) -> Duration:
    d = _DUR_NAMES.get(name.strip().lower())
    if d is None:
        raise PlanError(f"unknown aggregation duration {name!r}")
    return d


def parse_span_ms(text) -> int:
    """'1 hour' / '90 sec' / bare ms integer -> milliseconds."""
    s = str(text).strip()
    parts = s.split()
    if len(parts) == 2:
        return int(float(parts[0]) * duration_of(parts[1]).approx_millis)
    try:
        return int(s)
    except ValueError:
        raise PlanError(f"cannot parse retention span {text!r} "
                        f"(want e.g. '1 hour' or ms)") from None


def _parse_retention(ad: ast.AggregationDefinition) -> dict:
    """@purge on a `define aggregation` -> {Duration: retention_ms}.

    Forms (reference: @purge/@retentionPeriod on aggregations):
      @purge(retention='1 hour')            uniform retention
      @purge('1 hour')                      same, positional
      @purge(retention='1 hour', sec='2 min')   per-duration override
      @purge(enable='false', ...)           disabled
    Returns {} when absent/disabled — keep every bucket forever."""
    ann = ast.find_annotation(ad.annotations, "purge")
    if ann is None:
        return {}
    if str(ann.element("enable", "true")).lower() in ("false", "off"):
        return {}
    out: dict = {}
    default = ann.element("retention")
    if default is not None:
        for d in ad.durations:
            out[d] = parse_span_ms(default)
    seen = set()
    for name, dur in _DUR_NAMES.items():
        if dur in seen or dur not in ad.durations:
            continue
        v = ann.element(name) if len(ann.elements) > 1 or default is None \
            else None
        if v is not None and v != default:
            out[dur] = parse_span_ms(v)
            seen.add(dur)
    if not out:
        raise PlanError(
            f"aggregation {ad.id!r}: @purge needs a retention span "
            f"(e.g. @purge(retention='1 hour'))")
    return out


def bucket_starts(ts: np.ndarray, dur: Duration) -> np.ndarray:
    """Vectorized bucket start (ms) per timestamp; months/years use
    calendar boundaries via numpy datetime64 truncation (the reference
    uses Calendar arithmetic: IncrementalTimeConverterUtil)."""
    if dur == Duration.MONTHS:
        d = ts.astype("datetime64[ms]").astype("datetime64[M]")
        return d.astype("datetime64[ms]").astype(np.int64)
    if dur == Duration.YEARS:
        d = ts.astype("datetime64[ms]").astype("datetime64[Y]")
        return d.astype("datetime64[ms]").astype(np.int64)
    w = dur.approx_millis
    return (ts // w) * w


class _Site:
    """One aggregator call site in the aggregation's selector."""
    __slots__ = ("name", "key", "arg", "arg_fn", "in_type", "out_type")

    def __init__(self, name, key, arg, arg_fn, in_type, out_type):
        self.name = name          # sum/count/avg/min/max
        self.key = key            # env placeholder "__agg<i>"
        self.arg = arg            # column name if plain Variable, else None
        self.arg_fn = arg_fn      # per-row fallback evaluator
        self.in_type = in_type
        self.out_type = out_type


class AggregationRuntime(QueryPlan):
    """Ingest plan + queryable per-duration bucket store."""

    def __init__(self, rt, ad: ast.AggregationDefinition):
        from ..interp.engine import extract_aggregators
        from ..interp.expr import PyExprContext, compile_py

        self.rt = rt
        self.ad = ad
        self.name = f"#aggregation_{ad.id}"
        inp = ad.input
        if inp.stream_id not in rt.schemas:
            raise PlanError(f"aggregation {ad.id!r}: unknown input stream "
                            f"{inp.stream_id!r}")
        if inp.window is not None:
            raise PlanError(f"aggregation {ad.id!r}: windows not allowed")
        self.in_schema = rt.schemas[inp.stream_id]
        self.input_streams = (inp.stream_id,)
        self.output_target = None
        self.durations = tuple(ad.durations)
        if not self.durations:
            raise PlanError(f"aggregation {ad.id!r}: no durations")

        ctx = PyExprContext({inp.alias: self.in_schema,
                             inp.stream_id: self.in_schema},
                            default_ref=inp.alias, tables=rt.tables)
        self.filters = [compile_py(f.expr, ctx)[0] for f in inp.filters]

        # event-time source (reference: `aggregate by <attr>`)
        self.by_attr = None
        if ad.by_attribute is not None:
            self.by_attr = ad.by_attribute.attribute
            t = self.in_schema.type_of(self.by_attr)
            if t != AttrType.LONG:
                raise PlanError(f"aggregation {ad.id!r}: aggregate-by "
                                f"attribute must be long (epoch ms)")

        # group-by columns (plain variables, reference restriction)
        self.group_attrs: list[str] = []
        for g in ad.selector.group_by:
            if g.stream_ref not in (None, inp.alias, inp.stream_id):
                raise PlanError(f"aggregation {ad.id!r}: bad group-by ref")
            self.group_attrs.append(g.attribute)

        # selector: rewrite aggregator calls into placeholder sites
        if ad.selector.select_all:
            raise PlanError(f"aggregation {ad.id!r}: select * not allowed; "
                            f"name the aggregates")
        raw_sites: list = []
        rewritten: list[tuple[str, ast.Expression]] = []
        for oa in ad.selector.attributes:
            rewritten.append((oa.name,
                              extract_aggregators(oa.expr, raw_sites, ctx)))
        self.sites: list[_Site] = []
        for i, s in enumerate(raw_sites):
            if s.name not in _BASES:
                raise PlanError(
                    f"aggregation {ad.id!r}: {s.name}() has no incremental "
                    f"decomposition (reference supports sum/count/avg/min/max)")
            self.sites.append(_Site(s.name, s.key, None,
                                    s.arg_fns[0] if s.arg_fns else None,
                                    s.in_type, s.out_type))
        # plain-variable fast path for site args
        site_i = 0
        def scan_args(e):
            nonlocal site_i
            if isinstance(e, ast.FunctionCall) and e.namespace is None \
                    and e.name.lower() in _BASES:
                if len(e.args) == 1 and isinstance(e.args[0], ast.Variable) \
                        and e.args[0].attribute in self.in_schema.types:
                    self.sites[site_i].arg = e.args[0].attribute
                site_i += 1
                return
            for sub in getattr(e, "args", ()) or ():
                scan_args(sub)
            for nm in ("left", "right", "expr"):
                sub = getattr(e, nm, None)
                if isinstance(sub, ast.Expression):
                    scan_args(sub)
        for oa in ad.selector.attributes:
            scan_args(oa.expr)

        # output row evaluators over {group attrs, AGG_TIMESTAMP, __agg*}
        extra = {a: (a, self.in_schema.type_of(a)) for a in self.group_attrs}
        extra[AGG_TIMESTAMP] = (AGG_TIMESTAMP, AttrType.LONG)
        extra.update({s.key: (s.key, s.out_type) for s in self.sites})
        octx = PyExprContext({}, extra=extra, tables=rt.tables)
        self.out_fns: list = []
        names, types = [], []
        for nm, expr in rewritten:
            f, t = compile_py(expr, octx)
            self.out_fns.append(f)
            names.append(nm)
            types.append(t)
        self.out_schema = StreamSchema(ad.id, tuple(
            ast.Attribute(n, t) for n, t in zip(names, types)))

        # per-duration bucket stores:
        # (bucket_start_ms, group_key_tuple) -> [base floats ...]
        self.n_bases = sum(len(_BASES[s.name]) for s in self.sites)
        self.store: dict = {d: {} for d in self.durations}

        # @purge retention (reference: @purge/@retentionPeriod on the
        # aggregation definition): buckets whose start falls behind the
        # newest seen start minus the duration's retention are evicted
        # on ingest.  None = keep forever (and analyzer rule SA15 warns
        # when that meets an unbounded group-by).
        self.retention_ms: dict = _parse_retention(ad)
        self.evicted: dict = {d: 0 for d in self.durations}
        self._newest: dict = {d: None for d in self.durations}

        # Placement (docs/AGGREGATION.md "Device lowering"):
        #   default   device-RESIDENT plan (core/agg_device.py) — bucket
        #             state lives on device, host touch on query only;
        #   'always'  the legacy per-batch device reduce (kernel per
        #             batch, store on host) — kept for mesh sharding;
        #   'off'     host numpy path (also the forced-path differential
        #             lever).  Ineligible shapes (calendar durations,
        #             failed jax import) demote to host with a D-AGG
        #             record visible in rt.explain().
        da = ast.find_annotation(rt.app.annotations, "app:deviceAggregations")
        mode = str(da.element()).lower() if da is not None else "auto"
        calendar = (Duration.MONTHS in self.durations
                    or Duration.YEARS in self.durations)
        self.device = mode in ("always", "true") and not calendar
        self._dev_cache: dict = {}      # padded n -> jitted kernel
        # multi-chip: events shard over devices, each computes its
        # shard's per-(bucket, group) partials, and the commutative base
        # merge (sum/count/min/max) combines them host-side — the same
        # merge that already combines batches into the store
        from .planner import mesh_for
        self._mesh = mesh_for(rt, "shard") if self.device else None
        self.device_plan = None
        if not self.device:
            self._plan_device(rt, ad, mode, calendar)

    def _plan_device(self, rt, ad, mode: str, calendar: bool) -> None:
        """Build the device-resident plan, or record WHY not (D-AGG)."""
        import os
        env = os.environ.get("SIDDHI_AGG_DEVICE", "").lower()
        if mode in ("off", "never", "false", "host"):
            rt.placement.demote(
                ad.id, "D-AGG",
                f"@app:deviceAggregations({mode!r}) chose the host path",
                alternative="device-agg")
            return
        if env in ("0", "off", "host"):
            rt.placement.demote(
                ad.id, "D-AGG",
                "SIDDHI_AGG_DEVICE env opt-out chose the host path",
                alternative="device-agg")
            return
        if calendar:
            rt.placement.demote(
                ad.id, "D-AGG",
                "month/year durations need calendar (datetime64) bucket "
                "truncation — host path",
                alternative="device-agg")
            return
        try:
            from .agg_device import DeviceAggregationPlan
            from .autotune import agg_capacity_for
            cap = agg_capacity_for(rt, payload=None)
            self.device_plan = DeviceAggregationPlan(self, cap)
        except Exception as e:          # jax missing / backend init failed
            rt.placement.demote(
                ad.id, "D-AGG", "device aggregation plan unavailable",
                cause=e, alternative="device-agg")

    # -- ingest (vectorized segmented reduction) -----------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        n = batch.n
        if n == 0:
            return []
        ts = (batch.columns[self.by_attr].astype(np.int64)
              if self.by_attr else batch.timestamps)
        keep = None
        if self.filters:
            rows = batch.rows(self.rt.strings)
            names = self.in_schema.names
            keep = np.fromiter(
                (all(f(dict(zip(names, r), __timestamp__=int(t)))
                     for f in self.filters)
                 for t, r in zip(batch.timestamps, rows)),
                dtype=bool, count=n)
            if not keep.any():
                return []

        # rows whose group key or aggregate argument is NULL would otherwise
        # be bucketed/summed as their fill values (advisor r2): mask them out
        if batch.nulls:
            null_mask = np.zeros(n, dtype=bool)
            for a in self.group_attrs:
                if a in batch.nulls:
                    null_mask |= batch.nulls[a]
            for s in self.sites:
                if s.arg is not None and s.arg in batch.nulls:
                    null_mask |= batch.nulls[s.arg]
            if null_mask.any():
                keep = ~null_mask if keep is None else (keep & ~null_mask)
                if not keep.any():
                    return []

        gcols = [batch.columns[a] for a in self.group_attrs]
        vals = self._site_values(batch)
        if keep is not None:
            ts = ts[keep]
            gcols = [c[keep] for c in gcols]
            vals = [v[keep] for v in vals]

        # integer views of group columns for exact vectorized unique
        gints = [self._int_view(c) for c in gcols]
        if self.device_plan is not None:
            self._ingest_device_resident(ts, gints, gcols, vals)
            self._enforce_retention()
            return []
        if self.device:
            per_dur = self._reduce_device(ts, gints, vals)
        else:
            per_dur = self._reduce_host(ts, gints, vals)
        for dur, (buckets_of, rows_any, reduced) in zip(self.durations,
                                                        per_dur):
            st = self.store[dur]
            for j in range(len(rows_any)):
                r = int(rows_any[j])
                gkey = tuple(self._decode_gval(c[r], a)
                             for c, a in zip(gcols, self.group_attrs))
                key = (int(buckets_of[j]), gkey)
                new = [float(red[j]) for red in reduced]
                old = st.get(key)
                if old is None:
                    st[key] = new
                else:
                    st[key] = self._merge(old, new)
            if len(buckets_of):
                top = int(buckets_of.max())
                if self._newest[dur] is None or top > self._newest[dur]:
                    self._newest[dur] = top
        self._enforce_retention()
        return []

    def _ingest_device_resident(self, ts, gints, gcols, vals) -> None:
        """Per duration: host computes the batch's unique (bucket,
        group) segments (the same np.unique the host reduce uses, so
        keys match bit-for-bit), the device plan segment-reduces the
        bases and scatter-merges them into the resident bucket store —
        no per-event host work, no D2H until somebody queries."""
        vals64 = [np.ascontiguousarray(v, dtype=np.float64) for v in vals]
        for dur in self.durations:
            buckets = bucket_starts(ts, dur)
            segs = np.stack([buckets, *gints], axis=1) if gints \
                else buckets[:, None]
            uniq, inv = np.unique(segs, axis=0, return_inverse=True)
            m = len(uniq)
            first_rows = np.empty(m, dtype=np.int64)
            first_rows[inv[::-1]] = np.arange(len(inv))[::-1]
            gkeys = [tuple(self._decode_gval(c[int(r)], a)
                           for c, a in zip(gcols, self.group_attrs))
                     for r in first_rows]
            self.device_plan.ingest(dur, uniq[:, 0], gkeys,
                                    inv.astype(np.int32), vals64)
            top = int(uniq[:, 0].max())
            if self._newest[dur] is None or top > self._newest[dur]:
                self._newest[dur] = top

    def _enforce_retention(self) -> None:
        """@purge: drop buckets older than newest-start minus retention.
        Device-resident stores evict host-side only (slot frees; the
        stale device row is overwritten on reuse)."""
        if not self.retention_ms:
            return
        for dur in self.durations:
            r = self.retention_ms.get(dur)
            newest = self._newest[dur]
            if r is None or newest is None:
                continue
            cutoff = newest - r
            if self.device_plan is not None:
                self.evicted[dur] += self.device_plan.evict_before(
                    dur, cutoff)
                continue
            st = self.store[dur]
            doomed = [k for k in st if k[0] < cutoff]
            for k in doomed:
                del st[k]
            self.evicted[dur] += len(doomed)

    def _reduce_host(self, ts, gints, vals):
        """numpy segmented reduction; returns per duration
        (bucket_start_per_segment, any_row_of_segment, reduced[nb][m])."""
        out = []
        for dur in self.durations:
            buckets = bucket_starts(ts, dur)
            segs = np.stack([buckets, *gints], axis=1) if gints \
                else buckets[:, None]
            uniq, inv = np.unique(segs, axis=0, return_inverse=True)
            m = len(uniq)
            reduced: list[np.ndarray] = []
            for s, v in zip(self.sites, vals):
                for base in _BASES[s.name]:
                    if base == "sum":
                        reduced.append(np.bincount(inv, weights=v, minlength=m))
                    elif base == "count":
                        reduced.append(np.bincount(inv, minlength=m).astype(float))
                    elif base == "min":
                        acc = np.full(m, np.inf)
                        np.minimum.at(acc, inv, v)
                        reduced.append(acc)
                    elif base == "max":
                        acc = np.full(m, -np.inf)
                        np.maximum.at(acc, inv, v)
                        reduced.append(acc)
            first_rows = np.empty(m, dtype=np.int64)
            first_rows[inv[::-1]] = np.arange(len(inv))[::-1]
            out.append((uniq[:, 0], first_rows, reduced))
        return out

    # -- device segmented reduction (sort + segmented scans; no scatters —
    #    TPU scatters serialize).  One packed i32 pull for ALL durations.
    def _reduce_device(self, ts, gints, vals):
        import jax
        import jax.numpy as jnp

        n = len(ts)
        D = len(self._mesh.devices.ravel()) if self._mesh is not None else 1
        npad = 8 * D
        while npad < n:
            npad *= 2
        L = npad // D
        spans = [d.approx_millis for d in self.durations]
        nb = self.n_bases
        base_ops = [b for s in self.sites for b in _BASES[s.name]]
        val_of_base = []
        for i, s in enumerate(self.sites):
            for _b in _BASES[s.name]:
                val_of_base.append(i)

        fn = self._dev_cache.get(npad)
        if fn is None:
            def kernel(ts64, g64, v32):
                outs_i, outs_f = [], []
                pos = jnp.arange(L, dtype=jnp.int64)
                for w in spans:
                    bucket = (ts64 // w) * w
                    keys = [pos] + [g64[gi] for gi in
                                    range(g64.shape[0])][::-1] + [bucket]
                    order = jnp.lexsort(keys)
                    sb = bucket[order]
                    starts = jnp.concatenate(
                        [jnp.array([True]), sb[1:] != sb[:-1]])
                    for gi in range(g64.shape[0]):
                        sg = g64[gi][order]
                        starts = starts | jnp.concatenate(
                            [jnp.array([True]), sg[1:] != sg[:-1]])
                    rows = []
                    for bi, b in enumerate(base_ops):
                        if b == "count":
                            v = jnp.ones(L, jnp.float32)
                        else:
                            v = v32[val_of_base[bi]][order]
                        if b in ("sum", "count"):
                            # segmented associative scan in f64: a global
                            # f32 prefix difference cancels catastrophically
                            # for large values (advisor finding)
                            def comb_add(a, c):
                                af, av = a
                                cf, cv = c
                                return (af | cf,
                                        jnp.where(cf, cv, av + cv))
                            _f, run = jax.lax.associative_scan(
                                comb_add, (starts, v.astype(jnp.float64)))
                            rows.append(run)
                        else:
                            is_max = b == "max"
                            op = jnp.maximum if is_max else jnp.minimum

                            def comb(a, c):
                                af, av = a
                                cf, cv = c
                                return (af | cf,
                                        jnp.where(cf, cv, op(av, cv)))
                            _f, run = jax.lax.associative_scan(
                                comb, (starts, v.astype(jnp.float64)))
                            rows.append(run)
                    outs_i.append(jnp.stack(
                        [order.astype(jnp.int32), starts.astype(jnp.int32)]))
                    outs_f.append(jnp.stack(rows))
                return {"i": jnp.concatenate(outs_i, axis=0),
                        "f": jnp.concatenate(outs_f, axis=0)}
            if D == 1:
                fn = jax.jit(kernel)
            else:
                # shard axis 0 over the mesh: every device reduces its
                # own event shard in parallel; partials merge host-side
                from jax.sharding import NamedSharding, PartitionSpec
                sh = NamedSharding(self._mesh, PartitionSpec("shard"))
                fn = jax.jit(jax.vmap(kernel),
                             in_shardings=(sh, sh, sh), out_shardings=sh)
            self._dev_cache[npad] = fn

        ts_p = np.full(npad, np.int64(2**62))
        ts_p[:n] = ts
        g_p = np.zeros((len(gints), npad), np.int64)
        for i, g in enumerate(gints):
            g_p[i, :n] = g
        v_p = np.zeros((len(vals), npad), np.float32)
        for i, v in enumerate(vals):
            v_p[i, :n] = v
        if D == 1:
            res = fn(ts_p, g_p, v_p)
        else:
            res = fn(ts_p.reshape(D, L),
                     g_p.reshape(len(gints), D, L).swapaxes(0, 1),
                     v_p.reshape(len(vals), D, L).swapaxes(0, 1))
        from .pipeline import start_d2h
        start_d2h(res, keys=("i",))
        ipack = np.asarray(res["i"])
        fpack = np.asarray(res["f"])
        out = []
        for di, dur in enumerate(self.durations):
            parts = ([], [], [[] for _ in range(nb)])
            for s in range(D):
                ip = ipack if D == 1 else ipack[s]
                fp = fpack if D == 1 else fpack[s]
                n_s = min(max(n - s * L, 0), L)
                if n_s == 0:
                    continue
                order = ip[2 * di]
                starts = ip[2 * di + 1] != 0
                runs = fp[di * nb:(di + 1) * nb]
                sidx = np.flatnonzero(starts)
                sidx = sidx[sidx < n_s]         # drop padding segments
                ends = np.concatenate([sidx[1:], [n_s]]) - 1
                rows_any = order[sidx] + s * L
                parts[0].append(bucket_starts(ts[rows_any], dur))
                parts[1].append(rows_any)
                for bi in range(nb):
                    parts[2][bi].append(runs[bi][ends])
            out.append((np.concatenate(parts[0]),
                        np.concatenate(parts[1]),
                        [np.concatenate(p) for p in parts[2]]))
        return out

    def _merge(self, a: list, b: list) -> list:
        out = []
        i = 0
        for s in self.sites:
            for base in _BASES[s.name]:
                if base in ("sum", "count"):
                    out.append(a[i] + b[i])
                elif base == "min":
                    out.append(min(a[i], b[i]))
                else:
                    out.append(max(a[i], b[i]))
                i += 1
        return out

    def _site_values(self, batch: EventBatch) -> list:
        vals = []
        rows = None
        for s in self.sites:
            if s.name == "count" or s.arg_fn is None:
                vals.append(np.ones(batch.n))
            elif s.arg is not None:
                vals.append(batch.columns[s.arg].astype(np.float64))
            else:
                if rows is None:
                    rows = batch.rows(self.rt.strings)
                names = self.in_schema.names
                vals.append(np.fromiter(
                    (float(s.arg_fn(dict(zip(names, r)))) for r in rows),
                    dtype=np.float64, count=batch.n))
        return vals

    @staticmethod
    def _int_view(col: np.ndarray) -> np.ndarray:
        if col.dtype.kind in "iub":
            return col.astype(np.int64)
        if col.dtype.kind == "f":
            v = col.astype(np.float64)
            v = np.where(v == 0.0, 0.0, v)     # -0.0 keys with +0.0
            return v.view(np.int64)            # exact bit key otherwise
        raise PlanError("unsupported group-by column type")

    @staticmethod
    def _decode_gval(v, attr: str):
        # unwrap numpy scalars for stable dict keys; string codes decode
        # lazily in rows_between
        return v.item() if isinstance(v, np.generic) else v

    # -- query side (within/per selection) -----------------------------------

    def _materialize(self) -> None:
        """Pull device-resident bucket state into the host dict stores
        (no-op on the host path, and per-duration dirty-gated on the
        device path) — every read surface (store queries, snapshots)
        calls this first so both paths share one store format."""
        if self.device_plan is not None:
            self.device_plan.sync_into(self.store)

    def rows_between(self, per: Duration, t0: Optional[int],
                     t1: Optional[int]) -> list:
        """Output rows [(bucket_start, env)] for buckets of `per` whose
        start lies in [t0, t1)."""
        if per not in self.store:
            raise PlanError(
                f"aggregation {self.ad.id!r}: per-duration {per.value!r} not "
                f"in defined range {[d.value for d in self.durations]}")
        self._materialize()
        out = []
        for (start, gkey), bases in sorted(self.store[per].items()):
            if t0 is not None and start < t0:
                continue
            if t1 is not None and start >= t1:
                continue
            env = {AGG_TIMESTAMP: start, "__timestamp__": start}
            for a, v in zip(self.group_attrs, gkey):
                if self.in_schema.type_of(a) == AttrType.STRING:
                    v = self.rt.strings.decode(int(v))
                env[a] = v
            i = 0
            for s in self.sites:
                b = _BASES[s.name]
                if s.name == "avg":
                    sm, ct = bases[i], bases[i + 1]
                    env[s.key] = (sm / ct) if ct else None
                elif s.name == "count":
                    env[s.key] = int(bases[i])
                elif s.name in ("min", "max"):
                    env[s.key] = self._cast(bases[i], s.in_type)
                else:
                    env[s.key] = self._cast(bases[i], s.out_type)
                i += len(b)
            row_env = dict(env)
            row = [f(env) for f in self.out_fns]
            for nm, v in zip(self.out_schema.names, row):
                row_env[nm] = v
            out.append((start, row_env, row))
        return out

    @staticmethod
    def _cast(v: float, t: Optional[AttrType]):
        if t in (AttrType.INT, AttrType.LONG):
            return int(v)
        return float(v)

    # -- store-query support (reference: StoreQueryParser aggregation path) --

    def compile_store_query(self, sq: ast.StoreQuery):
        return _AggStoreExec(self, sq)

    # -- snapshot ------------------------------------------------------------

    def state_dict(self) -> dict:
        self._materialize()
        return {"store": {d.value: {k: list(v) for k, v in s.items()}
                          for d, s in self.store.items()}}

    def load_state_dict(self, d: dict) -> None:
        by_val = {x.value: x for x in Duration}
        self.store = {by_val[dv]: {k: list(v) for k, v in s.items()}
                      for dv, s in d["store"].items()}
        for dur in self.durations:           # tolerate missing durations
            self.store.setdefault(dur, {})
        for dur, st in self.store.items():
            self._newest[dur] = (max(k[0] for k in st) if st else None)
        if self.device_plan is not None:
            self.device_plan.load_from(self.store)

    # -- telemetry (statistics()["aggregation"] / siddhi_tpu_agg_*) ----------

    def group_count(self) -> int:
        """Distinct live group keys, measured on the finest duration
        (group cardinality is duration-invariant until retention evicts
        a key's last bucket)."""
        fine = self.durations[0]
        if self.device_plan is not None:
            keys = self.device_plan.rings[fine].key_to_slot
        else:
            keys = self.store[fine]
        return len({g for (_b, g) in keys})

    def metrics(self) -> dict:
        durs = {}
        for d in self.durations:
            live = (self.device_plan.live_buckets(d)
                    if self.device_plan is not None
                    else len(self.store[d]))
            ent = {"buckets": live, "evicted": self.evicted[d]}
            if self.device_plan is not None:
                ent["capacity"] = self.device_plan.capacity(d)
            r = self.retention_ms.get(d) if self.retention_ms else None
            if r is not None:
                ent["retention_ms"] = r
            durs[d.name] = ent
        return {"device": bool(self.device or self.device_plan is not None),
                "resident": self.device_plan is not None,
                "groups": self.group_count(),
                "durations": durs}


# ---------------------------------------------------------------------------
# within / per evaluation (shared by store queries and joins)
# ---------------------------------------------------------------------------

def parse_time_point(v) -> int:
    """'2017-06-01 04:05:50' / epoch-ms long -> epoch ms (UTC)."""
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, str):
        s = v.strip()
        for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%d %H:%M", "%Y-%m-%d"):
            try:
                t = _dt.datetime.strptime(s, fmt).replace(
                    tzinfo=_dt.timezone.utc)
                return int(t.timestamp() * 1000)
            except ValueError:
                continue
    raise PlanError(f"cannot interpret time point {v!r}")


def within_range_of(expr, value_fn_compiler, now_fn) -> Callable:
    """Compile a `within` clause to env -> (t0, t1).

    Forms: `within start, end` (two points), `within '2017-06-** ...'`
    (wildcard pattern -> covered range), `within 1 day` (trailing window
    ending now)."""
    if expr is None:
        return lambda env: (None, None)
    if isinstance(expr, ast.FunctionCall) and expr.name == "withinRange":
        f0 = value_fn_compiler(expr.args[0])
        f1 = value_fn_compiler(expr.args[1])
        return lambda env: (parse_time_point(f0(env)),
                            parse_time_point(f1(env)))
    if isinstance(expr, ast.TimeConstant):
        ms = expr.millis
        return lambda env: (now_fn() - ms, None)
    f = value_fn_compiler(expr)

    def rng(env):
        v = f(env)
        if isinstance(v, str) and "*" in v:
            return _wildcard_range(v)
        t0 = parse_time_point(v)
        return (t0, None)
    return rng


def _wildcard_range(pat: str) -> tuple[int, int]:
    """'2017-06-** **:**:**' -> (start, end) of the covered span, derived
    component-wise: wildcards floor to their minimum for the start, and
    the finest fully-specified component is incremented for the end."""
    pat = pat.strip()
    if len(pat) == 10:                  # date only
        pat = pat + " **:**:**"
    comps = _split_dt(pat)
    lo_v = []
    hi_v = []
    mins = [1, 1, 1, 0, 0, 0]
    for i, (c, mn) in enumerate(zip(comps, mins)):
        if "*" in c:
            lo_v.append(mn)
            hi_v.append(None)
        else:
            lo_v.append(int(c))
            hi_v.append(int(c))
    start = _dt.datetime(lo_v[0], lo_v[1], lo_v[2], lo_v[3], lo_v[4],
                         lo_v[5], tzinfo=_dt.timezone.utc)
    # end: increment the finest fully-specified component
    last_fixed = max(i for i, h in enumerate(hi_v) if h is not None)
    end = start
    if last_fixed == 0:
        end = start.replace(year=start.year + 1)
    elif last_fixed == 1:
        end = (start.replace(day=1) + _dt.timedelta(days=32)).replace(day=1)
    elif last_fixed == 2:
        end = start + _dt.timedelta(days=1)
    elif last_fixed == 3:
        end = start + _dt.timedelta(hours=1)
    elif last_fixed == 4:
        end = start + _dt.timedelta(minutes=1)
    else:
        end = start + _dt.timedelta(seconds=1)
    return int(start.timestamp() * 1000), int(end.timestamp() * 1000)


def _split_dt(pat: str) -> list:
    """'YYYY-MM-DD HH:MM:SS' -> 6 components."""
    date, _, time = pat.partition(" ")
    d = (date.split("-") + ["**", "**"])[:3]
    t = (time.split(":") + ["**", "**", "**"])[:3] if time else ["**"] * 3
    return d + t


def per_duration_of(expr, ctx=None) -> Duration:
    if isinstance(expr, ast.Constant):
        return duration_of(str(expr.value))
    if isinstance(expr, ast.Variable) and expr.stream_ref is None:
        return duration_of(expr.attribute)
    raise PlanError("per must be a constant duration like 'seconds'")


class _AggStoreExec:
    """`from A [on cond] within ... per ... select ...`"""

    def __init__(self, agg: AggregationRuntime, sq: ast.StoreQuery):
        from ..interp.expr import PyExprContext, compile_py
        self.agg = agg
        if sq.per is None:
            raise PlanError("aggregation store query needs `per`")
        self.per = per_duration_of(sq.per)
        empty = PyExprContext({}, tables=agg.rt.tables)
        self.within_fn = within_range_of(
            sq.within, lambda e: compile_py(e, empty)[0],
            lambda: agg.rt.now_ms())
        octx = PyExprContext({agg.ad.id: agg.out_schema},
                             default_ref=agg.ad.id, tables=agg.rt.tables)
        on = None
        for f in sq.input.filters:
            on = f.expr if on is None else ast.And(on, f.expr)
        self.cond = compile_py(on, octx)[0] if on is not None else None
        sel = sq.selector
        if sel.select_all:
            self.sel_fns = None
            self.out_schema = agg.out_schema
        else:
            extra = {a.name: (a.name, a.type)
                     for a in agg.out_schema.attributes}
            extra[AGG_TIMESTAMP] = (AGG_TIMESTAMP, AttrType.LONG)
            sctx = PyExprContext({}, extra=extra, tables=agg.rt.tables)
            self.sel_fns = []
            names, types = [], []
            for oa in sel.attributes:
                f, t = compile_py(oa.expr, sctx)
                self.sel_fns.append(f)
                names.append(oa.name)
                types.append(t)
            self.out_schema = StreamSchema(f"#store_{agg.ad.id}", tuple(
                ast.Attribute(n, t) for n, t in zip(names, types)))

    def execute(self) -> list:
        t0, t1 = self.within_fn({})
        out = []
        for start, row_env, row in self.agg.rows_between(self.per, t0, t1):
            if self.cond is not None and not self.cond(row_env):
                continue
            if self.sel_fns is None:
                out.append((start, tuple(row)))
            else:
                out.append((start, tuple(f(row_env) for f in self.sel_fns)))
        return out
