"""Partitions: `partition with (expr of Stream, ...) begin ... end`.

Two execution strategies, chosen per inner query:

1. Device axis (the TPU-native one): pattern/sequence queries whose input
   streams all carry value partition keys lower to ONE DevicePatternPlan
   whose partition axis P holds every key — thousands of per-key NFA
   instances advanced by one kernel, shardable across chips.  This is the
   framework's data-parallelism story (reference instead lazily clones the
   whole query graph per key: core:partition/PartitionRuntime.java:257-306,
   PartitionStreamReceiver.java:81-199).

2. Host clones (general fallback): the inner query's AST is rewritten per
   key — input/output stream ids get a per-instance synthetic prefix
   ("#p<idx>/<key#>/Stream") — and planned like any other query; a group
   receiver splits arriving batches by key (preserving global seqs, which
   carry cross-stream order into pattern instances) and republishes them
   under the synthetic ids.  Inner `#streams` are instance-local by the
   same renaming.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np

from ..query import ast
from .batch import EventBatch
from .planner import PlanError, QueryPlan
from .schema import StreamSchema


def _rewrite_state(elem, ren: Callable):
    if isinstance(elem, ast.StreamStateElement):
        return dataclasses.replace(elem, stream=ren(elem.stream))
    if isinstance(elem, ast.AbsentStreamStateElement):
        return dataclasses.replace(elem, stream=ren(elem.stream))
    if isinstance(elem, ast.CountStateElement):
        return dataclasses.replace(elem, stream=_rewrite_state(elem.stream, ren))
    if isinstance(elem, ast.LogicalStateElement):
        return dataclasses.replace(elem, left=_rewrite_state(elem.left, ren),
                                   right=_rewrite_state(elem.right, ren))
    if isinstance(elem, ast.NextStateElement):
        return dataclasses.replace(elem, state=_rewrite_state(elem.state, ren),
                                   next=_rewrite_state(elem.next, ren))
    if isinstance(elem, ast.EveryStateElement):
        return dataclasses.replace(elem, state=_rewrite_state(elem.state, ren))
    raise PlanError(f"cannot rewrite state element {type(elem).__name__}")


def rewrite_query(q: ast.Query, rename: dict) -> ast.Query:
    """Clone a query AST with stream ids substituted (aliases preserved)."""

    def ren_stream(s: ast.SingleInputStream) -> ast.SingleInputStream:
        key = f"#{s.stream_id}" if s.is_inner else s.stream_id
        new_id = rename.get(key)
        if new_id is None:
            return s
        # keep references resolving against the original name
        return dataclasses.replace(s, stream_id=new_id, is_inner=False,
                                   ref_id=s.ref_id or s.stream_id)

    inp = q.input
    if isinstance(inp, ast.SingleInputStream):
        inp = ren_stream(inp)
    elif isinstance(inp, ast.StateInputStream):
        inp = dataclasses.replace(inp, state=_rewrite_state(inp.state, ren_stream))
    elif isinstance(inp, ast.JoinInputStream):
        inp = dataclasses.replace(inp, left=ren_stream(inp.left),
                                  right=ren_stream(inp.right))
    else:
        raise PlanError(f"partition: unsupported input {type(inp).__name__}")
    out = q.output
    tgt = _output_key(out)
    if tgt is not None and tgt in rename:
        kw = {"target": rename[tgt]}
        if getattr(out, "is_inner", False):
            kw["is_inner"] = False
        out = dataclasses.replace(out, **kw)
    return dataclasses.replace(q, input=inp, output=out)


def _output_key(out) -> Optional[str]:
    tgt = getattr(out, "target", None)
    if tgt is None:
        return None
    return f"#{tgt}" if getattr(out, "is_inner", False) else tgt


def input_stream_ids(q: ast.Query) -> list:
    """Input stream ids; inner (#) streams come back with a '#' prefix."""
    def sid_of(s: ast.SingleInputStream) -> str:
        return f"#{s.stream_id}" if s.is_inner else s.stream_id

    inp = q.input
    if isinstance(inp, ast.SingleInputStream):
        return [sid_of(inp)]
    if isinstance(inp, ast.JoinInputStream):
        return [sid_of(inp.left), sid_of(inp.right)]
    if isinstance(inp, ast.StateInputStream):
        out: list = []

        def walk(e):
            if isinstance(e, ast.StreamStateElement):
                out.append(sid_of(e.stream))
            elif isinstance(e, ast.AbsentStreamStateElement):
                out.append(sid_of(e.stream))
            elif isinstance(e, ast.CountStateElement):
                walk(e.stream)
            elif isinstance(e, ast.LogicalStateElement):
                walk(e.left)
                walk(e.right)
            elif isinstance(e, ast.NextStateElement):
                walk(e.state)
                walk(e.next)
            elif isinstance(e, ast.EveryStateElement):
                walk(e.state)
        walk(inp.state)
        return out
    raise PlanError(f"partition: unsupported input {type(inp).__name__}")


class PartitionGroup(QueryPlan):
    """Routes keyed events to per-key query instances (strategy 2) and owns
    lazily-created clones.  Device-axis pattern plans (strategy 1) register
    themselves as ordinary plans and bypass this group entirely."""

    out_schema = None
    output_target = None

    def __init__(self, rt, part: ast.Partition, index: int,
                 clone_queries: list):
        from ..interp.expr import PyExprContext, compile_py
        self.rt = rt
        self.part = part
        self.index = index
        self.name = f"#partition_{index}"
        self.clone_queries = clone_queries      # queries run via cloning
        self.key_fns: dict = {}                 # sid -> fn(env) -> key | None
        for pk in part.keys:
            schema = rt.schemas.get(pk.stream_id)
            if schema is None:
                raise PlanError(f"partition: unknown stream {pk.stream_id!r}")
            ctx = PyExprContext({pk.stream_id: schema}, default_ref=pk.stream_id)
            if pk.expr is not None:
                f, _t = compile_py(pk.expr, ctx)
                self.key_fns[pk.stream_id] = f
            else:
                cases = [(compile_py(c.condition, ctx)[0], c.key)
                         for c in pk.ranges]

                def range_fn(env, _cases=cases):
                    for cond, label in _cases:
                        if cond(env):
                            return label
                    return None                  # no range -> dropped
                self.key_fns[pk.stream_id] = range_fn

        # only route streams the clone-strategy queries actually consume
        needed = {sid for q in clone_queries for sid in input_stream_ids(q)
                  if not sid.startswith("#")}
        missing = needed - set(self.key_fns)
        if missing:
            raise PlanError(
                f"partition: inner queries consume unkeyed streams {sorted(missing)}")
        self.input_streams = tuple(sid for sid in self.key_fns if sid in needed)
        self._key_index: dict = {}               # key -> instance number
        self._instances: set = set()             # instance numbers built

    # -- instance management -------------------------------------------------

    def _syn(self, inst: int, sid: str) -> str:
        base = sid[1:] if sid.startswith("#") else sid
        return f"#p{self.index}/{inst}/{base}"

    def _ensure_instance(self, inst: int) -> None:
        if inst in self._instances:
            return
        self._instances.add(inst)
        from .build import plan_query
        rt = self.rt
        # synthetic schemas for this instance's renamed streams
        rename: dict = {}
        inner_ids = set()
        for q in self.clone_queries:
            for sid in input_stream_ids(q):
                inner_ids.add(sid)
            tgt = _output_key(q.output)
            if tgt is not None and tgt.startswith("#"):
                inner_ids.add(tgt)
        for sid in inner_ids:
            if sid.startswith("#") or sid in self.key_fns:
                rename[sid] = self._syn(inst, sid)
        for sid, syn in rename.items():
            if syn not in rt.schemas and sid in rt.schemas:
                rt.schemas[syn] = StreamSchema(
                    syn, rt.schemas[sid].attributes)
        for qi, q in enumerate(self.clone_queries):
            q2 = rewrite_query(q, rename)
            base = q.name(f"query_p{self.index}_{qi}")
            plan = plan_query(rt, q2, default_name=base)
            plan.name = f"{base}#{inst}"
            plan.callback_name = base
            rt._register_plan(plan)

    # -- QueryPlan interface -------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        if batch.n == 0:
            return []
        fn = self.key_fns[stream_id]
        rows = batch.rows(self.rt.strings)
        names = batch.schema.names
        keys = []
        for ts, row in zip(batch.timestamps, rows):
            env = dict(zip(names, row))
            env["__timestamp__"] = int(ts)
            keys.append(fn(env))
        arr = np.asarray([self._key_index.setdefault(k, len(self._key_index))
                          if k is not None else -1 for k in keys],
                         dtype=np.int64)
        for inst in np.unique(arr):
            if inst < 0:
                continue
            inst = int(inst)
            self._ensure_instance(inst)
            m = arr == inst
            sub = EventBatch(
                batch.schema, batch.timestamps[m],
                {k: v[m] for k, v in batch.columns.items()}, int(m.sum()),
                batch.seqs[m] if batch.seqs is not None else None)
            # direct enqueue preserves original seqs (cross-stream order
            # matters inside pattern instances); _emit would re-stamp them
            self.rt._pending.append((self._syn(inst, stream_id), sub))
        return []

    def state_dict(self) -> dict:
        # keys are plain hashables (str/int/float/bool) — store them as-is
        return {"key_index": list(self._key_index.items())}

    def load_state_dict(self, d: dict) -> None:
        self._key_index = dict(d["key_index"])
        for inst in set(self._key_index.values()):
            self._ensure_instance(inst)


def plan_partition(rt, part: ast.Partition, index: int) -> None:
    """Split inner queries between the device partition axis and host
    clones, then register the group receiver (if any clones remain)."""
    from .build import output_target_of
    from .pattern_plan import DevicePatternPlan
    from .nfa_device import DeviceNFAUnsupported

    mode = getattr(rt, "device_patterns", "auto")
    value_keys = {pk.stream_id: pk.expr for pk in part.keys
                  if pk.expr is not None}
    clone_queries: list = []
    for qi, q in enumerate(part.queries):
        used = None
        name = q.name(f"query_p{index}_{qi}")
        if isinstance(q.input, ast.StateInputStream) and mode != "never":
            sids = set(input_stream_ids(q))
            if all(s in value_keys for s in sids):
                try:
                    key_fns = {s: _columnar_key_fn(rt, s, value_keys[s])
                               for s in sids}
                    plan = DevicePatternPlan(
                        name, rt, q, q.input, output_target_of(q),
                        partitions=rt.partition_capacity,
                        part_key_fns=key_fns, slots=rt.device_slots)
                    rt._register_plan(plan)
                    used = True
                except (DeviceNFAUnsupported, PlanError) as e:
                    if mode == "always":   # device-or-error, no silent clone
                        raise
                    rt.placement.demote(
                        name, "D-PARTITION",
                        "partitioned pattern fell back to per-key host "
                        "clones", cause=e, alternative="device-pattern")
                    used = False
            else:
                if mode == "always":
                    raise PlanError(
                        f"devicePatterns('always'): partition pattern consumes "
                        f"streams without value keys ({sorted(sids - set(value_keys))})")
                rt.placement.demote(
                    name, "D-PARTITION",
                    f"pattern consumes streams without value partition "
                    f"keys ({sorted(sids - set(value_keys))}); per-key "
                    f"host clones", alternative="device-pattern")
        elif isinstance(q.input, ast.StateInputStream):
            rt.placement.demote(name, "D-POLICY",
                                "@app:devicePatterns('never')",
                                alternative="device-pattern")
        if not used:
            clone_queries.append(q)
    if clone_queries:
        group = PartitionGroup(rt, part, index, clone_queries)
        rt._plans.append(group)
        rt._plan_by_name[group.name] = group
        for sid in group.input_streams:
            rt._subscribers[sid].append(group)
        for qi, q in enumerate(clone_queries):
            rt._known_query_names.add(q.name(f"query_p{index}_{qi}"))


def _columnar_key_fn(rt, stream_id: str, expr: ast.Expression):
    """batch -> np key codes; O(1) column grab for plain variables."""
    schema = rt.schemas[stream_id]
    if isinstance(expr, ast.Variable) and expr.stream_ref in (None, stream_id):
        name = expr.attribute
        if name not in schema.types:
            raise PlanError(f"partition key: unknown attribute {name!r}")
        return lambda batch: batch.columns[name]
    from ..interp.expr import PyExprContext, compile_py
    ctx = PyExprContext({stream_id: schema}, default_ref=stream_id)
    f, _t = compile_py(expr, ctx)
    names = schema.names

    def fn(batch: EventBatch) -> np.ndarray:
        rows = batch.rows(rt.strings)
        return np.asarray([f(dict(zip(names, r))) for r in rows])
    return fn
