"""Expression compiler: AST expression trees -> fused JAX columnar kernels.

The TPU replacement for the reference's interpreted per-event executor tree
(reference: core:executor/ExpressionExecutor.java + ~10k LoC of per-type
executor classes under core:executor/{condition,math,function}/ and
core:util/parser/ExpressionParser.java:231).  Where the reference walks one
executor object per AST node per event, here the whole expression compiles
once into a closed jnp function evaluated over entire columns; XLA fuses the
tree into a handful of vector ops.

Compiled signature:  fn(env: dict[str, jnp.ndarray]) -> jnp.ndarray
where env maps flattened variable keys ("price", "e1.price") to columns.

Type rules follow Java numeric promotion like the reference's typed executor
dispatch (int/long -> trunc division, widest type wins).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax

from ..query import ast
from ..query.ast import AttrType, CompareOp, MathOp

Array = jnp.ndarray
Env = dict


@dataclass
class CompiledExpr:
    fn: Callable[[Env], Array]
    type: AttrType
    # variable env keys this expression reads (for wiring/pruning)
    reads: frozenset
    # True when the whole expression is one bare Variable — only such
    # outputs can be null-reconstructed host-side (a derived expression
    # like `x is null` must EVALUATE the null, not propagate it)
    is_var: bool = False


class ExprError(Exception):
    pass


class ExprContext:
    """Resolves variables / functions for one compilation site."""

    def resolve(self, var: ast.Variable) -> tuple[str, AttrType]:
        raise NotImplementedError

    def resolve_string_constant(self, s: str) -> int:
        """Encode a string literal to its dictionary code."""
        raise NotImplementedError


class SingleStreamContext(ExprContext):
    """Variables resolve against a single stream schema (+ optional alias)."""

    def __init__(self, schema, strings, alias: Optional[str] = None,
                 extra: Optional[dict] = None):
        self.schema = schema
        self.strings = strings
        self.alias = alias or schema.id
        self.extra = extra or {}     # name -> (key, AttrType), e.g. group-by outputs

    def resolve(self, var: ast.Variable) -> tuple[str, AttrType]:
        if var.stream_ref is not None and var.stream_ref not in (self.alias, self.schema.id):
            raise ExprError(
                f"unknown stream reference {var.stream_ref!r} (stream is "
                f"{self.schema.id!r} / alias {self.alias!r})")
        if var.attribute in self.extra and var.stream_ref is None:
            return self.extra[var.attribute]
        return var.attribute, self.schema.type_of(var.attribute)

    def resolve_string_constant(self, s: str) -> int:
        return self.strings.encode(s)


class MultiStreamContext(ExprContext):
    """Variables resolve against several named schemas (joins, patterns).

    keys in env are "<ref>.<attr>"; unqualified attrs resolve if unambiguous.
    For pattern count-states, indexed refs ("e1[0].x") get key
    "<ref>[<idx>].<attr>".
    """

    def __init__(self, schemas: dict, strings, extra: Optional[dict] = None):
        self.schemas = schemas       # ref -> StreamSchema
        self.strings = strings
        self.extra = extra or {}

    def resolve(self, var: ast.Variable) -> tuple[str, AttrType]:
        if var.stream_ref is None:
            if var.attribute in self.extra:
                return self.extra[var.attribute]
            hits = [(ref, s) for ref, s in self.schemas.items()
                    if var.attribute in s.types]
            if not hits:
                raise ExprError(f"unknown attribute {var.attribute!r}")
            if len(hits) > 1:
                raise ExprError(
                    f"ambiguous attribute {var.attribute!r} (in "
                    f"{[r for r, _ in hits]}); qualify with stream ref")
            ref, schema = hits[0]
            return f"{ref}.{var.attribute}", schema.type_of(var.attribute)
        ref = var.stream_ref
        if ref not in self.schemas:
            raise ExprError(f"unknown stream reference {ref!r}; have {list(self.schemas)}")
        schema = self.schemas[ref]
        if var.index is not None:
            return (f"{ref}[{var.index}].{var.attribute}",
                    schema.type_of(var.attribute))
        return f"{ref}.{var.attribute}", schema.type_of(var.attribute)

    def resolve_string_constant(self, s: str) -> int:
        return self.strings.encode(s)


# ---------------------------------------------------------------------------
# type algebra (Java numeric promotion, reference ExpressionParser dispatch)
# ---------------------------------------------------------------------------

_NUM_RANK = {AttrType.INT: 0, AttrType.LONG: 1, AttrType.FLOAT: 2, AttrType.DOUBLE: 3}
_RANK_NUM = {v: k for k, v in _NUM_RANK.items()}


def promote(a: AttrType, b: AttrType) -> AttrType:
    if a not in _NUM_RANK or b not in _NUM_RANK:
        raise ExprError(f"cannot apply arithmetic to {a}/{b}")
    return _RANK_NUM[max(_NUM_RANK[a], _NUM_RANK[b])]


_JNP_OF = {
    AttrType.INT: jnp.int32, AttrType.LONG: jnp.int64,
    AttrType.FLOAT: jnp.float32, AttrType.DOUBLE: jnp.float64,
    AttrType.BOOL: jnp.bool_, AttrType.STRING: jnp.int32,
}

# Compute-precision override (device kernels): TPUs emulate f64 on the VPU,
# so hot kernels compute DOUBLE in f32 by default (opt out per app with
# @app:devicePrecision('f64')).  The override is consulted at trace time, so
# wrapping a kernel's trace in `compute_dtypes(...)` retargets every cast and
# constant the compiled expressions emit.
import contextvars as _contextvars
from contextlib import contextmanager as _contextmanager

_DTYPE_OVERRIDES: "_contextvars.ContextVar" = _contextvars.ContextVar(
    "siddhi_dtype_overrides", default=None)


@_contextmanager
def compute_dtypes(overrides: Optional[dict]):
    """Override AttrType -> jnp dtype inside this context (trace-time)."""
    tok = _DTYPE_OVERRIDES.set(overrides)
    try:
        yield
    finally:
        _DTYPE_OVERRIDES.reset(tok)


F32_MODE = {AttrType.DOUBLE: jnp.float32}


def jnp_dtype(t: AttrType):
    o = _DTYPE_OVERRIDES.get()
    if o is not None and t in o:
        return o[t]
    return _JNP_OF[t]


def _cast(x: Array, t: AttrType) -> Array:
    return x.astype(jnp_dtype(t))


# ---------------------------------------------------------------------------
# scalar function registry (extension point; analog of @Extension functions,
# reference: core:executor/function/*, core:util/SiddhiExtensionLoader.java:50)
# ---------------------------------------------------------------------------

# (namespace, name) -> builder(args: list[CompiledExpr], ctx) -> CompiledExpr
SCALAR_FUNCTIONS: dict = {}


def register_scalar_function(name: str, builder, namespace: Optional[str] = None,
                             meta=None):
    from ..extension import register_meta
    register_meta("function", meta)
    SCALAR_FUNCTIONS[(namespace, name.lower())] = builder


def _fn_if_then_else(args, ctx):
    c, a, b = args
    if c.type != AttrType.BOOL:
        raise ExprError("ifThenElse condition must be bool")
    t = a.type if a.type == b.type else promote(a.type, b.type)
    return CompiledExpr(
        lambda env: jnp.where(c.fn(env), _cast(a.fn(env), t), _cast(b.fn(env), t)),
        t, c.reads | a.reads | b.reads)


def _fn_coalesce(args, ctx):
    # device columns have no nulls except string code 0; coalesce picks the
    # first non-zero string code / first arg for numerics.
    t = args[0].type
    if t == AttrType.STRING:
        def fn(env):
            out = args[0].fn(env)
            for a in args[1:]:
                out = jnp.where(out != 0, out, a.fn(env))
            return out
        return CompiledExpr(fn, t, frozenset().union(*[a.reads for a in args]))
    return args[0]


def _make_convert(target: AttrType):
    def build(args, ctx):
        src = args[0]
        return CompiledExpr(lambda env: _cast(src.fn(env), target), target, src.reads)
    return build


def _fn_convert(args, ctx):
    raise ExprError("convert(x, 'type') handled in compile_function")


def _fn_math1(jfn, out_type=None):
    def build(args, ctx):
        a = args[0]
        t = out_type or (AttrType.DOUBLE if a.type in (AttrType.FLOAT, AttrType.DOUBLE)
                         else a.type)
        return CompiledExpr(lambda env: _cast(jfn(a.fn(env)), t), t, a.reads)
    return build


def _fn_minmax(jfn):
    def build(args, ctx):
        t = args[0].type
        for a in args[1:]:
            t = promote(t, a.type)
        def fn(env):
            out = _cast(args[0].fn(env), t)
            for a in args[1:]:
                out = jfn(out, _cast(a.fn(env), t))
            return out
        return CompiledExpr(fn, t, frozenset().union(*[a.reads for a in args]))
    return build


register_scalar_function("ifthenelse", _fn_if_then_else)
register_scalar_function("coalesce", _fn_coalesce)
register_scalar_function("maximum", _fn_minmax(jnp.maximum))
register_scalar_function("minimum", _fn_minmax(jnp.minimum))
register_scalar_function("abs", _fn_math1(jnp.abs), namespace="math")
register_scalar_function("sqrt", _fn_math1(jnp.sqrt, AttrType.DOUBLE), namespace="math")
register_scalar_function("log", _fn_math1(jnp.log, AttrType.DOUBLE), namespace="math")
register_scalar_function("exp", _fn_math1(jnp.exp, AttrType.DOUBLE), namespace="math")
register_scalar_function("floor", _fn_math1(jnp.floor, AttrType.DOUBLE), namespace="math")
register_scalar_function("ceil", _fn_math1(jnp.ceil, AttrType.DOUBLE), namespace="math")
register_scalar_function("round", _fn_math1(jnp.round), namespace="math")
register_scalar_function("sin", _fn_math1(jnp.sin, AttrType.DOUBLE), namespace="math")
register_scalar_function("cos", _fn_math1(jnp.cos, AttrType.DOUBLE), namespace="math")
register_scalar_function("power", _fn_minmax(jnp.power), namespace="math")


# ---------------------------------------------------------------------------
# the compiler
# ---------------------------------------------------------------------------

def compile_expression(expr: ast.Expression, ctx: ExprContext) -> CompiledExpr:
    if isinstance(expr, ast.Constant):
        return _compile_constant(expr, ctx)
    if isinstance(expr, ast.TimeConstant):
        v = jnp.asarray(expr.millis, dtype=jnp.int64)
        return CompiledExpr(lambda env: v, AttrType.LONG, frozenset())
    if isinstance(expr, ast.Variable):
        key, t = ctx.resolve(expr)
        return CompiledExpr(lambda env: env[key], t, frozenset([key]),
                            is_var=True)
    if isinstance(expr, ast.Compare):
        return _compile_compare(expr, ctx)
    if isinstance(expr, ast.And):
        l, r = compile_expression(expr.left, ctx), compile_expression(expr.right, ctx)
        _want_bool(l, r)
        return CompiledExpr(lambda env: l.fn(env) & r.fn(env), AttrType.BOOL,
                            l.reads | r.reads)
    if isinstance(expr, ast.Or):
        l, r = compile_expression(expr.left, ctx), compile_expression(expr.right, ctx)
        _want_bool(l, r)
        return CompiledExpr(lambda env: l.fn(env) | r.fn(env), AttrType.BOOL,
                            l.reads | r.reads)
    if isinstance(expr, ast.Not):
        e = compile_expression(expr.expr, ctx)
        _want_bool(e)
        return CompiledExpr(lambda env: ~e.fn(env), AttrType.BOOL, e.reads)
    if isinstance(expr, ast.Math):
        return _compile_math(expr, ctx)
    if isinstance(expr, ast.FunctionCall):
        return _compile_function(expr, ctx)
    if isinstance(expr, ast.IsNull):
        return _compile_is_null(expr, ctx)
    if isinstance(expr, ast.In):
        raise ExprError("'in Table' must be rewritten by the table planner "
                        "before expression compilation")
    raise ExprError(f"cannot compile expression node {type(expr).__name__}")


def _compile_constant(expr: ast.Constant, ctx: ExprContext) -> CompiledExpr:
    t = expr.type
    if t == AttrType.STRING:
        code = ctx.resolve_string_constant(expr.value)
        v = jnp.asarray(code, dtype=jnp.int32)
        return CompiledExpr(lambda env: v, t, frozenset())
    # dtype resolved at trace time so compute_dtypes() overrides apply
    val = expr.value
    return CompiledExpr(lambda env: jnp.asarray(val, dtype=jnp_dtype(t)),
                        t, frozenset())


def _want_bool(*exprs: CompiledExpr):
    for e in exprs:
        if e.type != AttrType.BOOL:
            raise ExprError(f"expected bool operand, got {e.type}")


def _compile_compare(expr: ast.Compare, ctx: ExprContext) -> CompiledExpr:
    l = compile_expression(expr.left, ctx)
    r = compile_expression(expr.right, ctx)
    if AttrType.STRING in (l.type, r.type):
        if l.type != r.type:
            raise ExprError(f"cannot compare {l.type} with {r.type}")
        if expr.op not in (CompareOp.EQ, CompareOp.NEQ):
            raise ExprError("strings support only ==/!= on device")
        op = {CompareOp.EQ: lambda a, b: a == b,
              CompareOp.NEQ: lambda a, b: a != b}[expr.op]
        return CompiledExpr(lambda env: op(l.fn(env), r.fn(env)), AttrType.BOOL,
                            l.reads | r.reads)
    if AttrType.BOOL in (l.type, r.type):
        if l.type != r.type or expr.op not in (CompareOp.EQ, CompareOp.NEQ):
            raise ExprError(f"bad bool comparison {l.type} {expr.op} {r.type}")
    else:
        t = promote(l.type, r.type)
        lf, rf = l.fn, r.fn
        l = CompiledExpr(lambda env: _cast(lf(env), t), t, l.reads)
        r = CompiledExpr(lambda env: _cast(rf(env), t), t, r.reads)
    ops = {
        CompareOp.LT: lambda a, b: a < b,
        CompareOp.LE: lambda a, b: a <= b,
        CompareOp.GT: lambda a, b: a > b,
        CompareOp.GE: lambda a, b: a >= b,
        CompareOp.EQ: lambda a, b: a == b,
        CompareOp.NEQ: lambda a, b: a != b,
    }
    op = ops[expr.op]
    lf2, rf2 = l.fn, r.fn
    return CompiledExpr(lambda env: op(lf2(env), rf2(env)), AttrType.BOOL,
                        l.reads | r.reads)


def _compile_math(expr: ast.Math, ctx: ExprContext) -> CompiledExpr:
    l = compile_expression(expr.left, ctx)
    r = compile_expression(expr.right, ctx)
    t = promote(l.type, r.type)
    is_int = t in (AttrType.INT, AttrType.LONG)
    lf, rf = l.fn, r.fn
    if expr.op == MathOp.ADD:
        fn = lambda env: _cast(lf(env), t) + _cast(rf(env), t)
    elif expr.op == MathOp.SUB:
        fn = lambda env: _cast(lf(env), t) - _cast(rf(env), t)
    elif expr.op == MathOp.MUL:
        fn = lambda env: _cast(lf(env), t) * _cast(rf(env), t)
    elif expr.op == MathOp.DIV:
        if is_int:
            # Java int division truncates toward zero (lax.div semantics)
            fn = lambda env: lax.div(_cast(lf(env), t), _cast(rf(env), t))
        else:
            fn = lambda env: _cast(lf(env), t) / _cast(rf(env), t)
    elif expr.op == MathOp.MOD:
        # Java % truncated remainder == lax.rem
        fn = lambda env: lax.rem(_cast(lf(env), t), _cast(rf(env), t))
    else:
        raise ExprError(f"unknown math op {expr.op}")
    return CompiledExpr(fn, t, l.reads | r.reads)


# functions resolvable statically at compile time
_CONVERT_TYPES = {"string": AttrType.STRING, "int": AttrType.INT,
                  "long": AttrType.LONG, "float": AttrType.FLOAT,
                  "double": AttrType.DOUBLE, "bool": AttrType.BOOL}


def _compile_function(expr: ast.FunctionCall, ctx: ExprContext) -> CompiledExpr:
    name = expr.name.lower()
    ns = expr.namespace.lower() if expr.namespace else None
    if ns is None and name in ("convert", "cast"):
        src = compile_expression(expr.args[0], ctx)
        if not isinstance(expr.args[1], ast.Constant):
            raise ExprError(f"{name} target type must be a literal")
        target = _CONVERT_TYPES[str(expr.args[1].value).lower()]
        if target == AttrType.STRING or src.type == AttrType.STRING:
            if src.type == target:
                return src
            raise ExprError("string<->numeric conversion is a host-side op")
        return CompiledExpr(lambda env: _cast(src.fn(env), target), target, src.reads)
    if ns is None and name == "eventtimestamp":
        return CompiledExpr(lambda env: env["__timestamp__"], AttrType.LONG,
                            frozenset(["__timestamp__"]))
    if ns is None and name.startswith("instanceof"):
        kind = name[len("instanceof"):]
        src = compile_expression(expr.args[0], ctx)
        expected = {"integer": AttrType.INT, "long": AttrType.LONG,
                    "float": AttrType.FLOAT, "double": AttrType.DOUBLE,
                    "boolean": AttrType.BOOL, "string": AttrType.STRING}.get(kind)
        ok = src.type == expected
        v = jnp.asarray(ok)
        return CompiledExpr(lambda env: jnp.broadcast_to(v, _any_shape(env)),
                            AttrType.BOOL, src.reads)
    builder = SCALAR_FUNCTIONS.get((ns, name))
    if builder is None:
        raise ExprError(f"unknown function {ns or ''}:{name}" if ns
                        else f"unknown function {name}()")
    args = [compile_expression(a, ctx) for a in expr.args]
    return builder(args, ctx)


def _any_shape(env):
    for v in env.values():
        if hasattr(v, "shape") and v.ndim > 0:
            return v.shape
    return ()


def _compile_is_null(expr: ast.IsNull, ctx: ExprContext) -> CompiledExpr:
    if expr.expr is not None:
        e = compile_expression(expr.expr, ctx)
        if e.type == AttrType.STRING:
            return CompiledExpr(lambda env: e.fn(env) == 0, AttrType.BOOL, e.reads)
        # numeric device columns cannot be null
        return CompiledExpr(lambda env: jnp.zeros(_any_shape(env), dtype=bool),
                            AttrType.BOOL, e.reads)
    # `e1 is null` — pattern state presence; resolved by the NFA compiler via
    # a presence column in env.
    ref = expr.stream_ref
    key = f"__present__.{ref}" if expr.index is None else f"__present__.{ref}[{expr.index}]"
    return CompiledExpr(lambda env: ~env[key], AttrType.BOOL, frozenset([key]))
