"""Shared async device-dispatch pipeline.

Every device plan follows the same protocol: build a host env, dispatch
the jitted block (async — the call returns once the device owns the
work), kick off the D2H pull with `copy_to_host_async`, and only later
block on `np.asarray(...)` to materialize the result.  This module owns
the in-flight bookkeeping that used to be re-implemented per plan
(pattern chunks, window aggs, joins, filters):

  * `DispatchPipeline` — the depth-D deferred-materialization queue
    behind `@app:devicePipeline`.  `push()` enqueues a dispatched entry
    and materializes whatever exceeds the configured depth; `drain()`
    is the flush barrier.  `hold()`/`collect()` let the runtime dispatch
    EVERY device plan subscribed to a batch before the first blocking
    pull, so N plans overlap on device even at depth 0 (host/device
    decoupling: the host's build+dispatch of plan B hides plan A's
    compute + readback).
  * `start_d2h` — best-effort async D2H prefetch of packed result
    buffers (the repeated try/except `copy_to_host_async` idiom).
  * `PadPool` — rotating zero-padded upload buffers reused across
    flushes, so padding a micro-batch to its pow2 grid stops allocating
    per flush.  Combined with `EventBatch.padded(...)` memoization,
    N plans subscribed to one stream share ONE pad per column per flush.

Telemetry (always on — two clock reads per entry): per-plan dispatch
count, live/max queue depth, and the overlap accounting behind the
`overlap_ratio` gauge: `overlap_s` is host-side time entries spent in
flight while the host moved on to other work, `wait_s` is the blocking
remainder paid at materialization.  `overlap_ratio ~ 1.0` means the
pipeline fully hid device compute + D2H behind host work; `~ 0.0` means
the host serialized against the device (no overlap).  Exposed through
`StatisticsManager.device_report()` as `dispatch_queue_depth`,
`pipeline_max_depth`, `pipeline_dispatches`, `overlap_ratio`.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np


def _entry_device_nbytes(entry) -> int:
    """Best-effort D2H payload size of one in-flight entry: sum nbytes
    of device arrays (anything exposing copy_to_host_async) found one
    or two levels into the entry tuple — the packed result buffers the
    materialize below will pull."""
    try:
        total = 0
        items = entry if isinstance(entry, (tuple, list)) else (entry,)
        for it in items:
            vals = it.values() if isinstance(it, dict) else (
                it if isinstance(it, (tuple, list)) else (it,))
            for v in vals:
                if hasattr(v, "copy_to_host_async"):
                    total += int(getattr(v, "nbytes", 0) or 0)
        return total
    except Exception:
        return 0


def start_d2h(out, keys=("i", "f", "b")) -> None:
    """Start async device->host copies for the packed result buffers so
    the pull overlaps remaining device compute (best-effort: some
    backends/array types don't support it)."""
    if isinstance(out, dict):
        arrays = [out[k] for k in keys if k in out]
    else:
        arrays = list(out)
    for a in arrays:
        try:
            a.copy_to_host_async()
        except Exception:
            pass


class DispatchPipeline:
    """Depth-D in-flight entry queue shared by all device plans.

    `materialize(entry)` is the plan's blocking pull + unpack; it must
    return an iterable of results (output batches, or raw chunks for the
    pattern plan).  Entries are materialized strictly in dispatch order
    — device results may complete out of order, but delivery is FIFO so
    output ordering matches the unpipelined path exactly.
    """

    __slots__ = ("plan", "depth", "entries", "_materialize", "_t_disp",
                 "_held", "dispatches", "max_depth", "overlap_s", "wait_s",
                 "origin", "_origins", "inject", "_ready", "prof")

    def __init__(self, plan_name: str, materialize: Callable,
                 depth: int = 0):
        self.plan = plan_name
        self.depth = int(depth)
        self._materialize = materialize
        self.entries: list = []
        self._t_disp: list = []        # dispatch-return time per entry
        self._held = False
        self.dispatches = 0
        self.max_depth = 0
        self.overlap_s = 0.0
        self.wait_s = 0.0
        # fault attribution + injection (core/faults.py): the runtime
        # sets `origin` to the (stream_id, batch) a dispatch round is
        # processing; push() snapshots it per entry so a materialization
        # failure D batches later still names the batch it belongs to
        # (@OnError routing stays exact under pipelining).  `inject` is
        # the "d2h" fault-injection hook, wired by _register_plan.
        self.origin = None
        self._origins: list = []
        self.inject: Optional[Callable] = None
        # device-time profiler (core/profiler.py), wired by
        # runtime._register_plan: the blocking pull below is THE
        # d2h_materialize phase (outermost-wins: inner `transfer`
        # stages inside a plan's materialize are suppressed)
        self.prof = None
        # results materialized but not yet handed to the caller: a later
        # entry failing mid-drain must not discard an earlier entry's
        # already-materialized outputs — they survive here and return on
        # the next collect/drain (zero silent loss under @OnError)
        self._ready: list = []

    def __len__(self) -> int:
        return len(self.entries) + len(self._ready)

    # -- dispatch side ---------------------------------------------------

    def push(self, entry) -> list:
        """Enqueue a dispatched entry; materialize (in FIFO order) any
        entries beyond the configured depth — unless a dispatch round is
        held open, in which case they wait for collect()."""
        self.entries.append(entry)
        self._origins.append(self.origin)
        self._t_disp.append(time.perf_counter())
        self.dispatches += 1
        if len(self.entries) > self.max_depth:
            self.max_depth = len(self.entries)
        if self._held:
            return []
        return self._drain_to(self.depth)

    def hold(self) -> None:
        """Open a dispatch round: push() stops auto-materializing until
        collect() — the runtime holds every subscribed plan, dispatches
        them all, then collects, so plans overlap on device."""
        self._held = True

    def set_depth(self, depth: int) -> None:
        """Retarget the in-flight depth (autotune regeometry).  Applied
        at the next push/collect boundary: lowering the depth simply
        materializes more entries there (FIFO, same delivery order), so
        a mid-stream depth change is output-invariant."""
        self.depth = max(0, int(depth))

    def collect(self) -> list:
        """Close a dispatch round: materialize entries beyond depth."""
        self._held = False
        return self._drain_to(self.depth)

    def drain(self) -> list:
        """Flush barrier: materialize EVERYTHING in flight."""
        self._held = False
        return self._drain_to(0)

    def _drain_to(self, target: int) -> list:
        while len(self.entries) > target:
            entry = self.entries.pop(0)
            origin = self._origins.pop(0)
            t_disp = self._t_disp.pop(0)
            t0 = time.perf_counter()
            self.overlap_s += t0 - t_disp
            # frame tracing: a deferred entry still knows the batch it
            # was dispatched for — the materialize span (which may land
            # D batches later, on the scheduler thread) parents on that
            # frame's tree, and the materialized outputs inherit the
            # handle so sink egress stays connected
            od = None if origin is None \
                else getattr(origin[1], "__dict__", None)
            h = None if od is None else od.get("_trace")
            pspan = None
            if self.prof is not None:
                self.prof.note_bytes(self.plan, "d2h",
                                     _entry_device_nbytes(entry))
                pspan = self.prof.phase("d2h_materialize")
                pspan.__enter__()
            try:
                if self.inject is not None:
                    self.inject()       # "d2h" fault-injection point
                res = self._materialize(entry)
                if h is not None:
                    res = list(res)
                    h.mark("materialize", t0, time.perf_counter() - t0,
                          plan=self.plan)
                    for r in res:
                        b = getattr(r, "batch", None)
                        if b is not None:
                            b.__dict__.setdefault("_trace", h)
                self._ready.extend(res)
            except Exception as e:
                # attribute the failure to the batch this entry was
                # dispatched for; the entry is consumed — later entries
                # stay queued and earlier entries' materialized results
                # stay in _ready, so subsequent collects keep flowing
                if origin is not None \
                        and getattr(e, "fault_origin", None) is None:
                    try:
                        e.fault_origin = origin
                    except Exception:
                        pass
                raise
            finally:
                if pspan is not None:
                    pspan.__exit__(None, None, None)
            self.wait_s += time.perf_counter() - t0
        out, self._ready = self._ready, []
        return out

    # -- retry support (plans that must replay the in-flight chain) ------

    def take_all(self) -> list:
        """Remove and return every queued entry (carry-overflow replay:
        the pre-states of everything dispatched after the failed entry
        are invalid and the whole chain re-dispatches)."""
        entries, self.entries, self._t_disp = self.entries, [], []
        self._origins = []
        return entries

    def requeue(self, entries: list) -> None:
        now = time.perf_counter()
        self.entries.extend(entries)
        # re-dispatched replay entries: origin attribution is lost (they
        # aggregate a replayed chain) — fault routing falls back to
        # propagation for these
        self._origins.extend([None] * len(entries))
        self._t_disp.extend([now] * len(entries))

    # -- telemetry -------------------------------------------------------

    def metrics(self) -> dict:
        m = {"dispatch_queue_depth": len(self.entries),
             "pipeline_depth": self.depth,
             "pipeline_max_depth": self.max_depth,
             "pipeline_dispatches": self.dispatches}
        tot = self.overlap_s + self.wait_s
        if tot > 0.0:
            m["overlap_ratio"] = round(self.overlap_s / tot, 4)
            m["pipeline_overlap_s"] = round(self.overlap_s, 4)
            m["pipeline_wait_s"] = round(self.wait_s, 4)
        return m


class PadPool:
    """Rotating pow2-padded upload buffers, reused across flushes.

    `take(key, n, dtype, min_slots)` returns a zeroed-tail (n,) buffer
    for the caller to fill [:batch_n].  Each key rotates through at
    least `min_slots` buffers so an env retained for a pipelined retry
    (up to depth flushes old) is never aliased by a newer flush —
    callers pass min_slots = pipeline_depth + 2.  jax copies numpy
    arguments to device at dispatch, so a buffer is safe to reuse once
    its slot cycles around.
    """

    def __init__(self):
        self._slots: dict = {}     # key -> [bufs, next_index]

    def reserve(self, key, n: int, dtype, min_slots: int) -> None:
        """Grow a key's rotation to at least min_slots without consuming
        a buffer — called on pad-memo hits so a later plan's deeper
        pipeline still widens the rotation it depends on."""
        ent = self._slots.get(key)
        if ent is None:
            ent = self._slots[key] = [[], 0]
        bufs = ent[0]
        while len(bufs) < max(2, min_slots):
            bufs.append(np.zeros(n, dtype=dtype))

    def take(self, key, n: int, dtype, min_slots: int = 2) -> np.ndarray:
        ent = self._slots.get(key)
        if ent is None:
            ent = self._slots[key] = [[], 0]
        bufs, i = ent
        if len(bufs) < max(2, min_slots):
            # two plans with different depths can share a key: the pool
            # grows to the largest requested rotation
            buf = np.zeros(n, dtype=dtype)
            bufs.append(buf)
            return buf
        buf = bufs[i]
        ent[1] = (i + 1) % len(bufs)
        return buf
