"""Multi-query device batching: structurally identical pattern queries
become LANES of one batched NFA kernel.

The reference's "1k concurrent queries over a shared InputHandler"
scenario (BASELINE config 5; reference analog: 1k QueryRuntimes each
walking its own processor chain per event —
core:query/QueryRuntime.java:47) maps naturally onto the TPU kernel's
partition axis: queries that share an AST SHAPE and differ only in
constants (thresholds, within windows, ...) compile once, with every
lifted constant becoming a per-lane (P,) parameter vector.  Every event
broadcasts to all lanes — grids ship as (T, 1) and broadcast on device —
and each emitted match carries its lane id so the host routes it to that
query's output stream.

Grouping is automatic: >= MIN_GROUP StateInputStream queries with equal
shape signatures (and no rate/having/limit) fuse; everything else plans
individually.  `@app:devicePatterns('never')` disables it.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..query import ast

MIN_GROUP = 8


# ---------------------------------------------------------------------------
# shape signature + constant lifting
# ---------------------------------------------------------------------------

def _sig(node, consts: Optional[list] = None):
    """Canonical shape token tree: constants -> type tokens (collected in
    order into `consts` when given)."""
    if isinstance(node, ast.Constant):
        if consts is not None:
            consts.append(node)
        return ("const", node.type.name)
    if isinstance(node, ast.TimeConstant):
        return ("timeconst", node.millis)   # within/for stay literal
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        out = [type(node).__name__]
        for f in dataclasses.fields(node):
            out.append((f.name, _sig(getattr(node, f.name), consts)))
        return tuple(out)
    if isinstance(node, (tuple, list)):
        return tuple(_sig(x, consts) for x in node)
    if isinstance(node, (str, int, float, bool)) or node is None:
        return node
    if isinstance(node, ast.AttrType) or hasattr(node, "name"):
        return getattr(node, "name", str(node))
    return str(node)


def _has_string_const(node) -> bool:
    if isinstance(node, ast.Constant):
        return node.type == ast.AttrType.STRING
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        return any(_has_string_const(getattr(node, f.name))
                   for f in dataclasses.fields(node))
    if isinstance(node, (tuple, list)):
        return any(_has_string_const(x) for x in node)
    return False


def query_signature(q: ast.Query):
    """Hashable shape signature of a pattern query (constants abstracted);
    None when the query can't participate in fusion."""
    if not isinstance(q.input, ast.StateInputStream):
        return None
    if q.rate is not None or q.selector.having is not None \
            or q.selector.group_by or q.selector.order_by \
            or q.selector.limit is not None or q.selector.offset \
            or q.selector.select_all:
        return None
    if not isinstance(q.output, ast.InsertInto):
        return None
    if getattr(q.output, "events_for",
               ast.OutputEventsFor.CURRENT) != ast.OutputEventsFor.CURRENT:
        return None
    if _has_string_const(q.input) or any(_has_string_const(oa.expr)
                                         for oa in q.selector.attributes):
        return None        # string params need interning: not lifted yet
    # output NAMES may differ per query; the target stream SCHEMA shape
    # must match (routing is per-lane)
    return ("pattern", _sig(q.input), _sig(tuple(
        ("attr", _sig(oa.expr)) for oa in q.selector.attributes)))


class _Lifter:
    """Rewrites constants into __qparam<i> variables (resolved through
    ctx.extra) and records each instance's constant values."""

    def __init__(self):
        self.types: list = []       # AttrType per param slot

    def lift(self, node, counter: list):
        if isinstance(node, ast.Constant):
            i = counter[0]
            counter[0] += 1
            if i == len(self.types):
                self.types.append(node.type)
            return ast.Variable(f"__qparam{i}")
        if isinstance(node, ast.TimeConstant):
            # time constants stay literal: `within 1 sec` feeds the
            # kernel's per-position within, parameterized separately
            return node
        if dataclasses.is_dataclass(node) and not isinstance(node, type):
            changes = {}
            for f in dataclasses.fields(node):
                v = getattr(node, f.name)
                nv = self.lift(v, counter)
                if nv is not v:
                    changes[f.name] = nv
            return dataclasses.replace(node, **changes) if changes else node
        if isinstance(node, tuple):
            out = tuple(self.lift(x, counter) for x in node)
            return out if any(a is not b for a, b in zip(out, node)) else node
        return node

    @staticmethod
    def const_values(node, acc: list):
        if isinstance(node, ast.Constant):
            acc.append(node.value)
            return
        if isinstance(node, ast.TimeConstant):
            return
        if dataclasses.is_dataclass(node) and not isinstance(node, type):
            for f in dataclasses.fields(node):
                _Lifter.const_values(getattr(node, f.name), acc)
        elif isinstance(node, (tuple, list)):
            for x in node:
                _Lifter.const_values(x, acc)


def plan_query_group(rt, queries: list, names: list):
    """Build one MultiQueryDevicePatternPlan for a same-shape group.
    queries: [(ast.Query)] — returns the plan or raises
    DeviceNFAUnsupported to fall back to per-query planning."""
    from .nfa_device import DeviceNFAUnsupported
    from .pattern_plan import DevicePatternPlan

    proto = queries[0]
    lifter2 = _Lifter()
    counter = [0]
    lifted = _lift_query(proto, lifter2, counter)
    n_params = counter[0]

    # per-instance parameter matrix (P queries x n_params)
    values = []
    for q in queries:
        acc: list = []
        _Lifter.const_values(q.input, acc)
        for oa in q.selector.attributes:
            _Lifter.const_values(oa.expr, acc)
        if len(acc) != n_params:
            raise DeviceNFAUnsupported("constant-count mismatch in group")
        values.append(acc)

    for q in queries:
        if _target_of(q) in rt.tables:
            raise DeviceNFAUnsupported("fused query targets a table")
    plan = MultiQueryDevicePatternPlan(
        names[0] + f"__x{len(queries)}", rt, lifted, lifted.input,
        param_types=lifter2.types, param_values=values,
        targets=[_target_of(q) for q in queries],
        out_names=[[oa.name for oa in q.selector.attributes]
                   for q in queries],
        query_names=names)
    return plan


def _lift_query(q: ast.Query, lifter: _Lifter, counter: list) -> ast.Query:
    new_input = lifter.lift(q.input, counter)
    new_attrs = tuple(dataclasses.replace(oa, expr=lifter.lift(oa.expr, counter))
                      for oa in q.selector.attributes)
    return dataclasses.replace(
        q, input=new_input,
        selector=dataclasses.replace(q.selector, attributes=new_attrs))


def _target_of(q: ast.Query) -> str:
    return q.output.target


# ---------------------------------------------------------------------------
# the fused plan
# ---------------------------------------------------------------------------

class MultiQueryDevicePatternPlan:
    """One device NFA whose lanes are query INSTANCES (not partition
    keys): events broadcast to every lane; emitted matches route to their
    lane's output stream."""

    def __init__(self, name, rt, q, state_input, param_types, param_values,
                 targets, out_names, query_names):
        from .expr import jnp_dtype
        from .pattern_plan import DevicePatternPlan

        self.name = name
        self.rt = rt
        self.query_names = query_names
        rt._known_query_names.update(query_names)
        self.targets = targets
        self.per_q_names = out_names
        P = len(param_values)

        extra = {f"__qparam{i}": (f"__qparam{i}", t)
                 for i, t in enumerate(param_types)}
        from .nfa_device import F32_MODE
        from .expr import compute_dtypes as _cd
        prec = ast.find_annotation(rt.app.annotations, "app:devicePrecision")
        f64 = prec is not None and str(prec.element()).lower() == "f64"
        with _cd(None if f64 else F32_MODE):
            params = {}
            for i, t in enumerate(param_types):
                col = np.asarray([v[i] for v in param_values])
                params[f"__qparam{i}"] = col.astype(np.dtype(jnp_dtype(t)))
        self.inner = DevicePatternPlan(
            name, rt, q, state_input, target=targets[0], partitions=P,
            part_key_fns=None, slots=rt.device_slots, param_extra=extra,
            broadcast_events=True, params=params)
        if self.inner.kernel.null_outputs:
            from .nfa_device import DeviceNFAUnsupported
            raise DeviceNFAUnsupported(
                "fused selector over maybe-absent refs (null routing)")
        self.n_queries = P
        # mesh rounding may pad the lane axis: padding lanes carry zero
        # params (match-everything thresholds) — permanently disarm them
        if self.inner.P > P:
            import jax.numpy as jnp
            st = dict(self.inner.state)
            st["armed0"] = st["armed0"] & (jnp.arange(self.inner.P) < P)
            self.inner.state = self.inner._shard(
                {k: np.asarray(v) for k, v in st.items()})
        # register inferred schemas for every routed target stream
        from .schema import StreamSchema
        for qi, tgt in enumerate(targets):
            if tgt not in rt.schemas and tgt not in rt.tables:
                rt.schemas[tgt] = StreamSchema(tgt, tuple(
                    ast.Attribute(nm, t) for nm, t in
                    zip(out_names[qi], self.inner._types)))
        self.input_streams = self.inner.input_streams
        self.output_target = None          # routed per lane
        self.out_schema = None
        self.table_writer = None

    # -- QueryPlan surface -------------------------------------------------

    def regeometry(self, **knobs) -> None:
        """Adaptive-geometry hook: delegate to the fused inner plan (the
        lane PACKING itself is a build-time knob — @app:fusedLanes /
        tuning cache — consulted in build.py before this plan exists)."""
        self.inner.regeometry(**knobs)

    def device_metrics(self) -> dict:
        """Sampled gauges of the fused kernel (lane = query instance, so
        occupancy here reads as per-query pending-match population)."""
        m = self.inner.device_metrics()
        m["fused_queries"] = self.n_queries
        m["padded_lanes"] = self.inner.P - self.n_queries
        return m

    def flush_pending(self):
        return []

    def begin_dispatch_round(self):
        pass        # broadcast kernels have no deferred-pull pipeline

    def collect_ready(self):
        return []

    def process(self, stream_id, batch):
        return self.inner.process(stream_id, batch)

    def finalize(self):
        from .batch import EventBatch
        from .planner import OutputBatch
        from .schema import StreamSchema, TIMESTAMP_DTYPE

        outs = self.inner.finalize_multi()
        if not outs:
            return []
        tss, seqs, hseqs, data, qids = outs
        res = []
        order = np.lexsort((hseqs, seqs))
        tss, seqs, qids = tss[order], seqs[order], qids[order]
        data = {k: v[order] for k, v in data.items()}
        for qi in np.unique(qids):
            if qi >= self.n_queries:      # defensive: padding lanes
                continue
            m = qids == qi
            names = self.per_q_names[int(qi)]
            cols = {nm: data[src][m] for nm, src
                    in zip(names, self.inner._names)}
            schema = StreamSchema(self.targets[int(qi)], tuple(
                ast.Attribute(nm, t) for nm, t
                in zip(names, self.inner._types)))
            ob = OutputBatch(self.targets[int(qi)], EventBatch(
                schema, tss[m].astype(TIMESTAMP_DTYPE), cols,
                int(m.sum()), seqs[m]))
            ob.callback_name = self.query_names[int(qi)]
            res.append(ob)
        return res

    def on_timer(self, now_ms):
        self.inner.on_timer(now_ms)      # deadline ticks; matches surface
        return self.finalize()           # via the buffered path

    def next_wakeup(self):
        return self.inner.next_wakeup()

    def state_dict(self):
        return self.inner.state_dict()

    def load_state_dict(self, d):
        self.inner.load_state_dict(d)
