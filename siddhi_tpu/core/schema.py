"""Schema layer: attribute types -> TPU-friendly columnar dtypes.

Replaces the reference's row-oriented `Object[]` event data + positional
`int[]` coordinate addressing (reference: core:event/stream/StreamEvent.java:37-58,
core:event/stream/MetaStreamEvent.java).  On TPU an event batch is a
struct-of-arrays: one fixed-dtype device array per attribute; strings are
dictionary-encoded to int32 codes at ingest (host side) so predicates on
strings become integer compares on device.

dtype policy:
  STRING -> int32 dictionary code      INT    -> int32
  LONG   -> int64                      FLOAT  -> float32
  DOUBLE -> float64 (Java-faithful; TPU emulates f64 on the VPU — hot
            kernels may downcast internally where zero-false-match checks pass)
  BOOL   -> bool_                      OBJECT -> host-only (never shipped)
Timestamps -> int64 milliseconds (x64 enabled at package import).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np

from ..query.ast import AttrType, Attribute, StreamDefinition

# int64 timestamps need x64; data columns stay explicitly f32/i32.
jax.config.update("jax_enable_x64", True)

TIMESTAMP_DTYPE = np.int64
STRING_CODE_DTYPE = np.int32

_DTYPE_OF = {
    AttrType.STRING: STRING_CODE_DTYPE,
    AttrType.INT: np.int32,
    AttrType.LONG: np.int64,
    AttrType.FLOAT: np.float32,
    AttrType.DOUBLE: np.float64,
    AttrType.BOOL: np.bool_,
}


def dtype_of(t: AttrType, float64: bool = False):
    if t == AttrType.OBJECT:
        return np.dtype(object)
    if float64 and t == AttrType.DOUBLE:
        return np.float64
    return np.dtype(_DTYPE_OF[t])


class StringTable:
    """Bidirectional string <-> int32 code dictionary, shared per app.

    Code 0 is reserved for None/absent so device-side null checks are `== 0`.
    """

    __slots__ = ("_to_code", "_to_str")

    def __init__(self):
        self._to_code: dict[str, int] = {}
        self._to_str: list[Optional[str]] = [None]

    def encode(self, s: Optional[str]) -> int:
        if s is None:
            return 0
        c = self._to_code.get(s)
        if c is None:
            c = len(self._to_str)
            self._to_code[s] = c
            self._to_str.append(s)
        return c

    def decode(self, code: int) -> Optional[str]:
        return self._to_str[code]

    def encode_many(self, values) -> np.ndarray:
        """Vectorized encode: the python dict is consulted once per
        DISTINCT value (np.unique + a gather), so a million-row column
        with a few thousand symbols costs thousands of dict hits, not a
        per-row loop.  Arrays holding None (object dtype) fall back to
        the row loop — None does not compare under np.unique."""
        arr = np.asarray(values)
        if arr.dtype.kind in "iu":              # pre-encoded dict codes
            return arr.astype(STRING_CODE_DTYPE, copy=False)
        if arr.dtype.kind == "U" and arr.ndim == 1:
            uniq, first, inv = np.unique(arr, return_index=True,
                                         return_inverse=True)
            codes = np.empty(len(uniq), dtype=STRING_CODE_DTYPE)
            # NEW values must get codes in first-appearance order (np
            # .unique sorts) so the dictionary is identical to the
            # per-row path's — batches byte-match across ingest paths
            for j in np.argsort(first, kind="stable").tolist():
                codes[j] = self.encode(uniq[j])
            return codes[inv]
        return np.asarray([self.encode(v) for v in values],
                          dtype=STRING_CODE_DTYPE)

    def __len__(self) -> int:
        return len(self._to_str)

    # snapshot support -------------------------------------------------------
    def state(self) -> list:
        return list(self._to_str)

    def restore(self, strings: list) -> None:
        self._to_str = list(strings)
        self._to_code = {s: i for i, s in enumerate(strings) if s is not None}


@dataclass
class StreamSchema:
    """Compile-time schema of one stream — the analog of MetaStreamEvent."""
    id: str
    attributes: tuple[Attribute, ...]

    def __post_init__(self):
        self.index_of = {a.name: i for i, a in enumerate(self.attributes)}
        self.types = {a.name: a.type for a in self.attributes}

    @classmethod
    def of(cls, d: StreamDefinition) -> "StreamSchema":
        return cls(d.id, tuple(d.attributes))

    @property
    def names(self) -> list[str]:
        return [a.name for a in self.attributes]

    def dtype(self, name: str):
        return dtype_of(self.types[name])

    def type_of(self, name: str) -> AttrType:
        try:
            return self.types[name]
        except KeyError:
            raise KeyError(f"stream {self.id!r} has no attribute {name!r}; "
                           f"has {self.names}") from None
