"""Device (TPU) window-join plan — batched probe of the opposite window.

Reference semantics (core:query/input/stream/join/JoinProcessor.java:62-126):
each arriving event, after its side's filters, probes the OPPOSITE side's
current window content with the `on` condition and emits one joined event
per match, in arrival order; outer joins emit null-filled rows for probes
with no match; `unidirectional` restricts which side triggers.

TPU-first reformulation: the per-event probe loop becomes ONE dense
(T_probe, N_other) boolean grid per micro-batch —

  * window membership "as of the probing event" is rank arithmetic:
    an opposite event with in-window position p is visible to probe a iff
    nlt(a) - M <= p < nlt(a), where nlt(a) counts opposite arrivals before
    a (mirror prefix + passed in-batch arrivals with smaller seq) and M is
    the opposite window length — the in-batch evolution of both windows is
    captured exactly, with no sequential loop;
  * the `on` condition (equality keys AND residuals alike) evaluates over
    the broadcast (T, N) grid in one fused pass — at micro-batch scale the
    dense grid saturates the VPU and needs no index structure;
  * matched pairs compact to (a_idx, b_idx) index pairs via the standard
    count-then-compact idiom (capacity-doubling retry; the kernel is
    STATELESS, so a retry is a plain re-dispatch);
  * only pair indices, miss bitmasks, filter bitmasks, and device-computed
    selector columns travel back — pass-through outputs gather host-side
    from the window mirror + batch columns (the tunnel pays per byte).

The window contents are mirrored host-side (bounded by the window length):
the mirror is both the device upload for the next block and the source for
pass-through output materialization, so the kernel carries NO persistent
device state (snapshot = the mirrors).

Supported: stream-stream joins where both sides are windowless or carry
#window.length(N), any device-compilable `on`/filters/projection,
inner/left/right/full outer, unidirectional.  Everything else (time
windows — their expiry rides the host scheduler —, tables, aggregations,
named windows, group-by/order-by/limit/rate/having) raises
DeviceJoinUnsupported -> the host interp plan takes over.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..query import ast
from .batch import EventBatch
from .expr import (ExprError, MultiStreamContext, SingleStreamContext,
                   compile_expression, compute_dtypes, F32_MODE, jnp_dtype)
from .planner import (OutputBatch, PlanError, QueryPlan,
                      selector_has_aggregators)
from .nfa_device import _hi32, _lo32, join64_np, pow2_at_least as pow2
from .telemetry import call_kernel, env_nbytes
from .schema import StreamSchema, TIMESTAMP_DTYPE, dtype_of

_I32 = jnp.int32


class DeviceJoinUnsupported(Exception):
    """Join shape needs the host interp plan."""


class _Side:
    """One join side: schema, length window, compiled filters, mirror."""

    def __init__(self, inp: ast.SingleInputStream, rt):
        if inp.stream_id in rt.tables or inp.stream_id in rt.aggregations \
                or inp.stream_id in getattr(rt, "named_windows", {}):
            raise DeviceJoinUnsupported("table/aggregation/named-window side")
        if inp.stream_id not in rt.schemas:
            raise PlanError(f"join: unknown stream {inp.stream_id!r}")
        self.ref = inp.alias
        self.stream_id = inp.stream_id
        self.schema = rt.schemas[inp.stream_id]
        for h in inp.handlers:
            if isinstance(h, ast.StreamFunction):
                raise DeviceJoinUnsupported("stream function on join side")
        self.win_len = 0                   # 0 = windowless (retains nothing)
        if inp.window is not None:
            w = inp.window
            if w.namespace is not None or w.name.lower() != "length":
                raise DeviceJoinUnsupported(f"window {w.name!r} on join side")
            if len(w.args) != 1 or not isinstance(w.args[0], ast.Constant):
                raise DeviceJoinUnsupported("non-constant window length")
            self.win_len = int(w.args[0].value)
            if self.win_len <= 0 or self.win_len > (1 << 16):
                raise DeviceJoinUnsupported("window length out of range")
        ctx = SingleStreamContext(self.schema, rt.strings, alias=self.ref)
        try:
            self.filters = [compile_expression(f.expr, ctx)
                            for f in inp.filters]
        except ExprError as e:
            raise DeviceJoinUnsupported(f"filter: {e}")
        for ce in self.filters:
            if ce.type != ast.AttrType.BOOL:
                raise DeviceJoinUnsupported("non-boolean side filter")
        # host mirror of the window content, right-packed, columnar
        self.mirror_cols = {a.name: np.empty(0, dtype=dtype_of(a.type))
                            for a in self.schema.attributes}
        self.mirror_ts = np.empty(0, dtype=np.int64)
        self.mirror_seq = np.empty(0, dtype=np.int64)

    @property
    def mirror_n(self) -> int:
        return len(self.mirror_ts)

    def update_mirror(self, batch_cols, batch_ts, batch_seq, passed) -> None:
        if self.win_len == 0:
            return
        for k in self.mirror_cols:
            self.mirror_cols[k] = np.concatenate(
                [self.mirror_cols[k], batch_cols[k][passed]])[-self.win_len:]
        self.mirror_ts = np.concatenate(
            [self.mirror_ts, batch_ts[passed]])[-self.win_len:]
        self.mirror_seq = np.concatenate(
            [self.mirror_seq, batch_seq[passed]])[-self.win_len:]

    def state(self) -> dict:
        return {"cols": {k: v.copy() for k, v in self.mirror_cols.items()},
                "ts": self.mirror_ts.copy(), "seq": self.mirror_seq.copy()}

    def restore(self, st: dict) -> None:
        self.mirror_cols = {k: np.asarray(v) for k, v in st["cols"].items()}
        self.mirror_ts = np.asarray(st["ts"], dtype=np.int64)
        self.mirror_seq = np.asarray(st["seq"], dtype=np.int64)


class DeviceJoinPlan(QueryPlan):
    """`from A#window.length(N) as a join B#window.length(M) as b
    on <cond> select ... insert into O` as one dense device probe grid."""

    def __init__(self, name: str, rt, q: ast.Query,
                 inp: ast.JoinInputStream, target: Optional[str]):
        self.name = name
        self.rt = rt
        self.output_target = target
        self.events_for = getattr(q.output, "events_for",
                                  ast.OutputEventsFor.CURRENT)
        if q.rate is not None:
            raise DeviceJoinUnsupported("output rate limiting")
        sel = q.selector
        if sel.group_by or sel.order_by or sel.having is not None \
                or selector_has_aggregators(sel):
            raise DeviceJoinUnsupported("group-by/order-by/having selector")
        if inp.per is not None or inp.within is not None:
            raise DeviceJoinUnsupported("within/per (aggregation join)")
        self.limit, self.offset = sel.limit, sel.offset
        if self.limit is not None or self.offset:
            raise DeviceJoinUnsupported("limit/offset")

        self.left = _Side(inp.left, rt)
        self.right = _Side(inp.right, rt)
        if self.left.ref == self.right.ref:
            raise PlanError(f"join {name!r}: both sides named "
                            f"{self.left.ref!r}; alias one with `as`")
        self.join_type = inp.join_type
        self.trigger = inp.trigger          # "all" | "left" | "right"

        schemas = {self.left.ref: self.left.schema,
                   self.right.ref: self.right.schema}
        ctx = MultiStreamContext(schemas, rt.strings)
        self.on = None
        if inp.on is not None:
            try:
                self.on = compile_expression(inp.on, ctx)
            except ExprError as e:
                raise DeviceJoinUnsupported(f"on: {e}")
            if self.on.type != ast.AttrType.BOOL:
                raise DeviceJoinUnsupported("non-boolean on condition")

        # selector: pass-through outputs gather host-side; computed ones
        # evaluate on device over the matched pairs
        from ..interp.joins import _join_selector
        sel = _join_selector(sel, self)
        names, types, fns, passthrough = [], [], [], []
        for oa in sel.attributes:
            try:
                ce = compile_expression(oa.expr, ctx)
            except ExprError as e:
                raise DeviceJoinUnsupported(f"selector: {e}")
            names.append(oa.name)
            types.append(ce.type)
            fns.append(ce)
            if ce.is_var:
                passthrough.append(next(iter(ce.reads)))
            else:
                passthrough.append(None)
        self._names, self._types, self._fns = names, types, fns
        self._passthrough = passthrough
        self.out_schema = StreamSchema(target or f"#{name}", tuple(
            ast.Attribute(n, t) for n, t in zip(names, types)))
        # miss rows (outer joins): evaluated via host closures (null side)
        self._py_sel = None
        if any(pt is None for pt in passthrough) and self._any_outer():
            from ..interp.expr import PyExprContext, compile_py
            pctx = PyExprContext(schemas, tables=rt.tables)
            try:
                self._py_sel = [compile_py(oa.expr, pctx)[0]
                                for oa in sel.attributes]
            except Exception:
                raise DeviceJoinUnsupported(
                    "outer-join selector not host-evaluable for miss rows")

        self.input_streams = tuple({self.left.stream_id,
                                    self.right.stream_id})
        from .pipeline import DispatchPipeline
        self._mode = F32_MODE       # device DOUBLE policy (f32 compute)
        self._buffered: list = []
        self._fn_cache: dict = {}
        self._m_hint = 16
        # side filters force a sync per flush (the mirror update needs the
        # device-evaluated pass masks); filter-less joins pipeline
        self._can_pipeline = not (self.left.filters or self.right.filters)
        from .autotune import pipeline_depth_for
        self.pipeline_depth = pipeline_depth_for(rt, "join", q) \
            if self._can_pipeline else 0
        self._pipe = DispatchPipeline(name, self._materialize,
                                      depth=self.pipeline_depth)
        # build-time trace so unsupported expressions fail at plan time
        # (eval_shape: no compile, no device)
        self._shape_check()

    def _shape_check(self) -> None:
        TL = TR = 8
        NL, NR = max(self.left.win_len, 1), max(self.right.win_len, 1)

        def dummy(side, T, N):
            ev = {"valid": np.zeros(T, bool), "ts64": np.zeros(T, np.int64),
                  "seq": np.zeros(T, np.int64), "bT": np.int32(T),
                  "mirror_n": np.int32(0)}
            for a in side.schema.attributes:
                dt = self._np_dtype(a.type)
                ev[a.name] = np.zeros(T, dtype=dt)
                ev[f"m.{a.name}"] = np.zeros(N, dtype=dt)
            return ev
        fn = self._block_fn(TL, TR, NL, NR, 16)
        jax.eval_shape(fn, dummy(self.left, TL, NL),
                       dummy(self.right, TR, NR))

    def _any_outer(self) -> bool:
        return self.join_type in (ast.JoinType.LEFT_OUTER,
                                  ast.JoinType.RIGHT_OUTER,
                                  ast.JoinType.FULL_OUTER)

    def _outer_for(self, side_name: str) -> bool:
        return (self.join_type == ast.JoinType.FULL_OUTER
                or (self.join_type == ast.JoinType.LEFT_OUTER
                    and side_name == "left")
                or (self.join_type == ast.JoinType.RIGHT_OUTER
                    and side_name == "right"))

    # -- kernel ----------------------------------------------------------

    def _np_dtype(self, t):
        if t == ast.AttrType.DOUBLE:
            return np.float32
        return dtype_of(t)

    def _block_fn(self, TL, TR, NL, NR, M):
        key = (TL, TR, NL, NR, M)
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        left, right = self.left, self.right
        on, mode = self.on, self._mode
        fns, passthrough = self._fns, self._passthrough
        types = self._types
        trig, jt = self.trigger, self.join_type
        outer_l, outer_r = self._outer_for("left"), self._outer_for("right")

        def bits32(m):
            n_ = m.shape[0]
            padded = -(-n_ // 32) * 32
            if padded != n_:
                m = jnp.concatenate([m, jnp.zeros(padded - n_, bool)])
            r = m.reshape(-1, 32).astype(jnp.uint32)
            w = (r << jnp.arange(32, dtype=jnp.uint32)[None, :]) \
                .sum(axis=1).astype(jnp.uint32)
            return jax.lax.bitcast_convert_type(w, jnp.int32)

        def side_pass(side, ev, T):
            m = ev["valid"]
            for ce in side.filters:
                env = {}
                for a in side.schema.attributes:     # unqualified + ref.
                    env[a.name] = ev[a.name]
                    env[f"{side.ref}.{a.name}"] = ev[a.name]
                env["__timestamp__"] = ev["ts64"]
                m = m & jnp.broadcast_to(ce.fn(env), (T,))
            return m

        def probes(probe, other, p_ev, o_ev, p_pass, o_pass, NO, Mw):
            """pairs (T, NO + T_other) grid: probe side vs other's window."""
            Lo = o_ev["mirror_n"]                      # i32 scalar
            # opposite union: [mirror slots (NO cap) | other batch]
            def ucol(name):
                return jnp.concatenate([o_ev[f"m.{name}"], o_ev[name]])
            # position of each union entry in the other side's arrival
            # order (mirror first, then passed batch events by rank)
            rankb = jnp.cumsum(o_pass.astype(_I32)) - o_pass
            b_pos = jnp.concatenate(
                [jnp.arange(NO, dtype=_I32), Lo + rankb])
            b_valid = jnp.concatenate(
                [jnp.arange(NO, dtype=_I32) < Lo, o_pass])
            # arrivals of `other` strictly before each probe event
            nlt = Lo + jnp.sum(
                (o_pass[None, :] & (o_ev["seq"][None, :]
                                    < p_ev["seq"][:, None])).astype(_I32),
                axis=1)                                 # (T,)
            member = b_valid[None, :] & (b_pos[None, :] < nlt[:, None])
            if Mw > 0:
                member = member & (b_pos[None, :]
                                   >= nlt[:, None] - jnp.int32(Mw))
            else:
                member = jnp.zeros_like(member)         # windowless: empty
            grid = member
            if on is not None:
                env = {}
                for a in probe.schema.attributes:
                    env[f"{probe.ref}.{a.name}"] = p_ev[a.name][:, None]
                for a in other.schema.attributes:
                    env[f"{other.ref}.{a.name}"] = ucol(a.name)[None, :]
                env["__timestamp__"] = p_ev["ts64"][:, None]
                grid = grid & jnp.broadcast_to(on.fn(env), member.shape)
            return grid & p_pass[:, None]

        def compact_pairs(grid, cap):
            flat = grid.reshape(-1)
            n = jnp.sum(flat, dtype=_I32)
            pos = jnp.cumsum(flat.astype(_I32)) - flat
            wpos = jnp.where(flat, jnp.minimum(pos, cap - 1), cap)
            idx = jnp.full((cap,), -1, _I32).at[wpos].set(
                jnp.arange(flat.shape[0], dtype=_I32), mode="drop")
            return n, idx                       # flat grid index per pair

        def computed_cols(probe, other, p_ev, o_ev, NO, flat_idx, width):
            """Device-computed selector columns for compacted pairs."""
            a_idx = flat_idx // width
            b_idx = flat_idx % width
            safe_a = jnp.maximum(a_idx, 0)
            safe_b = jnp.maximum(b_idx, 0)
            env = {}
            for a in probe.schema.attributes:
                env[f"{probe.ref}.{a.name}"] = p_ev[a.name][safe_a]
            for a in other.schema.attributes:
                u = jnp.concatenate([o_ev[f"m.{a.name}"], o_ev[a.name]])
                env[f"{other.ref}.{a.name}"] = u[safe_b]
            env["__timestamp__"] = p_ev["ts64"][safe_a]
            cols = {}
            for nm, ce, pt, t in zip(self._names, fns, passthrough, types):
                if pt is not None:
                    continue
                v = ce.fn(env)
                cols[nm] = jnp.broadcast_to(v, (flat_idx.shape[0],))
            return a_idx, b_idx, cols

        def block(lev, rev):
            with compute_dtypes(mode):
                pl = side_pass(left, lev, TL)
                pr = side_pass(right, rev, TR)
                out = {"pl": bits32(pl), "pr": bits32(pr)}  # packed below
                widthL = NR + TR        # left probes right's union
                widthR = NL + TL
                gl = probes(left, right, lev, rev, pl, pr, NR,
                            right.win_len) if trig in ("all", "left") \
                    else jnp.zeros((TL, NR + TR), bool)
                gr = probes(right, left, rev, lev, pr, pl, NL,
                            left.win_len) if trig in ("all", "right") \
                    else jnp.zeros((TR, NL + TL), bool)
                nL, idxL = compact_pairs(gl, M)
                nR, idxR = compact_pairs(gr, M)
                aL, bL, colsL = computed_cols(left, right, lev, rev, NR,
                                              idxL, widthL)
                aR, bR, colsR = computed_cols(right, left, rev, lev, NL,
                                              idxR, widthR)
                # EVERYTHING packs into ONE i32 vector: the tunnel pays
                # ~100 ms per pull, so one result = one pull
                irows = [jnp.stack([nL, nR, jnp.int32(M), jnp.int32(0)]),
                         out["pl"], out["pr"]]
                if trig in ("all", "left") and outer_l:
                    irows.append(bits32(pl & ~gl.any(axis=1)))
                if trig in ("all", "right") and outer_r:
                    irows.append(bits32(pr & ~gr.any(axis=1)))
                irows += [aL, bL, aR, bR]
                frows = []
                for nm, t in zip(self._names, types):
                    for cols in (colsL, colsR):
                        if nm not in cols:
                            continue
                        v = cols[nm]
                        if v.dtype in (jnp.float32,):
                            irows.append(jax.lax.bitcast_convert_type(
                                v, jnp.int32))
                        elif v.dtype == jnp.float64:
                            frows.append(v)
                        elif v.dtype == jnp.int64:
                            irows.append(_hi32(v))
                            irows.append(_lo32(v))
                        else:
                            irows.append(v.astype(_I32))
                res = {"i": jnp.concatenate([r.reshape(-1)
                                             for r in irows])}
                if frows:
                    res["f"] = jnp.stack(frows)
                return res

        fn = jax.jit(block)
        self._fn_cache[key] = fn
        return fn

    # -- QueryPlan interface ---------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        if batch.n:
            self._buffered.append((stream_id, batch))
        return []

    def _side_arrays(self, side: _Side, bufs):
        """Concatenate this side's buffered batches into (T,) arrays."""
        mine = [b for sid, b in bufs if sid == side.stream_id]
        n = sum(b.n for b in mine)
        cols = {}
        for a in side.schema.attributes:
            # ORIGINAL dtype: pass-through outputs gather from these
            # host-side at full precision; the device upload (ev_of)
            # downcasts its own padded copies (f32 DOUBLE policy)
            dt = dtype_of(a.type)
            col = np.empty(n, dtype=dt)
            o = 0
            for b in mine:
                col[o:o + b.n] = b.columns[a.name]
                o += b.n
            cols[a.name] = col
        ts = np.concatenate([b.timestamps for b in mine]) if mine \
            else np.empty(0, np.int64)
        seq = np.concatenate(
            [b.seqs if b.seqs is not None else np.arange(b.n)
             for b in mine]) if mine else np.empty(0, np.int64)
        order = np.argsort(seq, kind="stable")
        return ({k: v[order] for k, v in cols.items()}, ts[order],
                seq[order], n)

    # degradation-ladder contract: finalize restores its input buffer on
    # a dispatch failure (so the runtime may retry with a halved flush);
    # once mirrors advance the flush passed its point of no return and
    # _finalize_retry_ok drops, forcing propagation instead of a retry
    # that would double-advance window mirrors
    retryable_finalize = True

    def finalize(self) -> list:
        if not self._buffered:
            return []
        snapshot = list(self._buffered)
        self._finalize_retry_ok = True
        try:
            return self._finalize_impl()
        except Exception:
            if self._finalize_retry_ok:
                self._buffered = snapshot
            raise

    def _finalize_impl(self) -> list:
        bufs, self._buffered = self._buffered, []
        with self.rt.stats.stage("host_build", plan=self.name):
            lc, lts, lseq, ln = self._side_arrays(self.left, bufs)
            rc, rts, rseq, rn = self._side_arrays(self.right, bufs)
        if ln == 0 and rn == 0:
            return []
        TL, TR = pow2(max(ln, 1)), pow2(max(rn, 1))
        NL = max(self.left.win_len, 1)
        NR = max(self.right.win_len, 1)

        def ev_of(side, cols, ts, seq, n, T, N):
            ev = {"valid": np.zeros(T, bool),
                  "ts64": np.zeros(T, np.int64),
                  "seq": np.zeros(T, np.int64),
                  "bT": np.int32(T), "mirror_n": np.int32(side.mirror_n)}
            ev["valid"][:n] = True
            ev["ts64"][:n] = ts
            ev["seq"][:n] = seq
            ev["seq"][n:] = np.int64(2**62)    # padding: after everything
            for a in side.schema.attributes:
                dt = self._np_dtype(a.type)
                col = np.zeros(T, dtype=dt)
                col[:n] = cols[a.name]
                ev[a.name] = col
                mc = np.zeros(N, dtype=dt)
                mc[:side.mirror_n] = side.mirror_cols[a.name].astype(dt)
                ev[f"m.{a.name}"] = mc
            return ev

        lev = ev_of(self.left, lc, lts, lseq, ln, TL, NL)
        rev = ev_of(self.right, rc, rts, rseq, rn, TR, NR)
        entry = self._dispatch(lev, rev, TL, TR, NL, NR,
                               dict(lc=lc, rc=rc, lts=lts, rts=rts,
                                    lseq=lseq, rseq=rseq, ln=ln, rn=rn))
        if self._can_pipeline:
            # no side filters: every valid event passes — mirrors advance
            # host-side immediately, so the next flush needs NO sync.
            # The pipeline then defers the blocking pull: depth-D across
            # flushes, and within one dispatch round the runtime collects
            # AFTER every other device plan has dispatched (overlap)
            self._finalize_retry_ok = False     # mirrors advance now
            self.left.update_mirror(lc, lts, lseq, np.ones(ln, bool))
            self.right.update_mirror(rc, rts, rseq, np.ones(rn, bool))
            return self._pipe.push(entry)
        rows = self._materialize(entry, update_mirrors=True)
        return rows

    def _dispatch(self, lev, rev, TL, TR, NL, NR, meta, M=None,
                  mirror_snap=None) -> dict:
        # dispatch-boundary fault injection: raising here (before any
        # mirror advance) keeps the flush retryable
        self.rt.inject("dispatch", self.name)
        M = M if M is not None else max(self._m_hint, 16)
        prof = self.rt.profiler
        if not self.rt.stats.enabled and prof is None:
            res = self._block_fn(TL, TR, NL, NR, M)(lev, rev)
        else:
            hit = (TL, TR, NL, NR, M) in self._fn_cache
            fn = self._block_fn(TL, TR, NL, NR, M)
            res = call_kernel(
                self.rt.stats, self.name, fn, (lev, rev), cache_hit=hit,
                nbytes=env_nbytes(lev) + env_nbytes(rev), prof=prof)
        from .pipeline import start_d2h
        start_d2h(res)      # start the D2H pull while the device computes
        # snapshot the mirrors the probe actually saw: with pipelining
        # (and overflow retries) they advance before the entry
        # materializes, so a fresh snapshot would gather wrong values
        if mirror_snap is None:
            mirror_snap = {}
            for key, side in (("L", self.left), ("R", self.right)):
                mirror_snap[key] = (
                    {k: v.copy() for k, v in side.mirror_cols.items()},
                    side.mirror_n)
        return {"res": res, "lev": lev, "rev": rev, "TL": TL, "TR": TR,
                "NL": NL, "NR": NR, "M": M, "meta": meta,
                "mirror_snap": mirror_snap}

    def _materialize(self, entry: dict, update_mirrors: bool = False) -> list:
        while True:
            with self.rt.stats.stage("transfer", plan=self.name):
                ipack = np.asarray(entry["res"]["i"])      # ONE pull
            nL, nR = int(ipack[0]), int(ipack[1])
            M = entry["M"]
            if max(nL, nR) <= M:
                break
            entry = self._dispatch(entry["lev"], entry["rev"], entry["TL"],
                                   entry["TR"], entry["NL"], entry["NR"],
                                   entry["meta"],
                                   M=pow2(max(nL, nR), lo=32),
                                   mirror_snap=entry["mirror_snap"])
        self._m_hint = max(self._m_hint, entry["M"])
        fpack = np.asarray(entry["res"]["f"]) if "f" in entry["res"]             else None
        me = entry["meta"]
        TL, TR, M = entry["TL"], entry["TR"], entry["M"]
        ln, rn = me["ln"], me["rn"]
        off = [4]

        def take(n):
            v = ipack[off[0]:off[0] + n]
            off[0] += n
            return v
        pl = _unbits(take(-(-TL // 32)), TL)[:ln]
        pr = _unbits(take(-(-TR // 32)), TR)[:rn]
        missL = missR = None
        if self.trigger in ("all", "left") and self._outer_for("left"):
            missL = _unbits(take(-(-TL // 32)), TL)[:ln]
        if self.trigger in ("all", "right") and self._outer_for("right"):
            missR = _unbits(take(-(-TR // 32)), TR)[:rn]
        aL, bL, aR, bR = take(M), take(M), take(M), take(M)
        comp_cols = {"L": {}, "R": {}}
        fi = 0
        for nm, t, pt in zip(self._names, self._types, self._passthrough):
            if pt is not None:
                continue
            for sk in ("L", "R"):
                dt = np.float32 if t == ast.AttrType.DOUBLE \
                    else np.dtype(jnp_dtype(t))
                if dt == np.float64:
                    comp_cols[sk][nm] = np.asarray(fpack[fi]); fi += 1
                elif dt == np.float32:
                    comp_cols[sk][nm] = take(M).view(np.float32)
                elif dt == np.int64:
                    comp_cols[sk][nm] = join64_np(take(M), take(M))
                else:
                    comp_cols[sk][nm] = take(M)
        if update_mirrors:
            # entry mirrors were pre-advance: the probe saw the old ones
            self._finalize_retry_ok = False
            self.left.update_mirror(me["lc"], me["lts"], me["lseq"], pl)
            self.right.update_mirror(me["rc"], me["rts"], me["rseq"], pr)
        return self._assemble(entry, nL, nR, aL, bL, aR, bR, comp_cols,
                              missL, missR)

    def _assemble(self, entry, nL, nR, aL, bL, aR, bR, comp_cols,
                  missL, missR) -> list:
        """Merge pair and miss rows in the reference's arrival order
        (probe seq, left-probe-first, opposite position)."""
        if self.events_for == ast.OutputEventsFor.EXPIRED:
            return []
        names, types, passthrough = self._names, self._types, self._passthrough
        me = entry["meta"]
        lc, rc = me["lc"], me["rc"]
        lts, rts, lseq, rseq = me["lts"], me["rts"], me["lseq"], me["rseq"]
        ln, rn = me["ln"], me["rn"]
        TL, TR = entry["TL"], entry["TR"]

        def union_col(side, key, cols, name, n, T):
            dt = dtype_of(side.schema.type_of(name))     # full precision
            w = max(side.win_len, 1)
            u = np.zeros(w + T, dtype=dt)
            mc, mn = entry["mirror_snap"][key]
            u[:mn] = mc[name][:mn]
            u[w:w + n] = cols[name]
            return u

        segs = []       # (sort_seq, side_rank, pos, ts, row_cols, nulls)

        def pair_rows(side_probe, side_other, okey, a_idx, b_idx, npairs,
                      p_cols, p_ts, p_seq, o_cols, o_n, o_T, side_rank,
                      comp):
            if npairs == 0:
                return
            a = a_idx[:npairs]
            b = b_idx[:npairs]
            cols_out = {}
            for nm, t, pt in zip(names, types, passthrough):
                if pt is None:
                    cols_out[nm] = comp[nm][:npairs]
                    continue
                ref, attr = pt.split(".", 1)
                if ref == side_probe.ref:
                    cols_out[nm] = p_cols[attr][a]
                else:
                    u = union_col(side_other, okey, o_cols, attr, o_n, o_T)
                    cols_out[nm] = u[b]
            segs.append((p_seq[a], np.full(npairs, side_rank, np.int8),
                         b.astype(np.int64), p_ts[a], cols_out, None))

        pair_rows(self.left, self.right, "R", aL, bL, nL, lc, lts, lseq,
                  rc, rn, TR, 0, comp_cols["L"])
        pair_rows(self.right, self.left, "L", aR, bR, nR, rc, rts, rseq,
                  lc, ln, TL, 1, comp_cols["R"])

        def miss_rows(side_probe, side_other, miss, p_cols, p_ts, p_seq,
                      side_rank):
            if miss is None:
                return
            idx = np.flatnonzero(miss)
            if idx.size == 0:
                return
            cols_out = {}
            nulls = {}
            if all(pt is not None for pt in passthrough):
                for nm, t, pt in zip(names, types, passthrough):
                    ref, attr = pt.split(".", 1)
                    if ref == side_probe.ref:
                        cols_out[nm] = p_cols[attr][idx]
                    else:
                        cols_out[nm] = np.zeros(idx.size, dtype=dtype_of(t))
                        nulls[nm] = np.ones(idx.size, bool)
            else:
                # computed outputs over a null side: host closures
                rows = []
                pnames = side_probe.schema.names
                dec = self.rt.strings._to_str
                for i in idx:
                    env = {}
                    for nm2 in pnames:
                        v = p_cols[nm2][i]
                        if side_probe.schema.type_of(nm2) \
                                == ast.AttrType.STRING:
                            c = int(v)
                            v = dec[c] if 0 <= c < len(dec) else None
                        elif isinstance(v, np.generic):
                            v = v.item()
                        env[f"{side_probe.ref}.{nm2}"] = v
                        env[nm2] = v
                    env["__timestamp__"] = int(p_ts[i])
                    for nm2 in side_other.schema.names:
                        env[f"{side_other.ref}.{nm2}"] = None
                    rows.append([f(env) for f in self._py_sel])
                for j, (nm, t) in enumerate(zip(names, types)):
                    vals = [r[j] for r in rows]
                    isnull = np.array([v is None for v in vals])
                    filled = [0 if v is None else v for v in vals]
                    if t == ast.AttrType.STRING:
                        enc = self.rt.strings.encode
                        filled = [v if isinstance(v, (int, np.integer))
                                  else enc(v) for v in filled]
                    cols_out[nm] = np.asarray(filled, dtype=dtype_of(t))
                    if isnull.any():
                        nulls[nm] = isnull
            segs.append((p_seq[idx], np.full(idx.size, side_rank, np.int8),
                         np.full(idx.size, 1 << 60, np.int64),
                         p_ts[idx], cols_out, nulls or None))

        miss_rows(self.left, self.right, missL, lc, lts, lseq, 0)
        miss_rows(self.right, self.left, missR, rc, rts, rseq, 1)

        if not segs:
            return []
        tot = sum(len(s[0]) for s in segs)
        seq_all = np.concatenate([s[0] for s in segs])
        rank_all = np.concatenate([s[1] for s in segs])
        pos_all = np.concatenate([np.asarray(s[2], np.int64) for s in segs])
        ts_all = np.concatenate([s[3] for s in segs])
        order = np.lexsort((pos_all, rank_all, seq_all))
        cols = {}
        nulls_out = {}
        for nm, t in zip(names, types):
            dt = dtype_of(t)
            parts, nparts = [], []
            for s in segs:
                v = s[4][nm]
                parts.append(np.asarray(v))
                nl = (s[5] or {}).get(nm)
                nparts.append(nl if nl is not None
                              else np.zeros(len(s[0]), bool))
            cols[nm] = np.concatenate(parts).astype(dt)[order]
            nl = np.concatenate(nparts)[order]
            if nl.any():
                nulls_out[nm] = nl
        out = EventBatch(self.out_schema,
                         ts_all[order].astype(TIMESTAMP_DTYPE), cols, tot,
                         nulls=nulls_out or None)
        return [OutputBatch(self.output_target, out)]

    # -- snapshot ---------------------------------------------------------

    def state_dict(self) -> dict:
        return {"left": self.left.state(), "right": self.right.state()}

    def load_state_dict(self, d: dict) -> None:
        self._pipe.take_all()       # in-flight results predate the restore
        self.left.restore(d["left"])
        self.right.restore(d["right"])


def _unbits(words: np.ndarray, n: int) -> np.ndarray:
    b = ((words.view(np.uint32)[:, None]
          >> np.arange(32, dtype=np.uint32)) & 1).astype(bool)
    return b.reshape(-1)[:n]
