"""Continuous device-time attribution: the per-dispatch phase profiler.

PR 15's `bench.py --trace` showed host dispatch/materialization — not the
kernel — bounds end-to-end eps, but that split existed only as a one-shot
offline bench line.  This module makes the same attribution continuous
and per-plan in a *live* engine: every dispatch round (pattern
scan/dfa/chunk/seq, window, join, filter, fused multi-query — plus the
runtime's sink egress) attributes its wall time into six phases:

    h2d_upload        host->device argument upload (timed `device_put`
                      of the numpy leaves, sampled rounds only)
    kernel_compute    device execution (timed `block_until_ready`,
                      sampled rounds only)
    d2h_materialize   blocking result pull + unpack (DispatchPipeline
                      materialize / the `transfer` stage)
    host_pack_unpack  host-side batch build + callback scatter (the
                      `host_build` / `scatter` stages)
    python_dispatch   residual: python plan code, jit call overhead,
                      cache probes — whatever the round spent that no
                      explicit phase claimed
    sink_egress       sink payload delivery (runtime sink outbox flush)

Why sampling: JAX dispatch is async — a jitted call returns once the
device owns the work, so on the steady-state path kernel time is only
*observable* by blocking.  Blocking every round would serialize the
host/device overlap the pipeline exists to create, so kernel + h2d are
measured on a duty cycle (`@app:profile('sample=N')`, default 1-in-32
of the rounds that actually dispatch a warm kernel — collect polls and
scheduler pumps don't consume the cycle) and extrapolated: unsampled
rounds pay two clock reads and a dict merge.
The extrapolated kernel time is *subtracted* from the raw materialize
wall (which absorbs the device wait on unsampled rounds), so the
published shares are an estimate of the true steady-state split, and
always normalize to sum 1.0.

The sampled h2d probe relies on a JAX invariant: `jax.device_put` of a
numpy array yields a device array with the *identical* ShapedArray aval,
so substituting the uploaded leaves into the jit call triggers no
recompile and no second upload.

Surfaces: `rt.profile()` (totals + windowed ring + roofline fold),
`GET /siddhi/artifact/profile`, Prometheus
`siddhi_tpu_phase_seconds_total{plan,phase}` /
`siddhi_tpu_host_dispatch_share{plan}`, and a host-share breach trigger
(`@app:hostShareAlert(0.7)`) that promotes a flight-recorder dump via
the tracing trigger registry (docs/OBSERVABILITY.md).

Threading: dispatch rounds run on whatever thread drives `_drain`
(caller, scheduler pump, ingest worker) — round state is thread-local
and merged into the shared accumulators under `PhaseProfiler._lock`
once per round.  The profiler spawns no threads.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Callable, Optional

from ..utils.locks import new_lock
from .telemetry import Histogram

PHASES = ("h2d_upload", "kernel_compute", "d2h_materialize",
          "host_pack_unpack", "python_dispatch", "sink_egress")

DEVICE_PHASES = ("h2d_upload", "kernel_compute", "d2h_materialize")
HOST_PHASES = ("host_pack_unpack", "python_dispatch", "sink_egress")

# pseudo-plans: attribution that belongs to the dispatch loop, not a
# device plan ("_runtime" = scatter/emit between rounds, "_sink" = sink
# outbox egress)
PSEUDO_PLANS = ("_runtime", "_sink")


class _Acc:
    """Per-plan accumulator (one for the running totals, one per live
    ring window).  Mutated only under the profiler lock."""

    __slots__ = ("rounds", "kernel_rounds", "sampled_rounds", "events",
                 "wall_s", "kernel_wall_s", "sampled_wall_s", "phases",
                 "bytes_h2d", "bytes_d2h", "hist")

    def __init__(self):
        self.rounds = 0
        self.kernel_rounds = 0       # rounds that dispatched a warm kernel
        self.sampled_rounds = 0      # ... of which the probe blocked+timed
        self.events = 0
        self.wall_s = 0.0
        self.kernel_wall_s = 0.0
        self.sampled_wall_s = 0.0
        self.phases: dict = {}
        self.bytes_h2d = 0
        self.bytes_d2h = 0
        self.hist = Histogram()      # round wall -> p99

    def merge_round(self, wall: float, sampled: bool, has_kernel: bool,
                    phases: dict, events: int, bytes_h2d: int,
                    bytes_d2h: int) -> None:
        self.rounds += 1
        self.events += events
        self.wall_s += wall
        if has_kernel:
            self.kernel_rounds += 1
            self.kernel_wall_s += wall
            if sampled:
                self.sampled_rounds += 1
                self.sampled_wall_s += wall
        for k, v in phases.items():
            self.phases[k] = self.phases.get(k, 0.0) + v
        self.bytes_h2d += bytes_h2d
        self.bytes_d2h += bytes_d2h
        self.hist.record(wall)


class _Round:
    """Thread-local state of one open dispatch round.  Lock-free by
    construction: only the owning thread touches it."""

    __slots__ = ("plan", "sampled", "has_kernel", "phases", "attr_total",
                 "cur_phase", "bytes_h2d", "bytes_d2h")

    def __init__(self, plan: str):
        self.plan = plan
        # sampling is decided LAZILY at the first warm kernel call: the
        # dispatch loop opens many kernel-less rounds (collect polls,
        # scheduler pumps), and a duty cycle counted per round would
        # mostly land the probe on rounds with nothing to measure
        self.sampled = None       # None = no kernel seen yet
        self.has_kernel = False
        self.phases: dict = {}
        self.attr_total = 0.0     # explicitly attributed seconds so far
        self.cur_phase = None     # owner of the open phase span, if any
        self.bytes_h2d = 0
        self.bytes_d2h = 0

    def add(self, name: str, dt: float) -> None:
        if dt < 0.0:
            dt = 0.0
        self.phases[name] = self.phases.get(name, 0.0) + dt
        self.attr_total += dt


class _RoundCM:
    __slots__ = ("prof", "plan", "events", "t0", "rd", "nested")

    def __init__(self, prof: "PhaseProfiler", plan: str, events: int):
        self.prof = prof
        self.plan = plan
        self.events = events

    def __enter__(self):
        tls = self.prof._tls
        if getattr(tls, "round", None) is not None:
            # a round within a round (fused plan delegating to its inner
            # plan, a replay loop re-entering): the outer round owns the
            # attribution — this marker is a no-op
            self.nested = True
            return self
        self.nested = False
        self.rd = _Round(self.plan)
        tls.round = self.rd
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self.nested:
            return False
        wall = time.perf_counter() - self.t0
        rd = self.rd
        self.prof._tls.round = None
        # residual: round wall no explicit phase claimed — python plan
        # code, jit-call overhead, cache probes, arg packing
        py = wall - rd.attr_total
        if py > 0.0:
            rd.phases["python_dispatch"] = \
                rd.phases.get("python_dispatch", 0.0) + py
        self.prof._merge_round(self.plan, wall, bool(rd.sampled),
                               rd.has_kernel, rd.phases, self.events,
                               rd.bytes_h2d, rd.bytes_d2h)
        return False


class _PhaseSpan:
    """Outermost-wins phase span.  Nested spans mapping into an already
    open phase (the `transfer` stage inside the pipeline's materialize
    wrap) are suppressed; explicit attributions made *inside* the span
    (a sampled kernel re-dispatch during an M-overflow replay) are
    subtracted, so one second of wall is never counted twice."""

    __slots__ = ("prof", "name", "t0", "rd", "mark", "direct")

    _SUPPRESSED = -1.0

    def __init__(self, prof: "PhaseProfiler", name: str):
        self.prof = prof
        self.name = name

    def __enter__(self):
        rd = getattr(self.prof._tls, "round", None)
        self.rd = rd
        if rd is None:
            # outside any round (callback scatter between rounds):
            # attribute directly to the "_runtime" pseudo-plan
            self.direct = True
            self.mark = 0.0
        else:
            self.direct = False
            if rd.cur_phase is None:
                rd.cur_phase = self.name
                self.mark = rd.attr_total
            else:
                self.mark = self._SUPPRESSED
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.t0
        if self.direct:
            self.prof.note("_runtime", self.name, dt)
        elif self.mark != self._SUPPRESSED:
            rd = self.rd
            inner = rd.attr_total - self.mark
            rd.add(self.name, max(0.0, dt - inner))
            rd.cur_phase = None
        return False


class PhaseProfiler:
    """The per-runtime attribution plane.  `mode` is 'sample' or 'all'
    ('off' never constructs one — `rt.profiler is None`)."""

    def __init__(self, app_name: str, mode: str = "sample",
                 sample_every: int = 32, window_s: float = 5.0,
                 host_share_alert: float = 0.7, ring: int = 120):
        self.app = app_name
        self.mode = mode
        self.sample_every = 1 if mode == "all" else max(1, int(sample_every))
        self.window_s = float(window_s)
        self.host_share_alert = float(host_share_alert)
        # wired by the runtime to the tracing trigger registry
        # (enqueue-only, safe under engine locks)
        self.on_host_share_breach: Optional[Callable] = None
        self._tls = threading.local()
        self._rctr = itertools.count(0)   # round counter (duty cycle)
        self._lock = new_lock("PhaseProfiler._lock")
        # totals + the live window under construction, both per plan
        self._totals: dict = {}           # plan -> _Acc
        self._cur: dict = {}              # plan -> _Acc
        self._batch_wall_s = 0.0          # full dispatch-loop batch wall
        self._batch_events = 0
        self._cur_batch_wall_s = 0.0
        self._cur_batch_events = 0
        self._win_t0 = time.monotonic()
        self._win_wall = time.time()
        self._windows: list = []          # ring of rolled window dicts
        self._ring_cap = int(ring)
        self.probe_failures = 0
        self.breaches = 0

    # -- hot-path hooks ------------------------------------------------------

    def round(self, plan: str, events: int = 0) -> _RoundCM:
        """Wrap one plan dispatch round (process / collect / finalize)."""
        return _RoundCM(self, plan, events)

    def phase(self, name: str) -> _PhaseSpan:
        """Wrap a region whose wall belongs to one phase (outermost
        wins; see _PhaseSpan)."""
        return _PhaseSpan(self, name)

    def run_kernel(self, fn, args: tuple, cache_hit: bool = True):
        """Invoke a jitted kernel.  On a sampled round: time the numpy
        leaf upload (h2d_upload) and the device execution via
        `block_until_ready` (kernel_compute).  Unsampled rounds and
        compile calls (cache_hit=False — trace+XLA time must not skew
        the kernel estimate) dispatch untouched.

        The duty cycle counts KERNEL-carrying rounds, decided here on
        the round's first warm call: collect polls and scheduler pumps
        open rounds with no kernel, and a per-round cycle would burn
        most of its samples on them."""
        rd = getattr(self._tls, "round", None)
        if rd is None or not cache_hit:
            return fn(*args)
        rd.has_kernel = True
        if rd.sampled is None:
            se = self.sample_every
            rd.sampled = se <= 1 or (next(self._rctr) % se == 0)
        if not rd.sampled:
            return fn(*args)
        try:
            import jax
            t0 = time.perf_counter()
            args = tuple(_device_put_leaves(a) for a in args)
            t1 = time.perf_counter()
            out = fn(*args)
            out = jax.block_until_ready(out)
            t2 = time.perf_counter()
        except Exception:
            with self._lock:
                self.probe_failures += 1
            return fn(*args)
        rd.add("h2d_upload", t1 - t0)
        # t1..t2 = python dispatch + device execution; the dispatch-call
        # overhead is small vs a blocked kernel and is what this phase
        # names anyway
        rd.add("kernel_compute", t2 - t1)
        return out

    def note_bytes(self, plan: str, direction: str, nbytes: int) -> None:
        """H2D/D2H payload bytes for the current round (lock-free: the
        open round is thread-local; merged at round end)."""
        if not nbytes:
            return
        rd = getattr(self._tls, "round", None)
        if rd is None:
            with self._lock:
                acc = self._acc_locked(plan)
                if direction == "h2d":
                    acc[0].bytes_h2d += nbytes
                    acc[1].bytes_h2d += nbytes
                else:
                    acc[0].bytes_d2h += nbytes
                    acc[1].bytes_d2h += nbytes
            return
        if direction == "h2d":
            rd.bytes_h2d += nbytes
        else:
            rd.bytes_d2h += nbytes

    def note(self, plan: str, phase: str, seconds: float,
             events: int = 0) -> None:
        """Attribute an already-measured span outside any round (sink
        egress, scatter between rounds)."""
        ph = {phase: seconds}
        self._merge_round(plan, seconds, False, False, ph, events, 0, 0)

    def note_batch(self, seconds: float, events: int) -> None:
        """One full dispatch-loop batch wall (the coverage denominator)."""
        with self._lock:
            self._batch_wall_s += seconds
            self._batch_events += events
            self._cur_batch_wall_s += seconds
            self._cur_batch_events += events

    def maybe_roll(self, now: Optional[float] = None) -> None:
        """Roll the live window into the ring once window_s elapsed;
        called from the dispatch loop between batches (one clock read
        when nothing to do)."""
        now = time.monotonic() if now is None else now
        # lock-free fast path: a stale _win_t0 read only delays the roll
        # by one batch; the locked re-check below decides
        # lint: allow (unlocked fast-path read; locked re-check decides)
        if now - self._win_t0 < self.window_s:
            return
        breach_detail = None
        with self._lock:
            if now - self._win_t0 < self.window_s:
                return
            dur = now - self._win_t0
            if self._cur:
                snap = self._window_snapshot_locked(dur)
                self._windows.append(snap)
                if len(self._windows) > self._ring_cap:
                    del self._windows[:len(self._windows) - self._ring_cap]
                hs = snap.get("host_dispatch_share")
                if hs is not None and hs > self.host_share_alert:
                    self.breaches += 1
                    breach_detail = (
                        f"host dispatch share {hs:.3f} > alert "
                        f"{self.host_share_alert} over {dur:.1f}s window "
                        f"(eps {snap.get('eps', 0):.0f})")
            self._cur = {}
            self._cur_batch_wall_s = 0.0
            self._cur_batch_events = 0
            self._win_t0 = now
            self._win_wall = time.time()
        if breach_detail is not None and self.on_host_share_breach is not None:
            # outside the profiler lock: the callback enqueues a tracing
            # trigger (itself enqueue-only) — no lock-order edge
            try:
                self.on_host_share_breach(breach_detail)
            except Exception:
                pass

    # -- merge ---------------------------------------------------------------

    def _acc_locked(self, plan: str) -> tuple:
        tot = self._totals.get(plan)
        if tot is None:
            tot = self._totals[plan] = _Acc()
        cur = self._cur.get(plan)
        if cur is None:
            cur = self._cur[plan] = _Acc()
        return tot, cur

    def _merge_round(self, plan, wall, sampled, has_kernel, phases,
                     events, bytes_h2d, bytes_d2h) -> None:
        with self._lock:
            tot, cur = self._acc_locked(plan)
            tot.merge_round(wall, sampled, has_kernel, phases, events,
                            bytes_h2d, bytes_d2h)
            cur.merge_round(wall, sampled, has_kernel, phases, events,
                            bytes_h2d, bytes_d2h)

    # -- views ---------------------------------------------------------------

    @staticmethod
    def _view(acc: _Acc) -> dict:
        """Extrapolate sampled kernel/h2d to the full round population,
        correct the raw materialize/residual walls, and normalize.

        Raw `d2h_materialize` absorbs the device wait on *unsampled*
        rounds (async dispatch: the blocking pull pays for the kernel);
        raw `python_dispatch` absorbs their upload.  The extrapolation
        deltas move that time where it belongs, clamped at zero, and
        shares are normalized over the corrected total so they sum to
        exactly 1.0."""
        ph = acc.phases
        kern = ph.get("kernel_compute", 0.0)
        h2d = ph.get("h2d_upload", 0.0)
        f = 1.0
        # extrapolate over KERNEL-carrying rounds only: collect polls /
        # pump rounds never dispatch, so scaling by total round wall
        # would inflate the estimate by their (kernel-less) time
        if acc.sampled_rounds and acc.sampled_rounds < acc.kernel_rounds:
            f = (acc.kernel_wall_s / acc.sampled_wall_s
                 if acc.sampled_wall_s > 0.0
                 else acc.kernel_rounds / acc.sampled_rounds)
        kern_est = kern * f
        h2d_est = h2d * f
        d2h = max(0.0, ph.get("d2h_materialize", 0.0) - (kern_est - kern))
        py = max(0.0, ph.get("python_dispatch", 0.0) - (h2d_est - h2d))
        est = {"h2d_upload": h2d_est,
               "kernel_compute": kern_est,
               "d2h_materialize": d2h,
               "host_pack_unpack": ph.get("host_pack_unpack", 0.0),
               "python_dispatch": py,
               "sink_egress": ph.get("sink_egress", 0.0)}
        tot = sum(est.values())
        shares = {k: (v / tot if tot > 0.0 else 0.0)
                  for k, v in est.items()}
        host = sum(shares[k] for k in HOST_PHASES)
        v = {"rounds": acc.rounds,
             "kernel_rounds": acc.kernel_rounds,
             "sampled_rounds": acc.sampled_rounds,
             "events": acc.events,
             "wall_s": round(acc.wall_s, 6),
             "phases_s": {k: round(s, 6) for k, s in est.items()},
             "shares": {k: round(s, 4) for k, s in shares.items()},
             "host_dispatch_share": round(host, 4),
             "device_share": round(1.0 - host, 4)}
        if acc.bytes_h2d or acc.bytes_d2h:
            v["bytes"] = {"h2d": acc.bytes_h2d, "d2h": acc.bytes_d2h}
        if acc.hist.count:
            p99 = acc.hist.percentile(99)
            if p99 is not None:
                v["round_p99_ms"] = round(p99 * 1e3, 4)
        if acc.events and kern_est > 0.0:
            v["kernel_eps"] = round(acc.events / kern_est, 1)
        if acc.events and acc.wall_s > 0.0:
            v["end_to_end_eps"] = round(acc.events / acc.wall_s, 1)
        return v

    def _aggregate_locked(self, accs: dict, batch_wall: float,
                          batch_events: int) -> dict:
        agg = _Acc()
        covered = 0.0
        for name, a in accs.items():
            agg.rounds += a.rounds
            agg.kernel_rounds += a.kernel_rounds
            agg.sampled_rounds += a.sampled_rounds
            agg.wall_s += a.wall_s
            agg.kernel_wall_s += a.kernel_wall_s
            agg.sampled_wall_s += a.sampled_wall_s
            for k, s in a.phases.items():
                agg.phases[k] = agg.phases.get(k, 0.0) + s
            agg.bytes_h2d += a.bytes_h2d
            agg.bytes_d2h += a.bytes_d2h
            if name != "_sink":     # sink egress runs outside batch wall
                covered += a.wall_s
        agg.events = batch_events
        out = self._view(agg)
        if batch_wall > 0.0:
            out["coverage"] = round(min(1.0, covered / batch_wall), 4)
            out["batch_wall_s"] = round(batch_wall, 6)
            out["eps"] = round(batch_events / batch_wall, 1)
        return out

    def _window_snapshot_locked(self, dur_s: float) -> dict:
        plans = {n: self._view(a) for n, a in self._cur.items()}
        agg = self._aggregate_locked(self._cur, self._cur_batch_wall_s,
                                     self._cur_batch_events)
        snap = {"t_unix": round(self._win_wall, 3),
                "dur_s": round(dur_s, 3),
                "plans": plans,
                "host_dispatch_share": agg.get("host_dispatch_share"),
                "shares": agg.get("shares"),
                "coverage": agg.get("coverage")}
        if dur_s > 0.0:
            snap["eps"] = round(self._cur_batch_events / dur_s, 1)
            # share of the window the dispatch loop was busy at all
            snap["occupancy"] = round(
                min(1.0, self._cur_batch_wall_s / dur_s), 4)
        return snap

    def metrics(self) -> dict:
        """Compact summary for statistics()/Prometheus: cumulative
        totals per plan, no ring."""
        with self._lock:
            out = {"mode": self.mode,
                   "sample_every": self.sample_every,
                   "window_s": self.window_s,
                   "host_share_alert": self.host_share_alert,
                   "plans": {n: self._view(a)
                             for n, a in self._totals.items()},
                   "windows_rolled": len(self._windows),
                   "breaches": self.breaches}
            agg = self._aggregate_locked(self._totals, self._batch_wall_s,
                                         self._batch_events)
            out["aggregate"] = agg
            if self.probe_failures:
                out["probe_failures"] = self.probe_failures
            return out

    def profile(self, window: Optional[int] = None) -> dict:
        """The full surface behind rt.profile() and the HTTP endpoint:
        metrics() plus the last `window` ring snapshots (all retained
        windows when None)."""
        rep = self.metrics()
        with self._lock:
            wins = list(self._windows)
        if window is not None and window >= 0:
            wins = wins[-window:] if window else []
        rep["windows"] = wins
        return rep

    def reset(self) -> None:
        """Drop all accumulated attribution (bench A/B reuse)."""
        with self._lock:
            self._totals = {}
            self._cur = {}
            self._windows = []
            self._batch_wall_s = 0.0
            self._batch_events = 0
            self._cur_batch_wall_s = 0.0
            self._cur_batch_events = 0
            self._win_t0 = time.monotonic()
            self._win_wall = time.time()


def _device_put_leaves(x):
    """jax.device_put every numpy leaf of a (shallow pytree) kernel
    argument — dict envs, tuples/lists, bare arrays.  jax arrays and
    scalars pass through untouched; the resulting leaves have identical
    avals so the jit call neither recompiles nor re-uploads."""
    import numpy as np
    import jax
    if isinstance(x, np.ndarray):
        return jax.device_put(x)
    if isinstance(x, dict):
        return {k: _device_put_leaves(v) for k, v in x.items()}
    if isinstance(x, tuple):
        return tuple(_device_put_leaves(v) for v in x)
    if isinstance(x, list):
        return [_device_put_leaves(v) for v in x]
    return x


# ---------------------------------------------------------------------------
# roofline fold
# ---------------------------------------------------------------------------

# plan family -> native-C++ roofline family (the bench's native_baseline
# measures the sequence-pattern and partitioned families; window/join/
# filter have no native column yet)
_ROOFLINE_FAMILY = {"scan": "sequence", "dfa": "sequence",
                    "chunk": "sequence", "seq": "sequence",
                    "partitioned": "partitioned"}

_roofline_cache: dict = {"loaded": False, "eps": {}}


def _native_roofline() -> dict:
    """{family: native_cpp_eps} from scripts/perf_baseline.json (or
    $SIDDHI_PERF_BASELINE).  Best-effort: a deployed engine without the
    repo checkout simply reports no roofline columns."""
    if _roofline_cache["loaded"]:
        return _roofline_cache["eps"]
    eps: dict = {}
    path = os.environ.get("SIDDHI_PERF_BASELINE")
    if not path:
        here = os.path.dirname(os.path.abspath(__file__))
        path = os.path.join(here, "..", "..", "scripts",
                            "perf_baseline.json")
    try:
        import json
        with open(path) as f:
            base = json.load(f)
        for key, v in (base.get("native_cpp_eps") or {}).items():
            fam = "sequence" if "sequence" in key else (
                "partitioned" if "partitioned" in key else key)
            if isinstance(v, (int, float)) and v > 0:
                eps[fam] = float(v)
    except Exception:
        pass
    _roofline_cache["loaded"] = True
    _roofline_cache["eps"] = eps
    return eps


def fold_roofline(rep: dict, plans) -> None:
    """Attach per-plan roofline columns to a profile() report: kernel
    eps (from the sampled estimate) vs the native-C++ roofline eps vs
    end-to-end eps — the bench's roofline math, live."""
    native = _native_roofline()
    by_name = {getattr(p, "name", None): p for p in plans}
    for name, pv in (rep.get("plans") or {}).items():
        plan = by_name.get(name)
        fam = getattr(plan, "family", None) if plan is not None else None
        if fam is None and plan is not None:
            # fused multi-query wrapper: the family lives on the inner plan
            fam = getattr(getattr(plan, "inner", None), "family", None)
        roof = {"plan_family": fam,
                "kernel_eps": pv.get("kernel_eps"),
                "end_to_end_eps": pv.get("end_to_end_eps")}
        nat = native.get(_ROOFLINE_FAMILY.get(fam, fam))
        if nat:
            roof["native_cpp_eps"] = nat
            if pv.get("kernel_eps"):
                roof["vs_native_cpp"] = round(pv["kernel_eps"] / nat, 3)
        pv["roofline"] = roof


# ---------------------------------------------------------------------------
# annotation parsing
# ---------------------------------------------------------------------------

def profiler_from_annotations(app) -> Optional[PhaseProfiler]:
    """Build the runtime's profiler from `@app:profile(...)`:

        @app:profile('off')            -- rt.profiler is None (zero cost)
        @app:profile('all')            -- every round blocked + timed
        (default / 'sampled')          -- 1 in 32 rounds sampled
        @app:profile('sample=8')       -- 1 in 8 (positional form)
        @app:profile(sample='8')       -- 1 in 8 (keyed form)
        @app:profile(window='2')       -- ring window seconds
        @app:profile(ring='600')       -- retained window count

    `@app:hostShareAlert('0.7')` sets the windowed host-dispatch-share
    threshold above which the profiler fires a `host_share_breach`
    tracing trigger (flight-recorder dump).  $SIDDHI_PROFILE supplies
    the mode for apps without the annotation."""
    from ..query import ast as qast
    ann = qast.find_annotation(app.annotations, "app:profile")
    mode = None
    sample = None
    window_s = 5.0
    ring = 120
    if ann is not None:
        el = (ann.element() or "").lower() or None
        if el is not None:
            if el.startswith("sample=") or el.startswith("sample:"):
                mode = "sample"
                sample = int(el.split("=" if "=" in el else ":", 1)[1])
            else:
                mode = el
        for k, v in ann.elements:
            if k is None:
                continue
            kl = k.lower()
            if kl == "sample":
                mode = mode or "sample"
                sample = int(v)
            elif kl == "window":
                window_s = float(str(v).split()[0])
            elif kl == "ring":
                ring = int(v)
    if mode is None:
        env = (os.environ.get("SIDDHI_PROFILE") or "").lower() or None
        if env is not None:
            if env.startswith("sample="):
                mode, sample = "sample", int(env.split("=", 1)[1])
            else:
                mode = env
    if mode == "off":
        return None
    if mode in (None, "sampled", "sample", "on"):
        mode = "sample"
    elif mode != "all":
        from .planner import PlanError
        raise PlanError(
            f"@app:profile({mode!r}): unknown mode "
            f"(have: off | sample=N | all)")
    alert = 0.7
    aa = qast.find_annotation(app.annotations, "app:hostShareAlert")
    if aa is not None:
        el = aa.element() or next(
            (v for k, v in aa.elements if k and k.lower() == "share"), None)
        if el is not None:
            alert = float(el)
    return PhaseProfiler(app.name, mode=mode,
                         sample_every=sample if sample else 32,
                         window_s=window_s, host_share_alert=alert,
                         ring=ring)
