"""Device-resident incremental aggregation: the queryable state plane's
kernel layer (docs/AGGREGATION.md "Device lowering").

The host path (core/aggregation.py) reduces every micro-batch with numpy
scatter-reductions and merges the few unique (bucket, group) segments
into per-duration Python dict stores.  This module keeps the ROLLING
BUCKET STATE ITSELF on device: one f64 base matrix per duration
(`[capacity + 1, n_bases]`, the +1 row is scatter scratch for padding),
updated in place by a jitted segment-reduce + scatter-merge step, and
pulled to host ONLY on query / snapshot / restore — ROADMAP item 2's
device-resident steady state applied to aggregation state.

Per ingest batch and duration the division of labor is:

  host   (bucket, group) segment ids via one np.unique over int64 views
         (exact — float group keys compare by bit pattern), slot
         assignment against the per-duration ring (dict lookups on the
         FEW unique segments, never per event);
  device segment_sum / segment_min / segment_max of every base column
         over the batch's inverse segment ids, then one gather +
         elementwise combine + scatter that merges the partials into
         the resident base matrix at the host-assigned slots.

Base arithmetic is float64 end-to-end and the per-segment accumulation
order equals the host path's (both fold events in batch order, and both
merge batch partials into standing state as `old op new`), so the two
paths produce BYTE-IDENTICAL stores — `bench.py --matrix` and the
forced-path differential tests assert exactly that.

Slot lifecycle: the ring starts at `agg_capacity_for(rt)` slots
(annotation > tuning cache > 1024) and doubles when full; @purge
retention frees slots host-side only (the stale device row is simply
overwritten on reuse), so eviction costs zero device traffic.
"""
from __future__ import annotations

import numpy as np

from ..query.ast import Duration

__all__ = ["DeviceAggregationPlan"]


def _pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


class _DurationRing:
    """Host-side slot directory of one duration's device base matrix."""

    __slots__ = ("key_to_slot", "slot_keys", "free", "bases", "dirty")

    def __init__(self, capacity: int, n_bases: int, jnp):
        self.key_to_slot: dict = {}
        self.slot_keys: list = [None] * capacity
        self.free: list = list(range(capacity - 1, -1, -1))
        # +1 scratch row: padded segments scatter there, never read back
        self.bases = jnp.zeros((capacity + 1, n_bases), dtype=jnp.float64)
        self.dirty = False

    @property
    def capacity(self) -> int:
        return len(self.slot_keys)

    def live(self) -> int:
        return len(self.key_to_slot)


class DeviceAggregationPlan:
    """Device-resident per-duration bucket stores for one
    AggregationRuntime.  The owning runtime keeps parsing, filtering,
    retention policy, and the query/snapshot surfaces; this plan owns
    the base matrices and the segment-reduce merge step."""

    def __init__(self, agg, capacity: int):
        import jax
        import jax.numpy as jnp
        self._jax = jax
        self._jnp = jnp
        from .aggregation import _BASES
        self.agg = agg
        self.base_ops = [b for s in agg.sites for b in _BASES[s.name]]
        self.val_of_base = [i for i, s in enumerate(agg.sites)
                            for _b in _BASES[s.name]]
        self.n_bases = agg.n_bases
        self.rings = {d: _DurationRing(capacity, self.n_bases, jnp)
                      for d in agg.durations}
        # one jitted step reused across (capacity, npad, mpad) shapes —
        # jit's shape cache handles retraces; pow2 padding bounds them.
        # Donation hands the old base matrix's buffer to the output
        # (in-place on TPU); CPU ignores donation, so gate the flag to
        # keep tier-1 logs warning-free.
        kwargs = ({} if jax.default_backend() == "cpu"
                  else {"donate_argnums": (0,)})
        self._step = jax.jit(self._make_step(), **kwargs)

    # -- kernel ---------------------------------------------------------------

    def _make_step(self):
        jnp = self._jnp
        base_ops = list(self.base_ops)
        val_of_base = list(self.val_of_base)

        def step(bases, inv, vals, slots, fresh):
            """bases [cap+1, nb] f64; inv [npad] i32 (padding -> dummy
            segment); vals [n_sites, npad] f64; slots [mpad] i32
            (padding -> scratch row cap); fresh [mpad] bool."""
            from jax import ops as jops
            mpad = slots.shape[0]
            cols = []
            for bi, op in enumerate(base_ops):
                if op == "count":
                    v = jnp.ones(inv.shape[0], dtype=jnp.float64)
                else:
                    v = vals[val_of_base[bi]]
                if op in ("sum", "count"):
                    cols.append(jops.segment_sum(v, inv,
                                                 num_segments=mpad))
                elif op == "min":
                    cols.append(jops.segment_min(v, inv,
                                                 num_segments=mpad))
                else:
                    cols.append(jops.segment_max(v, inv,
                                                 num_segments=mpad))
            partial = jnp.stack(cols, axis=1)            # [mpad, nb]
            cur = bases[slots]                           # gather
            merged_cols = []
            for bi, op in enumerate(base_ops):
                if op in ("sum", "count"):
                    merged_cols.append(cur[:, bi] + partial[:, bi])
                elif op == "min":
                    merged_cols.append(jnp.minimum(cur[:, bi],
                                                   partial[:, bi]))
                else:
                    merged_cols.append(jnp.maximum(cur[:, bi],
                                                   partial[:, bi]))
            merged = jnp.stack(merged_cols, axis=1)
            new = jnp.where(fresh[:, None], partial, merged)
            return bases.at[slots].set(new)
        return step

    # -- ingest ---------------------------------------------------------------

    def ingest(self, dur: Duration, buckets: np.ndarray, gkeys: list,
               inv: np.ndarray, vals: list) -> None:
        """Merge one batch's segments into `dur`'s resident store.
        `buckets`/`gkeys` describe the m unique segments (host-decoded
        keys, exactly the host path's dict keys); `inv` maps each of the
        n events onto its segment; `vals` are the per-site f64 value
        columns."""
        jnp = self._jnp
        ring = self.rings[dur]
        m = len(gkeys)
        n = len(inv)
        # slot assignment (the ONLY per-segment host work)
        slot_of = np.empty(m, dtype=np.int32)
        fresh_of = np.zeros(m, dtype=bool)
        for j in range(m):
            key = (int(buckets[j]), gkeys[j])
            slot = ring.key_to_slot.get(key)
            if slot is None:
                if not ring.free:
                    self._grow(ring)
                slot = ring.free.pop()
                ring.key_to_slot[key] = slot
                ring.slot_keys[slot] = key
                fresh_of[j] = True
            slot_of[j] = slot

        npad = _pow2(n)
        mpad = _pow2(m + 1)          # >= 1 dummy segment for event padding
        inv_p = np.full(npad, mpad - 1, dtype=np.int32)
        inv_p[:n] = inv
        vals_p = np.zeros((max(len(vals), 1), npad), dtype=np.float64)
        for i, v in enumerate(vals):
            vals_p[i, :n] = v
        slots_p = np.full(mpad, ring.capacity, dtype=np.int32)  # scratch
        slots_p[:m] = slot_of
        fresh_p = np.ones(mpad, dtype=bool)   # scratch rows: plain set
        fresh_p[:m] = fresh_of
        ring.bases = self._step(ring.bases, jnp.asarray(inv_p),
                                jnp.asarray(vals_p), jnp.asarray(slots_p),
                                jnp.asarray(fresh_p))
        ring.dirty = True

    def _grow(self, ring: _DurationRing) -> None:
        jnp = self._jnp
        old_cap = ring.capacity
        new_cap = old_cap * 2
        host = np.asarray(ring.bases)
        grown = np.zeros((new_cap + 1, self.n_bases), dtype=np.float64)
        grown[:old_cap] = host[:old_cap]
        ring.bases = jnp.asarray(grown)
        ring.slot_keys.extend([None] * (new_cap - old_cap))
        ring.free.extend(range(new_cap - 1, old_cap - 1, -1))

    # -- eviction (host-side slot frees; zero device traffic) -----------------

    def evict_before(self, dur: Duration, cutoff_ms: int) -> int:
        ring = self.rings[dur]
        doomed = [k for k in ring.key_to_slot if k[0] < cutoff_ms]
        for key in doomed:
            slot = ring.key_to_slot.pop(key)
            ring.slot_keys[slot] = None
            ring.free.append(slot)
        if doomed:
            ring.dirty = True    # the materialized dict view is stale now
        return len(doomed)

    # -- host materialization (query / snapshot / restore) --------------------

    def sync_into(self, store: dict) -> None:
        """Rebuild the owning runtime's per-duration dict stores from
        the device matrices — one D2H pull per DIRTY duration, so a
        steady ingest stream pays nothing until somebody asks."""
        for dur, ring in self.rings.items():
            if not ring.dirty:
                continue
            host = np.asarray(ring.bases)
            store[dur] = {key: [float(x) for x in host[slot]]
                          for key, slot in ring.key_to_slot.items()}
            ring.dirty = False

    def load_from(self, store: dict) -> None:
        """Reset the rings from restored host dict stores (snapshot /
        WAL recovery) — the inverse of sync_into, one H2D per
        duration."""
        jnp = self._jnp
        for dur, ring in self.rings.items():
            entries = store.get(dur, {})
            cap = ring.capacity
            while cap < len(entries):
                cap *= 2
            ring.key_to_slot = {}
            ring.slot_keys = [None] * cap
            ring.free = list(range(cap - 1, -1, -1))
            host = np.zeros((cap + 1, self.n_bases), dtype=np.float64)
            for key, bases in sorted(entries.items()):
                slot = ring.free.pop()
                ring.key_to_slot[key] = slot
                ring.slot_keys[slot] = key
                host[slot] = bases
            ring.bases = jnp.asarray(host)
            # restored state lives on device now; the dict store the
            # caller holds is already current
            ring.dirty = False

    # -- telemetry ------------------------------------------------------------

    def live_buckets(self, dur: Duration) -> int:
        return self.rings[dur].live()

    def capacity(self, dur: Duration) -> int:
        return self.rings[dur].capacity
