"""Deep telemetry: pipeline tracing, latency histograms, device metrics,
the statistics/reporter SPI, and Prometheus text exposition.

Folds the former `stats.py` trackers into one observability layer
(reference surface: core:util/statistics/metrics/SiddhiStatisticsManager.java:35-85
— Codahale registry with throughput/latency/memory trackers — plus
core:debugger/SiddhiDebugger.java:36-139).  What the reference cannot see
— and this engine must — are the device-economics quantities that govern
throughput on TPU (SURVEY §3.3; Simultaneous Finite Automata,
arxiv 1405.0562): jit compile count/wall-time, kernel-cache hit rates,
host->device transfer bytes, NFA lane occupancy and state-frontier
width, and window/join carry-buffer fill.

Layout:

  * `Histogram` — HDR-style fixed log-bucket latency histogram (pure
    python, no deps): 16 sub-buckets per octave over 1 µs..~4000 s, so
    p50/p95/p99 carry <= ~4.5 % relative quantile error at O(1)/record.
  * `Tracker` — per-(stream|query|stage) counter + histogram.
  * `PipelineTracer` — span-based flight recorder: a bounded ring of the
    last N batch traces (lex/parse -> plan -> compile -> host-batch-build
    -> device-dispatch -> block_until_ready -> callback-scatter), with
    Chrome `trace_event` JSON export.
  * `StatisticsManager` — hangs off the runtime's batch dispatch loop;
    enabled statistics cost one clock read per (stream, plan) batch.
  * reporter SPI (`register_stats_reporter`) with console / log /
    prometheus reporters; `render_prometheus` emits the text exposition
    served by `service.py`'s `GET /metrics`.
  * `SiddhiDebugger` — micro-batch-boundary breakpoints (unchanged).

Pipeline stage names (the leaf spans; `report()["stages"]`):
  parse, plan, compile, host_build, ingest, kernel, transfer, scatter.
`kernel` is the jitted dispatch call (async: it returns once the device
has the work); `transfer` is block_until_ready + the D2H pull, so on the
async path it includes the device execution wait.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import defaultdict, deque
from typing import Callable, Optional

STAGES = ("parse", "plan", "compile", "host_build", "ingest", "kernel",
          "transfer", "scatter")


# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------

class Histogram:
    """HDR-style fixed log-bucket histogram over seconds.

    Bucket i covers [MIN * 2^(i/SUB), MIN * 2^((i+1)/SUB)): geometric
    buckets, SUB per octave — the classic HdrHistogram trade of bounded
    relative error for O(1) record and a few hundred ints of memory.
    Values clamp at both ends (1 µs .. ~4000 s)."""

    SUB = 16                       # sub-buckets per octave
    MIN = 1e-6                     # 1 µs resolution floor
    OCTAVES = 32                   # ~4300 s ceiling
    NBUCKETS = SUB * OCTAVES

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts = [0] * self.NBUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def record(self, seconds: float) -> None:
        if seconds < 0.0:
            seconds = 0.0
        self.count += 1
        self.sum += seconds
        if seconds < self.min:
            self.min = seconds
        if seconds > self.max:
            self.max = seconds
        if seconds <= self.MIN:
            i = 0
        else:
            i = int(math.log2(seconds / self.MIN) * self.SUB)
            if i >= self.NBUCKETS:
                i = self.NBUCKETS - 1
        self.counts[i] += 1

    @classmethod
    def bucket_hi(cls, i: int) -> float:
        """Upper bound (seconds) of bucket i."""
        return cls.MIN * 2.0 ** ((i + 1) / cls.SUB)

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100] -> seconds (bucket upper bound, clamped to the
        observed max so a lone sample reports itself exactly)."""
        if not self.count:
            return None
        target = max(1, math.ceil(self.count * p / 100.0))
        acc = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            acc += c
            if acc >= target:
                return min(self.bucket_hi(i), self.max)
        return self.max

    def quantiles(self, ps=(50, 95, 99)) -> dict:
        return {p: self.percentile(p) for p in ps}

    def reset(self) -> None:
        self.counts = [0] * self.NBUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0


# ---------------------------------------------------------------------------
# trackers
# ---------------------------------------------------------------------------

# coarse upper bounds (seconds) for the Prometheus histogram render +
# its trace-id exemplars; the +Inf bucket is implicit
EXEMPLAR_LE = (0.001, 0.005, 0.025, 0.1, 0.5, 2.5)


def _le_label(seconds: float) -> str:
    for le in EXEMPLAR_LE:
        if seconds <= le:
            return str(le)
    return "+Inf"


class Tracker:
    __slots__ = ("events", "batches", "seconds", "hist", "exemplars")

    def __init__(self):
        self.events = 0
        self.batches = 0
        self.seconds = 0.0
        self.hist = Histogram()
        # le-label -> (trace_id, observed_seconds, unix_ts): the last
        # TRACED sample per coarse bucket — OpenMetrics exemplars on
        # the /metrics histogram render (docs/OBSERVABILITY.md)
        self.exemplars: Optional[dict] = None

    def observe(self, seconds: float, events: int = 0,
                trace_id: Optional[str] = None) -> None:
        """One timed batch; a traced frame's id becomes the bucket
        exemplar linking the latency histogram back to its span tree."""
        self.events += events
        self.batches += 1
        self.seconds += seconds
        self.hist.record(seconds)
        if trace_id is not None:
            if self.exemplars is None:
                self.exemplars = {}
            self.exemplars[_le_label(seconds)] = (
                trace_id, seconds, time.time())

    def bucket_counts(self) -> dict:
        """Cumulative sample counts per EXEMPLAR_LE bound (+Inf last),
        aggregated from the fine log buckets — the Prometheus
        histogram render; computed at scrape time, never on the hot
        path."""
        edges = EXEMPLAR_LE
        totals = [0] * (len(edges) + 1)
        for i, c in enumerate(self.hist.counts):
            if not c:
                continue
            hi = self.hist.bucket_hi(i)
            for j, le in enumerate(edges):
                if hi <= le * (1.0 + 1e-9):
                    totals[j] += c
                    break
            else:
                totals[-1] += c
        out = {}
        acc = 0
        for j, le in enumerate(edges):
            acc += totals[j]
            out[str(le)] = acc
        out["+Inf"] = self.hist.count
        return out

    def as_dict(self, buckets: bool = False) -> dict:
        d = {"events": self.events, "batches": self.batches}
        if self.seconds:
            d["seconds"] = self.seconds
            if self.events:
                d["latency_us_per_event"] = 1e6 * self.seconds / self.events
            # key OMITTED (not None) when seconds is falsy: a consumer
            # summing/dividing report values must not meet nulls
            d["throughput_eps"] = self.events / self.seconds
        if self.hist.count:
            for p in (50, 95, 99):
                v = self.hist.percentile(p)
                if v is not None:
                    d[f"p{p}_ms"] = round(v * 1e3, 4)
            if buckets:
                d["buckets"] = self.bucket_counts()
                if self.exemplars:
                    # list() snapshot: a scrape races the dispatch
                    # thread's first insert into a new coarse bucket
                    d["exemplars"] = {k: list(v) for k, v in
                                      list(self.exemplars.items())}
        return d


# ---------------------------------------------------------------------------
# span tracing / flight recorder
# ---------------------------------------------------------------------------

class PipelineTracer:
    """Bounded in-memory flight recorder of the last N batch traces.

    A "batch trace" is the list of stage spans recorded while one
    micro-batch moved through the dispatch loop; spans recorded outside
    a batch scope (parse/plan/compile at build time) become standalone
    one-span traces.  Span nesting is positional — Chrome's trace viewer
    reconstructs parent/child from (ts, dur) containment per thread, so
    the recorder stores flat (name, t0, dur, plan) tuples."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self.enabled = False
        self.traces: deque = deque(maxlen=capacity)
        self._tls = threading.local()
        self._t0 = time.perf_counter()

    # -- batch scope -------------------------------------------------------

    def begin_batch(self, label: str) -> None:
        if not self.enabled:
            return
        self._tls.spans = []
        self._tls.label = label
        self._tls.bt0 = time.perf_counter()

    def end_batch(self) -> None:
        if not self.enabled:
            return
        spans = getattr(self._tls, "spans", None)
        if spans is None:
            return
        now = time.perf_counter()
        self.traces.append({
            "label": self._tls.label,
            "t0": self._tls.bt0 - self._t0,
            "dur": now - self._tls.bt0,
            "tid": threading.get_ident() % 100_000,
            "spans": spans,
        })
        self._tls.spans = None

    def add(self, name: str, t0: float, dur: float,
            plan: Optional[str] = None) -> None:
        if not self.enabled:
            return
        rec = (name, t0 - self._t0, dur, plan)
        spans = getattr(self._tls, "spans", None)
        if spans is None:            # standalone span (build-time etc.)
            self.traces.append({
                "label": name, "t0": t0 - self._t0, "dur": dur,
                "tid": threading.get_ident() % 100_000, "spans": [rec]})
        else:
            spans.append(rec)

    # -- export ------------------------------------------------------------

    def chrome_trace(self) -> list:
        """Chrome `trace_event` JSON (the array form): load via
        chrome://tracing or https://ui.perfetto.dev."""
        evs = []
        for tr in list(self.traces):
            evs.append({"name": tr["label"], "cat": "batch", "ph": "X",
                        "ts": round(tr["t0"] * 1e6, 1),
                        "dur": round(tr["dur"] * 1e6, 1),
                        "pid": 1, "tid": tr["tid"]})
            for name, t0, dur, plan in tr["spans"]:
                ev = {"name": name, "cat": "stage", "ph": "X",
                      "ts": round(t0 * 1e6, 1), "dur": round(dur * 1e6, 1),
                      "pid": 1, "tid": tr["tid"]}
                if plan:
                    ev["args"] = {"plan": plan}
                evs.append(ev)
        return evs

    def export_chrome_trace(self, path: str) -> int:
        evs = self.chrome_trace()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(evs, f)
        os.replace(tmp, path)
        return len(evs)

    def reset(self) -> None:
        self.traces.clear()


class _Noop:
    seconds = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class _StageTimer:
    __slots__ = ("mgr", "name", "events", "plan", "t0", "seconds",
                 "_pspan")

    def __init__(self, mgr, name, events, plan, pspan=None):
        self.mgr = mgr
        self.name = name
        self.events = events
        self.plan = plan
        self.seconds = 0.0
        # piggy-backed profiler phase span (core/profiler.py): stages
        # that map onto a dispatch phase record both from one timer
        self._pspan = pspan

    def __enter__(self):
        if self._pspan is not None:
            self._pspan.__enter__()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.t0
        self.seconds = dt
        self.mgr.stages[self.name].observe(dt, self.events)
        self.mgr.tracer.add(self.name, self.t0, dt, plan=self.plan)
        if self._pspan is not None:
            self._pspan.__exit__(*exc)
        return False


class _PlanTimer:
    __slots__ = ("mgr", "name", "n", "start")

    def __init__(self, mgr, name, n):
        self.mgr = mgr
        self.name = name
        self.n = n

    def __enter__(self):
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.start
        self.mgr.query[self.name].observe(dt, self.n)
        self.mgr.tracer.add(f"query:{self.name}", self.start, dt)
        return False


class _StreamTimer:
    __slots__ = ("mgr", "sid", "n", "start", "trace_id")

    def __init__(self, mgr, sid, n, trace_id=None):
        self.mgr = mgr
        self.sid = sid
        self.n = n
        self.trace_id = trace_id

    def __enter__(self):
        self.mgr.tracer.begin_batch(f"{self.sid} x{self.n}")
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.start
        self.mgr.stream_in[self.sid].observe(dt, self.n,
                                             trace_id=self.trace_id)
        self.mgr.tracer.end_batch()
        return False


# ---------------------------------------------------------------------------
# XLA persistent-cache observation (process-global, best-effort)
# ---------------------------------------------------------------------------

XLA_CACHE = {"hits": 0, "misses": 0}


def _watch_xla_cache() -> None:
    """Count the persistent compilation cache's hit/miss events (the
    disk cache enabled by `_enable_kernel_cache`).  Event names are jax
    internals — match loosely and tolerate absence."""
    try:
        from jax._src import monitoring as _mon

        def _listener(event, *a, **k):
            if "cache_hit" in event:
                XLA_CACHE["hits"] += 1
            elif "cache_miss" in event:
                XLA_CACHE["misses"] += 1
        _mon.register_event_listener(_listener)
    except Exception:      # pragma: no cover - observation is best-effort
        pass


_watch_xla_cache()


# ---------------------------------------------------------------------------
# kernel-call instrumentation helper (shared by the device modules)
# ---------------------------------------------------------------------------

def env_nbytes(env) -> int:
    """Host->device payload size of one kernel argument dict."""
    try:
        return sum(int(getattr(v, "nbytes", 0)) for v in env.values())
    except Exception:
        return 0


def call_kernel(stats, plan: str, fn, args: tuple, *, cache_hit: bool,
                nbytes: int = 0, prof=None):
    """Invoke a jitted kernel `fn(*args)` recording: per-plan fn-cache
    hit/miss, H2D bytes, and a `compile` (fn-cache miss — the call that
    pays trace + XLA compilation) or `kernel` (steady-state dispatch)
    stage span.  Classification rides the caller's cache probe so a
    block compiled while stats were off is never misreported as a
    compile after `enable_stats(True)`.

    `prof` (core/profiler.py PhaseProfiler, or None) routes the call
    through the sampled h2d/kernel probe and records H2D bytes into the
    phase plane.  Note: on a *sampled* round the stats `kernel` span
    includes the probe's block_until_ready (full device wait), where
    the steady-state span measures only the async dispatch — the
    profiler's kernel_compute estimate is the authoritative device
    time; the stage histogram keeps its dispatch-latency meaning for
    the 31-in-32 unsampled majority."""
    if prof is not None and nbytes:
        prof.note_bytes(plan, "h2d", nbytes)
    if stats is None or not stats.enabled:
        if prof is not None:
            return prof.run_kernel(fn, args, cache_hit=cache_hit)
        return fn(*args)
    stats.on_kernel_cache(plan, cache_hit)
    if nbytes:
        stats.add_transfer_bytes(plan, nbytes)
    with stats.stage("kernel" if cache_hit else "compile", plan=plan) as sp:
        out = prof.run_kernel(fn, args, cache_hit=cache_hit) \
            if prof is not None else fn(*args)
    if not cache_hit:
        stats.on_compile(plan, sp.seconds)
    return out


# ---------------------------------------------------------------------------
# reporter SPI
# ---------------------------------------------------------------------------

REPORTERS: dict = {}

# latest Prometheus exposition per app, refreshed by the `prometheus`
# reporter (scrape-side consumers can also hit service.py's GET /metrics,
# which renders live instead)
PROM_LATEST: dict = {}

# latest raw report per app — the $SIDDHI_PROM_FILE writer renders ALL
# apps from here so concurrent reporters don't clobber each other's series
_PROM_REPORTS: dict = {}


def register_stats_reporter(name: str, fn, meta=None) -> None:
    """fn(app_name, report_dict) — the reporter SPI (reference:
    SiddhiStatisticsManager.java:35-85 console/JMX reporters).
    Re-registering a name overrides it."""
    from ..extension import register_meta
    register_meta("stats-reporter", meta)
    REPORTERS[name.lower()] = fn


def _console_reporter(app: str, report: dict) -> None:
    import sys
    print(f"[siddhi-stats] {app}: {json.dumps(report, default=str)}",
          file=sys.stderr)


def _log_reporter(app: str, report: dict) -> None:
    import logging
    logging.getLogger("siddhi_tpu.stats").info("%s: %s", app, report)


def _prometheus_reporter(app: str, report: dict) -> None:
    """Render the report as Prometheus text exposition; kept in
    PROM_LATEST[app] and (optionally) written atomically to
    $SIDDHI_PROM_FILE for file-based scrape setups (node_exporter
    textfile collector).  The file always carries EVERY reporting app
    (rendered from the latest report of each), so two runtimes sharing
    one process don't alternate-clobber each other's series."""
    PROM_LATEST[app] = render_prometheus({app: report})
    _PROM_REPORTS[app] = report
    path = os.environ.get("SIDDHI_PROM_FILE")
    if path:
        try:
            text = render_prometheus(dict(sorted(_PROM_REPORTS.items())))
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
        except OSError:
            pass


REPORTERS["console"] = _console_reporter
REPORTERS["log"] = _log_reporter
REPORTERS["prometheus"] = _prometheus_reporter


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_DEV_COUNTERS = {
    "compiles": ("siddhi_tpu_jit_compiles_total",
                 "jit kernel compilations per plan"),
    "compile_seconds": ("siddhi_tpu_jit_compile_seconds_total",
                        "wall time spent in jit compilation per plan"),
    "cache_hits": ("siddhi_tpu_kernel_cache_hits_total",
                   "per-plan jitted-block cache hits"),
    "cache_misses": ("siddhi_tpu_kernel_cache_misses_total",
                     "per-plan jitted-block cache misses"),
    "h2d_bytes": ("siddhi_tpu_h2d_transfer_bytes_total",
                  "host->device payload bytes shipped per plan"),
}


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n",
                                                                    "\\n")


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f != f or f in (math.inf, -math.inf):
        return "NaN" if f != f else ("+Inf" if f > 0 else "-Inf")
    return repr(f)


class _Prom:
    """Accumulates samples grouped per metric so # HELP / # TYPE render
    exactly once per metric name (the exposition-format requirement).
    `openmetrics=True` attaches exemplars and the `# EOF` terminator —
    exemplar syntax is ONLY legal under the OpenMetrics content type; a
    classic text-format (0.0.4) scrape must never meet one, or a real
    Prometheus parser rejects the whole exposition."""

    def __init__(self, openmetrics: bool = False):
        self.openmetrics = openmetrics
        self.metrics: dict = {}          # name -> (type, help, [samples])

    def add(self, name, mtype, help_, labels: dict, value,
            suffix: str = "", exemplar=None) -> None:
        if value is None:
            return
        ent = self.metrics.setdefault(name, (mtype, help_, []))
        lab = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
        line = (f"{name}{suffix}{{{lab}}} {_fmt(value)}"
                if lab else f"{name}{suffix} {_fmt(value)}")
        if exemplar is not None and self.openmetrics:
            # OpenMetrics exemplar syntax: `# {labels} value timestamp`
            # — the trace id links this bucket back to its span tree
            tid, ev, ets = exemplar
            line += (f' # {{trace_id="{_esc(tid)}"}} '
                     f'{_fmt(float(ev))} {_fmt(float(ets))}')
        ent[2].append(line)

    def render(self) -> str:
        out = []
        for name, (mtype, help_, samples) in self.metrics.items():
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {mtype}")
            out.extend(samples)
        if self.openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n"


def _summary(doc: _Prom, name: str, help_: str, labels: dict, td: dict):
    """One tracker dict -> a Prometheus summary (quantiles + _sum/_count)."""
    for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
        if key in td:
            doc.add(name, "summary", help_,
                    {**labels, "quantile": str(q)}, td[key] / 1e3)
    doc.add(name, "summary", help_, labels, td.get("seconds", 0.0),
            suffix="_sum")
    doc.add(name, "summary", help_, labels, td.get("batches", 0),
            suffix="_count")


def render_prometheus(reports: dict, openmetrics: bool = False) -> str:
    """reports: {app_name: StatisticsManager.report() dict} ->
    text exposition.  Default: classic Prometheus format 0.0.4 (no
    exemplars).  `openmetrics=True` — served when the scraper's Accept
    header negotiates `application/openmetrics-text` — attaches
    trace-id exemplars to histogram buckets and terminates with
    `# EOF`."""
    doc = _Prom(openmetrics=openmetrics)
    for app, rep in reports.items():
        al = {"app": app}
        doc.add("siddhi_tpu_uptime_seconds", "gauge",
                "runtime uptime", al, rep.get("uptime_s"))
        for sid, td in rep.get("streams", {}).items():
            sl = {**al, "stream": sid}
            doc.add("siddhi_tpu_events_total", "counter",
                    "events ingested per stream", sl, td.get("events", 0))
            doc.add("siddhi_tpu_batches_total", "counter",
                    "micro-batches dispatched per stream", sl,
                    td.get("batches", 0))
            if "p50_ms" in td:
                _summary(doc, "siddhi_tpu_stream_latency_seconds",
                         "per-batch dispatch latency per stream", sl, td)
            bk = td.get("buckets")
            if bk:
                # real histogram render of the same latency data: the
                # bucket lines carry trace-id exemplars for frames the
                # tracing plane sampled (docs/OBSERVABILITY.md)
                hn = "siddhi_tpu_stream_dispatch_latency_seconds"
                hh = ("per-batch dispatch latency histogram per stream; "
                      "buckets carry trace-id exemplars")
                ex = td.get("exemplars") or {}
                for le, c in bk.items():
                    doc.add(hn, "histogram", hh, {**sl, "le": le}, c,
                            suffix="_bucket",
                            exemplar=tuple(ex[le]) if le in ex else None)
                doc.add(hn, "histogram", hh, sl, td.get("seconds", 0.0),
                        suffix="_sum")
                doc.add(hn, "histogram", hh, sl, td.get("batches", 0),
                        suffix="_count")
        for qn, td in rep.get("queries", {}).items():
            ql = {**al, "query": qn}
            doc.add("siddhi_tpu_query_events_total", "counter",
                    "events processed per query", ql, td.get("events", 0))
            _summary(doc, "siddhi_tpu_query_latency_seconds",
                     "per-batch processing latency per query", ql, td)
        for st, td in rep.get("stages", {}).items():
            _summary(doc, "siddhi_tpu_stage_latency_seconds",
                     "per-span latency per pipeline stage",
                     {**al, "stage": st}, td)
        for plan, m in rep.get("device", {}).items():
            pl = {**al, "plan": plan}
            for key, v in m.items():
                if key in _DEV_COUNTERS:
                    name, help_ = _DEV_COUNTERS[key]
                    doc.add(name, "counter", help_, pl, v)
                elif isinstance(v, (int, float)):
                    doc.add("siddhi_tpu_device", "gauge",
                            "device-side gauges (lane occupancy, frontier "
                            "width, buffer fill, drops)",
                            {**pl, "metric": key}, v)
        # fault-tolerance series (core/faults.py)
        for scope, fd in rep.get("faults", {}).items():
            for action, n in fd.items():
                doc.add("siddhi_tpu_faults_total", "counter",
                        "fault dispositions per stream and action",
                        {**al, "stream": scope, "action": action}, n)
        if "degraded_plans" in rep:
            doc.add("siddhi_tpu_degraded_plans", "gauge",
                    "device plans quarantined onto the interpreter path",
                    al, len(rep["degraded_plans"]))
        # placement plane (core/placement.py): the no-silent-demotions
        # series — every interpreter fallback carries a recorded reason,
        # and this gauge is how a future silent demotion shows up on a
        # dashboard before anyone reads explain()
        pl = rep.get("placement")
        if pl:
            doc.add("siddhi_tpu_interp_demotions", "gauge",
                    "queries demoted off the device path with a recorded "
                    "Demotion reason (rt.explain() has the chain)",
                    al, pl.get("interp_demotions", 0))
            doc.add("siddhi_tpu_placement_queries", "gauge",
                    "query count per chosen execution path",
                    {**al, "path": "device"}, pl.get("device", 0))
            doc.add("siddhi_tpu_placement_queries", "gauge",
                    "query count per chosen execution path",
                    {**al, "path": "interpreter"}, pl.get("interpreter", 0))
            for qn, qd in pl.get("queries", {}).items():
                ql = {**al, "query": qn, "path": qd.get("path", "")}
                if qd.get("family"):
                    ql["family"] = qd["family"]
                doc.add("siddhi_tpu_query_placement", "gauge",
                        "chosen execution path per query (1 = placed)",
                        ql, 1)
        es = rep.get("error_store")
        if es:
            doc.add("siddhi_tpu_error_store_entries", "gauge",
                    "replayable entries captured in the ErrorStore", al,
                    es.get("entries", 0))
            doc.add("siddhi_tpu_error_store_evicted_total", "counter",
                    "ErrorStore entries evicted by the capacity bound", al,
                    es.get("evicted", 0))
        for sid, sd in rep.get("sources", {}).items():
            sl = {**al, "stream": sid}
            doc.add("siddhi_tpu_source_dropped_events_total", "counter",
                    "malformed source messages logged and dropped", sl,
                    sd.get("dropped_events", 0))
            doc.add("siddhi_tpu_source_stored_events_total", "counter",
                    "malformed source messages captured in the ErrorStore",
                    sl, sd.get("stored_events", 0))
        _SINK_COUNTERS = (("published", "siddhi_tpu_sink_published_total",
                           "payloads delivered per sink"),
                          ("retries", "siddhi_tpu_sink_retries_total",
                           "publish retries per sink"),
                          ("failures", "siddhi_tpu_sink_failures_total",
                           "publish attempt failures per sink"),
                          ("stored", "siddhi_tpu_sink_stored_total",
                           "payloads captured in the ErrorStore per sink"),
                          # net egress (siddhi_tpu/net sink.py): batched
                          # columnar frames shipped over the wire
                          ("frames_out", "siddhi_tpu_sink_frames_out_total",
                           "columnar frames shipped by a net sink"),
                          ("bytes_out", "siddhi_tpu_sink_bytes_out_total",
                           "wire bytes shipped by a net sink"))
        for label, m in rep.get("sinks", {}).items():
            kl = {**al, "sink": label}
            for key, name, help_ in _SINK_COUNTERS:
                if m.get(key):
                    doc.add(name, "counter", help_, kl, m[key])
            if "circuit_state" in m:
                doc.add("siddhi_tpu_sink_circuit_state", "gauge",
                        "per-sink circuit breaker state "
                        "(0=closed 1=half-open 2=open)", kl,
                        m["circuit_state"])
                doc.add("siddhi_tpu_sink_circuit_opens_total", "counter",
                        "times the per-sink circuit breaker opened", kl,
                        m.get("circuit_opens", 0))
        # serving-plane series (siddhi_tpu/net): wire ingest + admission
        _NET_COUNTERS = (
            ("frames_in", "siddhi_tpu_net_frames_total",
             "wire frames received per stream"),
            ("events_in", "siddhi_tpu_net_events_total",
             "events received over the serving plane per stream"),
            ("bytes_in", "siddhi_tpu_net_bytes_total",
             "payload bytes received per stream"),
            ("admitted_events", "siddhi_tpu_net_admitted_events_total",
             "events admitted by the rate controller per stream"),
            ("shed_events", "siddhi_tpu_net_shed_events_total",
             "events shed into the ErrorStore per stream"),
            ("shed_frames", "siddhi_tpu_net_shed_frames_total",
             "frames shed into the ErrorStore per stream"),
            ("credit_granted", "siddhi_tpu_net_credit_granted_total",
             "credit frames granted to producers per stream"),
            ("protocol_errors", "siddhi_tpu_net_protocol_errors_total",
             "malformed/checksum-failed frames per stream"))
        _NET_GAUGES = (
            ("pending_frames", "siddhi_tpu_net_pending_frames",
             "frames parked by the 'oldest' admission queue"),
            ("pending_bytes", "siddhi_tpu_net_pending_bytes",
             "bytes parked by the 'oldest' admission queue"),
            ("rate_factor", "siddhi_tpu_net_admission_factor",
             "SLO-driven admission throttle (1.0 = full rate)"),
            ("open_connections", "siddhi_tpu_net_open_connections",
             "live ingest connections per stream"),
            ("ring_occupancy", "siddhi_tpu_net_ring_occupancy",
             "shm-ring frames awaiting the consumer"),
            ("blocked_seconds", "siddhi_tpu_net_blocked_seconds",
             "cumulative block-policy backpressure wait"))
        for sid, m in rep.get("net", {}).items():
            nl = {**al, "stream": sid}
            for key, name, help_ in _NET_COUNTERS:
                if key in m:
                    doc.add(name, "counter", help_, nl, m[key])
            for key, name, help_ in _NET_GAUGES:
                if key in m:
                    doc.add(name, "gauge", help_, nl, m[key])
        # adaptive-geometry series (core/autotune.py)
        tun = rep.get("tuning")
        if tun:
            doc.add("siddhi_tpu_tuning_cache_hits_total", "counter",
                    "tuning-cache lookups that found a persisted geometry",
                    al, tun.get("cache_hits", 0))
            doc.add("siddhi_tpu_tuning_cache_misses_total", "counter",
                    "tuning-cache lookups that fell back to defaults",
                    al, tun.get("cache_misses", 0))
            doc.add("siddhi_tpu_tuning_cache_entries", "gauge",
                    "persisted geometry winners in the tuning cache",
                    al, tun.get("tuning_cache_entries"))
        # queryable-state series (core/aggregation.py): per-duration
        # bucket/eviction gauges, group cardinality, and the store-query
        # latency histogram (exemplar-carrying, like the stream
        # dispatch histogram above)
        ag = rep.get("aggregation")
        if ag:
            for an, m in (ag.get("aggregations") or {}).items():
                gl = {**al, "aggregation": an}
                doc.add("siddhi_tpu_agg_groups", "gauge",
                        "live group keys per aggregation", gl,
                        m.get("groups"))
                doc.add("siddhi_tpu_agg_device", "gauge",
                        "aggregation lowered to the device plan "
                        "(1 device, 0 host; rt.explain() has the D-AGG "
                        "chain)", gl, 1 if m.get("device") else 0)
                for dn, dd in (m.get("durations") or {}).items():
                    dl = {**gl, "duration": dn}
                    doc.add("siddhi_tpu_agg_buckets", "gauge",
                            "live rollup buckets per aggregation "
                            "duration", dl, dd.get("buckets"))
                    doc.add("siddhi_tpu_agg_evicted_total", "counter",
                            "rollup buckets evicted by @purge retention "
                            "per aggregation duration", dl,
                            dd.get("evicted", 0))
            sq = ag.get("store_query")
            if sq:
                doc.add("siddhi_tpu_agg_store_queries_total", "counter",
                        "on-demand store queries executed (REST + wire "
                        "QUERY frames)", al, sq.get("batches", 0))
                doc.add("siddhi_tpu_agg_store_query_rows_total", "counter",
                        "rows returned by on-demand store queries", al,
                        sq.get("events", 0))
                bk = sq.get("buckets")
                if bk:
                    hn = "siddhi_tpu_agg_store_query_latency_seconds"
                    hh = ("store-query execution latency histogram; "
                          "buckets carry trace-id exemplars")
                    ex = sq.get("exemplars") or {}
                    for le, c in bk.items():
                        doc.add(hn, "histogram", hh, {**al, "le": le}, c,
                                suffix="_bucket",
                                exemplar=tuple(ex[le]) if le in ex
                                else None)
                    doc.add(hn, "histogram", hh, al,
                            sq.get("seconds", 0.0), suffix="_sum")
                    doc.add(hn, "histogram", hh, al,
                            sq.get("batches", 0), suffix="_count")
        # durability series (core/wal.py): WAL volume, fsync latency,
        # segment churn, and the crash-recovery gauges
        dur = rep.get("durability")
        if dur:
            doc.add("siddhi_tpu_wal_enabled", "gauge",
                    "write-ahead log live (0 with @app:durability "
                    "declared means durability silently lost — alert)",
                    al, 1 if dur.get("enabled") else 0)
            _WAL_COUNTERS = (
                ("appended_frames", "siddhi_tpu_wal_appends_total",
                 "admitted frames appended to the WAL"),
                ("appended_events", "siddhi_tpu_wal_events_total",
                 "events covered by WAL records"),
                ("appended_bytes", "siddhi_tpu_wal_bytes_total",
                 "bytes appended to the WAL"),
                ("fsyncs", "siddhi_tpu_wal_fsyncs_total",
                 "WAL fsync calls (per-append under 'fsync', "
                 "barrier-only under 'batch')"),
                ("corrupt_skipped", "siddhi_tpu_wal_corrupt_skipped_total",
                 "torn/corrupt WAL records or segments dropped by "
                 "recovery scans"),
                ("truncated_segments",
                 "siddhi_tpu_wal_truncated_segments_total",
                 "sealed segments deleted behind snapshot barriers"))
            for key, name, help_ in _WAL_COUNTERS:
                if key in dur:
                    doc.add(name, "counter", help_, al, dur[key])
            doc.add("siddhi_tpu_wal_segments", "gauge",
                    "live WAL segments (sealed + open)", al,
                    dur.get("segments"))
            for sid, s in (dur.get("last_seq") or {}).items():
                doc.add("siddhi_tpu_wal_last_seq", "gauge",
                        "last durable frame seq per stream",
                        {**al, "stream": sid}, s)
            fs = dur.get("fsync")
            if fs:
                _summary(doc, "siddhi_tpu_wal_fsync_latency_seconds",
                         "WAL fsync latency", al, fs)
            rec = dur.get("recovery")
            if rec:
                doc.add("siddhi_tpu_wal_recovery_seconds", "gauge",
                        "wall time of the last crash recovery "
                        "(restore + WAL replay)", al, rec.get("recovery_s"))
                doc.add("siddhi_tpu_wal_replayed_frames", "gauge",
                        "frames replayed by the last recovery", al,
                        rec.get("replayed_frames"))
                doc.add("siddhi_tpu_wal_replayed_events", "gauge",
                        "events replayed by the last recovery", al,
                        rec.get("replayed_events"))
        # replication series (core/replication.py): role, lag, volume,
        # fencing rejections — the HA dashboard (docs/OBSERVABILITY.md)
        repl = rep.get("replication")
        if repl:
            doc.add("siddhi_tpu_repl_role", "gauge",
                    "replication role (1 primary, 0 standby)",
                    {**al, "role": str(repl.get("role"))},
                    1 if repl.get("role") == "primary" else 0)
            doc.add("siddhi_tpu_repl_standbys", "gauge",
                    "standby replicas attached to this primary", al,
                    repl.get("standbys", 0))
            doc.add("siddhi_tpu_repl_lag_records", "gauge",
                    "WAL records appended locally but not yet "
                    "acknowledged by a standby", al,
                    repl.get("lag_records", 0))
            doc.add("siddhi_tpu_repl_lag_seconds", "gauge",
                    "seconds since the last standby ack/heartbeat "
                    "(primary) or applied record (standby)", al,
                    repl.get("lag_seconds", 0.0))
            _REPL_COUNTERS = (
                ("shipped_records", "siddhi_tpu_repl_shipped_records_total",
                 "WAL records shipped to standbys"),
                ("shipped_bytes", "siddhi_tpu_repl_shipped_bytes_total",
                 "WAL bytes shipped to standbys"),
                ("shipped_snapshots",
                 "siddhi_tpu_repl_shipped_snapshots_total",
                 "snapshot revisions shipped for catch-up"),
                ("applied_records", "siddhi_tpu_repl_applied_records_total",
                 "replicated WAL records appended to the local log"),
                ("applied_snapshots",
                 "siddhi_tpu_repl_applied_snapshots_total",
                 "shipped snapshot revisions saved locally"),
                ("acks", "siddhi_tpu_repl_acks_total",
                 "standby append-acks received"),
                ("rejected_generation",
                 "siddhi_tpu_repl_rejected_generation_total",
                 "frames/links rejected by the fencing token "
                 "(deposed-primary writes)"),
                ("barrier_timeouts",
                 "siddhi_tpu_repl_barrier_timeouts_total",
                 "semi-sync durable-ACK barriers failed waiting for a "
                 "standby"))
            for key, name, help_ in _REPL_COUNTERS:
                if key in repl:
                    doc.add(name, "counter", help_, al, repl[key])
        # frame-tracing series (core/tracing.py)
        trc = rep.get("tracing")
        if trc:
            doc.add("siddhi_tpu_trace_traces_total", "counter",
                    "frame traces started (sampled + producer-stamped)",
                    al, trc.get("traces_started"))
            doc.add("siddhi_tpu_trace_ring_spans", "gauge",
                    "spans currently retained in the flight ring", al,
                    trc.get("ring_spans"))
            doc.add("siddhi_tpu_trace_dumps", "gauge",
                    "retained trigger-promoted trace dumps", al,
                    trc.get("dumps"))
            for kind, n in (trc.get("triggers") or {}).items():
                doc.add("siddhi_tpu_trace_triggers_total", "counter",
                        "trace-dump triggers by kind",
                        {**al, "kind": kind}, n)
        # device-time attribution series (core/profiler.py)
        prof = rep.get("profile")
        if prof:
            for plan, pd in (prof.get("plans") or {}).items():
                pl2 = {**al, "plan": plan}
                for phase, secs in (pd.get("phases_s") or {}).items():
                    doc.add("siddhi_tpu_phase_seconds_total", "counter",
                            "attributed wall seconds per plan and "
                            "dispatch phase (sampled kernel/h2d "
                            "extrapolated; docs/OBSERVABILITY.md)",
                            {**pl2, "phase": phase}, secs)
                if "host_dispatch_share" in pd:
                    doc.add("siddhi_tpu_host_dispatch_share", "gauge",
                            "share of a plan's dispatch wall spent "
                            "host-side (pack/unpack + python + sink)",
                            pl2, pd["host_dispatch_share"])
            agg = prof.get("aggregate")
            if agg and "host_dispatch_share" in agg:
                doc.add("siddhi_tpu_host_dispatch_share", "gauge",
                        "share of a plan's dispatch wall spent "
                        "host-side (pack/unpack + python + sink)",
                        {**al, "plan": "_aggregate"},
                        agg["host_dispatch_share"])
        slo = rep.get("slo")
        if slo:
            doc.add("siddhi_tpu_slo_target_seconds", "gauge",
                    "@app:latencySLO p99 target", al,
                    (slo["target_ms"] / 1e3) if "target_ms" in slo
                    else None)
            doc.add("siddhi_tpu_slo_window_p99_seconds", "gauge",
                    "SLO controller's last decision-window p99", al,
                    (slo["window_p99_ms"] / 1e3)
                    if "window_p99_ms" in slo else None)
            doc.add("siddhi_tpu_slo_batch_target", "gauge",
                    "SLO controller's current micro-batch target", al,
                    slo.get("batch_target"))
            for action, n in slo.get("decisions", {}).items():
                doc.add("siddhi_tpu_slo_decisions_total", "counter",
                        "AIMD controller decisions by action",
                        {**al, "action": action}, n)
    # process-wide (not per-app): emitted ONCE, unlabeled — an app label
    # would duplicate the same counter N times across a multi-app scrape
    # and N-fold overcount any PromQL sum()
    xc = next((r["xla_cache"] for r in reports.values()
               if r.get("xla_cache")), None)
    if xc:
        doc.add("siddhi_tpu_xla_cache_hits_total", "counter",
                "persistent XLA compilation cache hits (process-wide)",
                {}, xc.get("hits", 0))
        doc.add("siddhi_tpu_xla_cache_misses_total", "counter",
                "persistent XLA compilation cache misses (process-wide)",
                {}, xc.get("misses", 0))
    return doc.render()


# ---------------------------------------------------------------------------
# the statistics manager
# ---------------------------------------------------------------------------

class StatisticsManager:
    """Per-stream throughput + per-query and per-stage latency histograms
    (+ device metrics + flight recorder).
    `@app:statistics(reporter='console', interval='5 sec')` starts a
    periodic reporter thread (reference: @app:statistics reporter/interval,
    SiddhiAppParser.java:108-144)."""

    def __init__(self, rt):
        self.rt = rt
        self.enabled = False
        self.stream_in: dict = defaultdict(Tracker)
        self.query: dict = defaultdict(Tracker)
        self.stages: dict = defaultdict(Tracker)
        self.device: dict = defaultdict(lambda: defaultdict(float))
        # fault dispositions per stream/scope (ALWAYS counted — faults
        # are rare and must be visible even with statistics off)
        self.faults: dict = defaultdict(lambda: defaultdict(int))
        # on-demand (store) query latency — ALWAYS observed (not gated
        # on `enabled`): the queryable-state plane is its own surface
        # (REST + wire QUERY frames) and its p99 is an SLO input
        self.store_query = Tracker()
        self.tracer = PipelineTracer()
        self._t0 = time.perf_counter()
        self.reporter = None
        self.interval_s: float = 5.0
        self._rep_thread = None
        self._rep_stop = None

    # -- reporters -----------------------------------------------------------

    def configure(self, reporter: str, interval_s: float) -> None:
        fn = REPORTERS.get((reporter or "console").lower())
        if fn is None:
            raise ValueError(f"unknown statistics reporter {reporter!r}; "
                             f"have {sorted(REPORTERS)}")
        self.reporter = fn
        self.interval_s = interval_s

    def start_reporting(self) -> None:
        if self.reporter is None or self._rep_thread is not None:
            return
        self._rep_stop = threading.Event()

        def pump():
            while not self._rep_stop.wait(self.interval_s):
                try:
                    self.reporter(self.rt.app.name, self.report())
                except Exception:
                    pass
        self._rep_thread = threading.Thread(
            target=pump, name="siddhi-stats-report", daemon=True)
        self._rep_thread.start()

    def stop_reporting(self) -> None:
        if self._rep_stop is not None:
            self._rep_stop.set()
            self._rep_thread.join(timeout=2)
            self._rep_thread = None
            self._rep_stop = None
        # drop this app's cached prometheus series: a shut-down app must
        # not keep exporting frozen metrics through $SIDDHI_PROM_FILE /
        # PROM_LATEST renders triggered by other apps' reporter ticks
        app = getattr(getattr(self.rt, "app", None), "name", None)
        if app is not None:
            _PROM_REPORTS.pop(app, None)
            PROM_LATEST.pop(app, None)

    # -- recording hooks -----------------------------------------------------

    def time_stream(self, sid: str, n: int, trace_id=None):
        """Times one micro-batch's full pass through the dispatch loop
        (callbacks + every subscribed plan) and opens a batch-trace
        scope; a traced frame's id rides into the latency histogram as
        the bucket exemplar."""
        if not self.enabled:
            return _NOOP
        return _StreamTimer(self, sid, n, trace_id)

    def time_plan(self, name: str, n: int):
        """Context manager timing one plan.process batch."""
        return _PlanTimer(self, name, n)

    # pipeline stages that map onto a dispatch phase of the device-time
    # profiler (core/profiler.py): one timer records both planes
    _STAGE_PHASE = {"host_build": "host_pack_unpack",
                    "transfer": "d2h_materialize",
                    "scatter": "host_pack_unpack"}

    def stage(self, name: str, events: int = 0, plan: Optional[str] = None):
        """Context manager timing one pipeline-stage span.  Stages that
        map onto a profiler phase keep recording into the phase plane
        even with statistics disabled (the profiler is its own knob)."""
        prof = getattr(self.rt, "profiler", None)
        phase = self._STAGE_PHASE.get(name) if prof is not None else None
        if not self.enabled:
            return prof.phase(phase) if phase is not None else _NOOP
        return _StageTimer(self, name, events, plan,
                           pspan=None if phase is None
                           else prof.phase(phase))

    def note_stage(self, name: str, seconds: float, events: int = 0) -> None:
        """Record an already-measured span (parse time measured before
        the runtime — and its stats manager — existed)."""
        if not self.enabled:
            return
        self.stages[name].observe(seconds, events)

    def observe_store_query(self, seconds: float, rows: int,
                            trace=None) -> None:
        """One executed store query (runtime.query_with_schema) — rows
        count as the tracker's `events`; a traced caller (the net QUERY
        path under a TRACE-stamped connection) lands a histogram
        exemplar linking the latency bucket to its span tree."""
        tid = getattr(trace, "trace_id", None) if trace is not None else None
        self.store_query.observe(seconds, rows, trace_id=tid)

    def on_fault(self, scope: str, action: str) -> None:
        """One fault disposition (scope = stream or sink label, action =
        the @OnError / on.error disposition taken).  Not gated on
        `enabled`: a dropped batch must never be invisible."""
        self.faults[scope][action] += 1

    def on_kernel_cache(self, plan: str, hit: bool) -> None:
        if self.enabled:
            self.device[plan]["cache_hits" if hit else "cache_misses"] += 1

    def on_compile(self, plan: str, seconds: float) -> None:
        if self.enabled:
            d = self.device[plan]
            d["compiles"] += 1
            d["compile_seconds"] += seconds

    def add_transfer_bytes(self, plan: str, nbytes: int) -> None:
        if self.enabled:
            self.device[plan]["h2d_bytes"] += nbytes

    # -- reporting -----------------------------------------------------------

    def memory_bytes(self) -> int:
        """Approximate retained state size (reference:
        ObjectSizeCalculator.java:66 — we pickle-size the snapshot)."""
        import pickle
        try:
            return len(pickle.dumps(self.rt._snapshot_locked()))
        except Exception:
            return -1

    def device_report(self) -> dict:
        """Per-plan device metrics: the accumulated counters merged with
        each plan's sampled gauges (lane occupancy, frontier width,
        buffer fill) — sampled on demand, one D2H pull per stateful
        plan, so scrapes pay the cost, not the hot path."""
        # snapshot before iterating: the dispatch thread inserts new
        # tracker keys concurrently (first compile of a new shape, a
        # freshly added plan) and a live dict comprehension would raise
        # "dictionary changed size during iteration" on a /metrics scrape
        out = {name: {k: (int(v) if float(v).is_integer() else v)
                      for k, v in list(ctr.items())}
               for name, ctr in list(self.device.items())}
        for p in getattr(self.rt, "_plans", ()):
            dm = getattr(p, "device_metrics", None)
            if dm is not None:
                try:
                    m = dm()
                except Exception:
                    m = None
                if m:
                    out.setdefault(p.name, {}).update(m)
            # dispatch-pipeline gauges (pipeline.py): in-flight queue
            # depth, dispatch count, and the overlap_ratio behind the
            # async host/device decoupling story
            pipe = getattr(p, "_pipe", None)
            if pipe is not None:
                try:
                    out.setdefault(p.name, {}).update(pipe.metrics())
                except Exception:
                    pass
        # degradation-ladder gauges (consecutive dispatch failures,
        # halvings, quarantine flag) — keyed by the original plan name,
        # which survives the interpreter swap
        for name, lad in list(getattr(self.rt, "_ladders", {}).items()):
            out.setdefault(name, {}).update(lad.metrics())
        return out

    def report(self) -> dict:
        up = time.perf_counter() - self._t0
        rep = {
            "uptime_s": up,
            # list() snapshots: scrapes race the dispatch thread's inserts
            # (streams carry histogram buckets + trace-id exemplars for
            # the /metrics histogram render)
            "streams": {k: v.as_dict(buckets=True)
                        for k, v in list(self.stream_in.items())},
            "queries": {k: v.as_dict() for k, v in list(self.query.items())},
            "stages": {k: v.as_dict() for k, v in list(self.stages.items())},
        }
        dev = self.device_report()
        if dev:
            rep["device"] = dev
        if XLA_CACHE["hits"] or XLA_CACHE["misses"]:
            rep["xla_cache"] = dict(XLA_CACHE)
        # fault-tolerance surface (core/faults.py): dispositions taken,
        # quarantined plans, source drop counters, sink retry/breaker
        # gauges, ErrorStore fill — all additive keys, present only when
        # non-empty so fault-free reports keep their shape
        faults = {k: dict(v) for k, v in list(self.faults.items())}
        if faults:
            rep["faults"] = faults
        degraded = list(getattr(self.rt, "_degraded", ()))
        if degraded:
            rep["degraded_plans"] = [d["plan"] for d in degraded]
            rep["degraded_detail"] = degraded
        # placement accounting (core/placement.py): device vs interpreter
        # query counts + the Demotion tally.  ALWAYS present (not gated
        # on `enabled`): a silent demotion must never be invisible —
        # the bench summary and the siddhi_tpu_interp_demotions series
        # both read this block
        if getattr(self.rt, "placement", None) is not None:
            from .placement import summary as _placement_summary
            rep["placement"] = _placement_summary(self.rt)
        es = getattr(self.rt, "error_store", None)
        if es is not None and (len(es) or es.evicted):
            rep["error_store"] = {"entries": len(es), "evicted": es.evicted}
        sources: dict = {}
        for s in getattr(self.rt, "sources", ()):
            if s.dropped_events or s.stored_events:
                d = sources.setdefault(s.stream_id, {"dropped_events": 0,
                                                     "stored_events": 0})
                d["dropped_events"] += s.dropped_events
                d["stored_events"] += s.stored_events
        if sources:
            rep["sources"] = sources
        sinks: dict = {}
        for i, s in enumerate(getattr(self.rt, "sinks", ())):
            try:
                m = s.metrics()
            except Exception:
                continue
            if any(m.values()):
                sinks[f"{s.stream_id}[{i}]"] = m
        if sinks:
            rep["sinks"] = sinks
        # serving plane (siddhi_tpu/net): per-stream admission gauges
        # (frames/events/bytes in, sheds, pending, rate factor) merged
        # with transport-level counters from net sources (connections,
        # credit granted, ring occupancy)
        net: dict = {}
        for sid, ctrl in list(getattr(self.rt, "admission", {}).items()):
            try:
                net[sid] = ctrl.metrics()
            except Exception:
                continue
        for s in getattr(self.rt, "sources", ()):
            nm = getattr(s, "net_metrics", None)
            if nm is None:
                continue
            try:
                m = nm()
            except Exception:
                m = None
            if m:
                net.setdefault(s.stream_id, {}).update(m)
        if net:
            rep["net"] = net
        # queryable-state plane (core/aggregation.py): per-aggregation
        # bucket/group/eviction gauges + the store-query latency
        # histogram.  ALWAYS present when an aggregation exists or a
        # store query ran (not gated on `enabled`) — the agg series on
        # /metrics and the bench matrix both read this block
        agg: dict = {}
        for name, a in list(getattr(self.rt, "aggregations", {}).items()):
            try:
                m = a.metrics()
            except Exception:
                continue
            if m:
                agg[name] = m
        if agg or self.store_query.batches:
            ab: dict = {}
            if agg:
                ab["aggregations"] = agg
            if self.store_query.batches:
                ab["store_query"] = self.store_query.as_dict(buckets=True)
            rep["aggregation"] = ab
        # adaptive execution geometry (core/autotune.py): tuning-cache
        # hit/miss gauges + the SLO controller's state and decision log
        tn = getattr(self.rt, "tuner", None)
        if tn is not None and tn.enabled:
            rep["tuning"] = tn.metrics()
        slo = getattr(self.rt, "slo", None)
        if slo is not None:
            rep["slo"] = slo.metrics()
        # durability (core/wal.py): the runtime's shared report block —
        # ALWAYS present when @app:durability is declared (not gated on
        # `enabled`): a silently-disabled log must be as loud as a
        # silent demotion would be
        if getattr(self.rt, "durability", "off") != "off":
            rep["durability"] = self.rt.durability_report()
        # replication (core/replication.py): role, peer, lag, shipped/
        # applied volume, fencing rejections — present once the app has
        # a coordinator (annotated, or a standby subscribed)
        coord = getattr(self.rt, "replication", None)
        if coord is not None:
            rep["replication"] = coord.metrics()
        # frame tracing (core/tracing.py): sampling/ring/trigger gauges.
        # ALWAYS present when the tracer exists (not gated on `enabled`)
        # — a triggered dump must be discoverable from any scrape
        tr = getattr(self.rt, "tracing", None)
        if tr is not None:
            rep["tracing"] = tr.metrics()
        # device-time attribution (core/profiler.py): per-plan phase
        # shares + host-dispatch share.  ALWAYS present when the
        # profiler exists (not gated on `enabled`) — the phase plane is
        # its own knob (@app:profile) and feeds its own /metrics series
        prof = getattr(self.rt, "profiler", None)
        if prof is not None:
            rep["profile"] = prof.metrics()
        return rep

    def prometheus(self, openmetrics: bool = False) -> str:
        return render_prometheus({self.rt.app.name: self.report()},
                                 openmetrics=openmetrics)

    def export_chrome_trace(self, path: str) -> int:
        """Write the flight recorder as Chrome trace_event JSON; returns
        the event count."""
        return self.tracer.export_chrome_trace(path)

    def reset(self) -> None:
        self.stream_in.clear()
        self.query.clear()
        self.stages.clear()
        self.device.clear()
        self.tracer.reset()
        self._t0 = time.perf_counter()


# ---------------------------------------------------------------------------
# debugger (unchanged surface)
# ---------------------------------------------------------------------------

class SiddhiDebugger:
    """Micro-batch-boundary breakpoints (reference: SiddhiDebugger.java:36:
    acquireBreakPoint(query, IN|OUT) + SiddhiDebuggerCallback.debugEvent).

    The callback runs synchronously inside the dispatch loop; inspect live
    state via runtime.snapshot() / runtime.tables etc. from within it."""

    IN = "in"
    OUT = "out"

    def __init__(self, rt):
        self.rt = rt
        self._breakpoints: set = set()       # (query_name, point)
        self._callback: Optional[Callable] = None

    def acquire_breakpoint(self, query_name: str, point: str = IN) -> None:
        if query_name not in self.rt._known_query_names:
            raise KeyError(f"unknown query {query_name!r}")
        self._breakpoints.add((query_name, point))

    def release_breakpoint(self, query_name: str, point: str = IN) -> None:
        self._breakpoints.discard((query_name, point))

    def release_all(self) -> None:
        self._breakpoints.clear()

    def set_callback(self, fn: Callable) -> None:
        """fn(query_name, point, events) — events are decoded host Events."""
        self._callback = fn

    # -- engine hooks --------------------------------------------------------

    def check_in(self, plan, batch) -> None:
        name = getattr(plan, "callback_name", plan.name)
        if self._callback and (name, self.IN) in self._breakpoints:
            self._callback(name, self.IN, self.rt._decode(batch))

    def check_out(self, plan, out_batches: list) -> None:
        name = getattr(plan, "callback_name", plan.name)
        if self._callback and (name, self.OUT) in self._breakpoints:
            for ob in out_batches:
                if ob.batch.n:
                    self._callback(name, self.OUT, self.rt._decode(ob.batch))
