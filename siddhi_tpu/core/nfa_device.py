"""Batched device NFA — the TPU pattern/sequence matching kernel.

The north-star component (SURVEY §3.3): the reference walks per-event
pending-StateEvent lists through Pre/PostStateProcessor chains
(reference: core:query/input/stream/state/StreamPreStateProcessor.java:292,
StreamPostStateProcessor.java:53, LogicalPreStateProcessor.java:330-337,
CountPreStateProcessor.java:370-393, AbsentStreamPreStateProcessor.java:60-115).
Here the whole matcher is ONE fused array program:

  * the partition axis P (reference: core:partition/PartitionRuntime.java
    clones the query graph per key) becomes the minor (lane) axis —
    thousands of independent NFA instances evaluated in lockstep and
    shardable over a `jax.sharding.Mesh`;
  * pending partial matches become A fixed "slots" per partition laid out
    (A, P): `occ` (0 = free, p = stationed at position p-1, S+1 = parked
    completion) plus capture rows `ref.attr -> (A, P)`;
  * a micro-batch becomes a dense (T, P) block — one event per partition
    per `lax.scan` step, so in-partition order (the sequential semantics)
    is preserved while all partitions and slots advance in parallel;
  * `every` heads are an always-armed flag; `within` expiry, sequence
    strictness, logical fills, count collection, absent deadlines, and
    match emission are masked vector ops.

Pattern algebra on device (mirrors the host oracle interp/nfa.py):
  * count quantifiers `<m:n>` / `+`: a per-slot counter row per count
    position; collection is decoupled from the slot's station (`cnt_active`)
    so a partial match keeps absorbing occurrences while waiting further
    down the chain, exactly like the reference's pending count lists;
    indexed captures (e1[0], e1[i], e1[last], e1[last-1]) are capture rows;
    completions whose count is still collecting emit WITHOUT freeing the
    slot (more occurrences -> more matches).
  * logical `and`/`or`: a position holds a partner pair with a fill
    bitmask; `or` completions leave the other ref NULL (emitted present
    bits -> host-side null columns); an absent partner (`not X and e2=Y`)
    kills the slot when X arrives.
  * absent (`not X for T`): a deadline row per absent position; the
    forbidden stream's arrival kills the slot; deadline passage emits (at
    the deadline timestamp) or advances.  Deadlines fire on timer "tick"
    cells injected by the host scheduler (and, in playback mode, lazily
    against event timestamps, matching the host's pre-fire loop); the
    block reports the earliest pending deadline so the host scheduler
    knows when to tick.

TPU-economics of this kernel (what round-2 got wrong; measured on v5e):
  * NO f64/i64 inside the scan.  x64 arrays are emulated as f32/u32
    pairs, which (a) doubles every carry/output buffer and (b) made XLA
    choose mismatched layouts for the big scan-output accumulators,
    copying ~30 GB of HBM per block (~2 ms/step).  Timestamps and seqs
    travel as i32 offsets from per-plan bases, rebased host-side before
    they can overflow; DOUBLE computes in f32 by default
    (`@app:devicePrecision('f64')` opts out, documented slower).
  * capture storage holds ONLY the columns some predicate / selector /
    having actually reads (CompiledExpr.reads), grouped per-dtype into
    stacked (K, A, P) arrays so writes/emissions are one masked select
    per group instead of one per column.
  * predicates that read only the arriving event (no captures) are
    evaluated for the WHOLE block outside the scan as fused (T, P)
    vector ops; only capture-dependent conjuncts run per-step.
  * completing slots park their snapshot in slot storage (sentinel
    station) and drain through E narrow i32/f32 lanes per step (masked
    one-hot reductions — TPU scatters serialize); after the scan,
    ceil(A/E) drain rounds empty any backlog, then ONE cumsum + one
    scatter per lane-grid row compacts matches into a flat (M,) buffer
    (capacity doubled-and-retried on overflow — state is functional, so
    a retry is exact).

Still host-only (DeviceNFAUnsupported -> sequential fallback):
absent states in the head position, `every` wrapping logical/count/
absent states below the head, min-count 0 in the head position,
sequences containing absent states, and non-Variable selector outputs
over maybe-absent refs.  Everything else — `every` below the head
(slot forking), optional states (min-count 0 epsilon cascade),
adjacent/multiple count positions, sequences with logical states —
runs on device.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..query import ast
from .expr import (CompiledExpr, ExprError, MultiStreamContext, compute_dtypes,
                   F32_MODE, compile_expression, jnp_dtype)
from .schema import StreamSchema, StringTable

# local-offset budget: rebase when offsets approach this (i32 headroom)
LOCAL_SPAN = 1 << 30
NO_DEADLINE = np.int32(2**31 - 1)
NO_FIRST = np.int32(LOCAL_SPAN)   # first_ts sentinel: no capture yet


class DeviceNFAUnsupported(Exception):
    """Raised when a pattern shape needs the sequential fallback."""


class PatternFilterContext(MultiStreamContext):
    """Filter compile context for one chain state: unqualified attributes
    resolve to the state's own (arriving) event first — mirroring the
    reference, where a condition's unqualified variables read the current
    event (reference: core:util/parser/ExpressionParser variable binding
    for state elements)."""

    def __init__(self, schemas: dict, strings, own_ref: str):
        super().__init__(schemas, strings)
        self.own_ref = own_ref

    def resolve(self, var: ast.Variable):
        if var.stream_ref is None and var.index is None \
                and var.attribute in self.schemas[self.own_ref].types:
            return (f"{self.own_ref}.{var.attribute}",
                    self.schemas[self.own_ref].type_of(var.attribute))
        return super().resolve(var)


@dataclass
class PNode:
    """One condition inside a position (a reference Pre/PostStateProcessor)."""
    ref: str
    stream_id: str
    scode: int
    kind: str                       # "stream" | "absent"
    waiting_ms: Optional[int]       # absent `for T`
    pre_conjs: list = field(default_factory=list)   # event-only -> (T,P)
    step_conjs: list = field(default_factory=list)  # capture-referencing
    step_asts: list = field(default_factory=list)   # raw AST per step conj
    #   (parallel to step_conjs; nfa_parallel lowers monotone comparisons
    #   over earlier captures into segment-tree threshold hops)
    pre_key: Optional[str] = None   # xs key of the precomputed mask


@dataclass
class Position:
    """One chain position: a single state or a logical partner pair."""
    nodes: list                     # [PNode] (2 for logical)
    op: Optional[str] = None        # None | "and" | "or"
    min_count: int = 1
    max_count: int = 1
    within_ms: Optional[int] = None
    sticky: bool = False            # `every` head arm
    # state-row assignments (set by the kernel):
    cnt_row: Optional[int] = None   # counter row (count positions)
    log_row: Optional[int] = None   # fill-bit row (logical positions)
    dl_rows: Optional[dict] = None  # node idx -> deadline row (absent+for)

    @property
    def is_count(self) -> bool:
        return (self.min_count, self.max_count) != (1, 1)

    @property
    def refs(self) -> list:
        return [n.ref for n in self.nodes]


@dataclass
class ChainSpec:
    positions: list                  # [Position]
    stream_ids: list                 # distinct stream ids, scode order
    schemas: dict                    # ref -> StreamSchema
    is_sequence: bool
    every_head: bool

    @property
    def S(self) -> int:
        return len(self.positions)

    @property
    def all_nodes(self) -> list:
        return [n for p in self.positions for n in p.nodes]

    def maybe_absent_refs(self) -> set:
        """Refs that can be NULL in an emitted match (or-sides, absent
        nodes, and-pair sides advanced by a partner deadline, min-0
        counts that may emit with zero occurrences)."""
        out = set()
        for p in self.positions:
            if p.op is not None:
                out.update(p.refs)
            if p.is_count and p.min_count == 0:
                out.update(p.refs)
            for n in p.nodes:
                if n.kind == "absent":
                    out.add(n.ref)
        return out

    @property
    def needs_init_slot(self) -> bool:
        """Chains whose START state pre-registers a partial match before
        any event (host: PatternMatcher.start + _commit_epsilons): an
        absent head (`not A for T -> ...`) or a min-0 count head
        (`e1=A<0:2> -> ...`).  Each lane lazily arms one slot on its
        first activity."""
        head = self.positions[0]
        return (any(n.kind == "absent" for n in head.nodes)
                or (head.is_count and head.min_count == 0))


def _conjuncts(e: ast.Expression) -> list:
    if isinstance(e, ast.And):
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def lower_chain(state_input, schemas_by_stream: dict, strings: StringTable,
                filters_by_node: list,
                param_extra: Optional[dict] = None) -> ChainSpec:
    """Validate + lower a StateInputStream into a device position chain.

    Reuses the host NFACompiler lowering so device and host agree on
    structure; anything outside the supported algebra raises
    DeviceNFAUnsupported (-> sequential fallback).
    """
    from ..interp.nfa import NFACompiler
    from ..query.ast import StateType

    comp = NFACompiler()
    entries, _exits = comp.lower(state_input.state)
    nodes = comp.nodes
    is_sequence = state_input.type == StateType.SEQUENCE
    qw = state_input.within.millis if state_input.within else None

    # walk entry -> FINAL, grouping logical partners into one position
    if len(entries) == 1:
        head_ids = [entries[0].id]
    elif len(entries) == 2 and entries[0].partner_id == entries[1].id:
        head_ids = [entries[0].id, entries[1].id]
    else:
        raise DeviceNFAUnsupported("unsupported entry structure")

    stream_ids, scode_of = [], {}

    def scode(sid: str) -> int:
        if sid not in schemas_by_stream:
            raise DeviceNFAUnsupported(f"unknown stream {sid!r}")
        if sid not in scode_of:
            scode_of[sid] = len(stream_ids)
            stream_ids.append(sid)
        return scode_of[sid]

    def mk_pnode(n) -> PNode:
        return PNode(n.ref, n.stream_id, scode(n.stream_id), n.kind,
                     n.waiting_ms)

    positions: list = []
    seen: set = set()
    cur = head_ids
    while cur:
        n0 = nodes[cur[0]]
        group = [n0] + ([nodes[n0.partner_id]] if n0.partner_id is not None
                        else [])
        for g in group:
            if g.id in seen:
                raise DeviceNFAUnsupported("cyclic state graph")
            seen.add(g.id)
        pos = Position([mk_pnode(g) for g in group])
        if n0.partner_id is not None:
            pos.op = n0.partner_op
        pos.min_count, pos.max_count = n0.min_count, n0.max_count
        w = n0.within_ms if n0.within_ms is not None else qw
        if w is not None and w >= LOCAL_SPAN:
            raise DeviceNFAUnsupported("within > ~12 days (i32 ms offsets)")
        pos.within_ms = w
        pos.sticky = bool(n0.sticky)
        positions.append(pos)
        nxt = n0.next_id
        cur = [nxt] if nxt is not None else []
    if len(seen) != len(nodes):
        raise DeviceNFAUnsupported("non-linear state graph")

    # ---- support matrix ---------------------------------------------------
    # (absent-in-head, sequences with absents, min-0 heads, and
    # `every`-wrapped absents below the head all lower now — r5)
    S = len(positions)
    for i, pos in enumerate(positions):
        if pos.sticky and i != 0 and (pos.op is not None or pos.is_count):
            # `every` wrapping a logical pair or count BELOW the head needs
            # per-slot standing-arm forking at a shared station — host-only
            # (head every-logical/count re-arm via armed0; head every-absent
            # via the init-slot fork)
            raise DeviceNFAUnsupported(
                "`every`-wrapped logical/count state below the head")
        if pos.sticky and i == 0 and (
                (pos.op is not None
                 and any(n.kind == "absent" for n in pos.nodes))
                or (pos.is_count and pos.min_count == 0)):
            # every-wrapped absent-logical / optional-count heads would
            # need a forking standing INIT slot — host-only
            raise DeviceNFAUnsupported(
                "`every`-wrapped absent-logical or optional-count head")
        if pos.min_count == 0 and i > 0 and positions[i - 1].is_count \
                and positions[i - 1].min_count >= 1:
            # an optional-count run after a counting state keeps the
            # station at the counting state with a chained arm; the chain
            # must land on a plain (1,1) stream position
            k = i
            while k < S and positions[k].is_count \
                    and positions[k].min_count == 0:
                k += 1
            if (k >= S or positions[k].is_count
                    or positions[k].op is not None
                    or positions[k].nodes[0].kind == "absent"
                    or positions[k].sticky):
                raise DeviceNFAUnsupported(
                    "optional count run after a counting state landing on "
                    "a non-stream state")
        # (count on logical/absent states and min-0 non-count states are
        # structurally unbuildable from the AST: CountStateElement wraps a
        # StreamStateElement only — no check needed)

    schemas = {n.ref: schemas_by_stream[n.stream_id]
               for p in positions for n in p.nodes}
    spec = ChainSpec(positions, stream_ids, schemas, is_sequence,
                     positions[0].sticky)

    # ---- compile filters (filters_by_node follows NFACompiler node order) -
    flat_pnodes: dict = {}
    for p in positions:
        for n in p.nodes:
            flat_pnodes[n.ref] = n
    for host_n, elem_filters in zip(nodes, filters_by_node):
        pn = flat_pnodes.get(host_n.ref)
        if pn is None:
            continue
        conjs: list = []
        for f in elem_filters:
            conjs.extend(_conjuncts(f.expr))
        ctx = PatternFilterContext(spec.schemas, strings, pn.ref)
        if param_extra:
            ctx.extra = dict(param_extra)
        is_head = host_n.id in head_ids
        for c in conjs:
            try:
                ce = compile_expression(c, ctx)
            except ExprError as e:
                raise DeviceNFAUnsupported(f"filter not device-compilable: {e}")
            if ce.type != ast.AttrType.BOOL:
                raise DeviceNFAUnsupported("non-boolean filter")
            own = {f"{pn.ref}.{a.name}" for a in spec.schemas[pn.ref].attributes}
            own.add("__timestamp__")
            if param_extra:
                own.update(param_extra)
            if set(ce.reads) <= own:
                pn.pre_conjs.append(ce)
            else:
                if is_head:
                    raise DeviceNFAUnsupported(
                        "head filter references later captures")
                pn.step_conjs.append(ce)
                pn.step_asts.append(c)
    return spec


# ---------------------------------------------------------------------------
# kernel builder
# ---------------------------------------------------------------------------

_I32 = jnp.int32


def _base_ref(refpart: str):
    """'e1' -> ('e1', None); 'e1[0]' -> ('e1', 0); 'e1[last]' etc."""
    if "[" in refpart and refpart.endswith("]"):
        base, idx = refpart[:-1].split("[", 1)
        return base, idx
    return refpart, None


class NFAKernel:
    """Builds the jitted block function for one ChainSpec.

    state pytree (persistent across blocks; all (A, P) with P minor):
      occ      (A, P) i32      0 = free, p = stationed at position p-1,
                               S+1 = parked completion awaiting a drain lane
      first_ts (A, P) i32      head-capture ts offset (within anchor)
      head_seq (A, P) i32      head-capture seq offset (emission tie order)
      cnt      (Kc, A, P) i32  occurrence counters (count positions)
      cnt_on   (Kc, A, P) bool still-collecting flags
      narm     (Kc, A, P) bool successor armed (set when cnt hits min,
                               consumed by the successor's match — the
                               reference re-registers the next state only
                               at the exact min crossing)
      fl       (Kl, A, P) i32  logical fill bits (1 = left, 2 = right)
      dl       (Ka, A, P) i32  absent deadlines (NO_DEADLINE = disarmed)
      caps_f   (Kf, A, P) f32  float capture rows (self.rows_f)
      caps_i   (Ki, A, P) i32  int/string/bool/present capture rows +
                               parked completion ts/seq (self.rows_i)
      caps_l   (Kl', A, P) i64 LONG capture rows (hi/lo i32 lane pairs)
      armed0   (P,)  bool      entry arm (always True for `every`)
      of_slots (P,)  i32       head drops from slot exhaustion
      of_lanes (P,)  i32       direct-emit drops (count-survivor bursts
                               wider than E; host doubles E and retries)

    block(state, ev) -> (state', out): ev holds (T, P) i32/f32 grids plus
    0-d base scalars; out is ONE packed i32 matrix (+ f64 matrix only in
    f64 mode).  out row 0 = [n, of_slots, of_lanes, min_deadline, ...].
    """

    def __init__(self, spec: ChainSpec, sel_fns: dict, having: Optional[CompiledExpr],
                 P: int, A: int, E: Optional[int] = None, f64: bool = False,
                 playback: bool = False, params: Optional[dict] = None,
                 emit_qid: bool = False, init_on_tick: bool = False):
        self.spec = spec
        self.sel_fns = sel_fns          # out name -> CompiledExpr (ref.attr env)
        self.having = having
        self.P, self.A = P, A
        self.f64 = f64
        self.playback = playback
        # chains with a pre-registered START state (absent / min-0 count
        # head): each lane lazily arms one slot on first activity.
        # init_on_tick: unpartitioned plans also arm on a timer tick (the
        # host matcher starts at plan start, not first event); partitioned
        # lanes arm only on their first OWN event (host clones are created
        # lazily per key).
        self.needs_init = spec.needs_init_slot
        self.init_on_tick = init_on_tick
        # multi-query lanes: per-lane (P,) parameter vectors for lifted
        # constants, baked into the trace; emit_qid adds a lane-id row so
        # the host can route each match to its query's output stream
        self.params = params or {}
        self.emit_qid = emit_qid
        self._mode = None if f64 else F32_MODE
        self.E = E if E is not None else (1 if spec.S == 1 else min(A, 2))

        # ---- state-row assignment ----------------------------------------
        kc = kl = ka = 0
        for pos in spec.positions:
            if pos.is_count:
                pos.cnt_row = kc
                kc += 1
            if pos.op is not None:
                pos.log_row = kl
                kl += 1
            pos.dl_rows = {}
            for ni, n in enumerate(pos.nodes):
                if n.kind == "absent" and n.waiting_ms is not None:
                    pos.dl_rows[ni] = ka
                    ka += 1
        self.Kc, self.Kl, self.Ka = kc, kl, ka
        self.has_absent = any(n.kind == "absent" for n in spec.all_nodes)

        # ---- capture rows: only columns something downstream reads -------
        cap_keys: set = set()
        for pos in spec.positions:
            for n in pos.nodes:
                for ce in n.step_conjs:
                    for k in ce.reads:
                        if k == "__timestamp__":
                            continue
                        ref = k.split(".", 1)[0]
                        if ref != n.ref:
                            cap_keys.add(k)
        for ce in list(sel_fns.values()) + ([having] if having else []):
            for k in ce.reads:
                if k.startswith("__present__."):
                    cap_keys.add(k)
                elif "." in k and not k.startswith("__"):
                    cap_keys.add(k)
        # present bits for maybe-absent refs are always emitted (host null
        # reconstruction needs them even when the selector doesn't is-null)
        self._maybe_absent = spec.maybe_absent_refs()
        sel_refs = set()
        sel_rparts = set()
        for ce in sel_fns.values():
            for k in ce.reads:
                if "." in k and not k.startswith("__"):
                    sel_rparts.add(k.split(".", 1)[0])
                    sel_refs.add(_base_ref(k.split(".", 1)[0])[0])
        for r in self._maybe_absent & sel_refs:
            cap_keys.add(f"__present__.{r}")

        # indexed captures over count positions that may be UNFILLED at
        # emission (fewer than i+1 occurrences collected): the host emits
        # NULL for them (interp/nfa.py env_of_captures leaves the key out
        # of the env).  Selector reads get a per-index presence bit so the
        # host can null-reconstruct; predicate/having reads can't express
        # null semantics on device and fall back.
        minc_of = {p.nodes[0].ref: p.min_count
                   for p in spec.positions if p.is_count}
        self._maybe_unfilled = set()
        for k in list(cap_keys):
            if k.startswith("__present__."):
                continue
            refpart = k.split(".", 1)[0]
            base, cidx = _base_ref(refpart)
            if cidx is None or base not in minc_of:
                continue
            if cidx not in ("last", "last-1") and not cidx.isdigit():
                continue        # the _key_type loop below rejects it
            want = (1 if cidx == "last" else
                    2 if cidx == "last-1" else int(cidx) + 1)
            if want > minc_of[base]:
                self._maybe_unfilled.add(refpart)
        if self._maybe_unfilled:
            conjs = [c for n_ in spec.all_nodes for c in n_.step_conjs]
            if having is not None:
                conjs.append(having)
            for ce in conjs:
                for k in ce.reads:
                    if "." in k and k.split(".", 1)[0] in self._maybe_unfilled:
                        raise DeviceNFAUnsupported(
                            f"predicate reads maybe-unfilled indexed "
                            f"capture {k!r}")
        self._unfilled_sel = sorted(self._maybe_unfilled & sel_rparts)
        for rp in self._unfilled_sel:
            cap_keys.add(f"__present__.{rp}")

        self._key_type: dict = {}
        for k in sorted(cap_keys):
            if k.startswith("__present__."):
                self._key_type[k] = ast.AttrType.BOOL
                continue
            refpart, attr = k.split(".", 1)
            base, cidx = _base_ref(refpart)
            if base not in spec.schemas:
                raise DeviceNFAUnsupported(f"unresolvable capture key {k!r}")
            if cidx is not None and cidx not in ("last", "last-1") \
                    and not cidx.isdigit():
                raise DeviceNFAUnsupported(f"indexed capture {k!r}")
            self._key_type[k] = spec.schemas[base].type_of(attr)
        with compute_dtypes(self._mode):
            grp = {}
            for k, t in self._key_type.items():
                if k.startswith("__present__."):
                    grp[k] = "i"
                else:
                    grp[k] = self._group_of(jnp_dtype(t))
        self.rows_f = [k for k in sorted(cap_keys) if grp[k] == "f"]
        self.rows_l = [k for k in sorted(cap_keys) if grp[k] == "l"]
        self.rows_i = [k for k in sorted(cap_keys) if grp[k] == "i"]
        if spec.S > 1 or self.has_absent or spec.positions[0].op is not None \
                or spec.positions[0].is_count:
            self.rows_i += ["__comp_ts__", "__comp_seq__"]
        self._parked_emission = "__comp_ts__" in self.rows_i
        self._row_of = {k: ("f", i) for i, k in enumerate(self.rows_f)}
        self._row_of.update({k: ("i", i) for i, k in enumerate(self.rows_i)})
        self._row_of.update({k: ("l", i) for i, k in enumerate(self.rows_l)})

        # or-sides whose selected outputs must come back as NULL: selector
        # outputs that are plain variables over maybe-absent refs (anything
        # fancier can't be null-reconstructed host-side)
        self.null_outputs: dict = {}      # out name -> ref (or indexed refpart)
        for name, ce in sel_fns.items():
            reads = [k for k in ce.reads if "." in k and not k.startswith("__")]
            rparts = {k.split(".", 1)[0] for k in reads}
            # indexed reads (e2[last].p over a count) null-reconstruct via
            # the per-index presence machinery; bare reads via the ref's
            # presence bit — don't double-count one read as both
            hit = set()
            for rp in rparts:
                base, cidx = _base_ref(rp)
                if cidx is not None:
                    if rp in self._maybe_unfilled:
                        hit.add(rp)
                elif base in self._maybe_absent:
                    hit.add(base)
            if not hit:
                continue
            if ce.is_var and len(hit) == 1:
                self.null_outputs[name] = next(iter(hit))
            else:
                # a derived expression (e.g. `x is null`) must EVALUATE
                # the null, which the device can't represent — fall back
                raise DeviceNFAUnsupported(
                    f"selector output {name!r} derives from a maybe-absent "
                    f"ref (only bare variables null-reconstruct)")

        # ---- output rows (post-selector) ----------------------------------
        self.out_names = list(sel_fns) + ["__timestamp__", "__seq__",
                                          "__head_seq__"]
        if emit_qid:
            self.out_names.append("__qid__")
        for r in sorted(self._maybe_absent & sel_refs):
            self.out_names.append(f"__present__.{r}")
        for rp in self._unfilled_sel:
            self.out_names.append(f"__present__.{rp}")
        with compute_dtypes(self._mode):
            self.out_dtypes = {n: jnp_dtype(ce.type)
                               for n, ce in sel_fns.items()}
        self.out_dtypes["__timestamp__"] = _I32   # local offsets
        self.out_dtypes["__seq__"] = _I32
        self.out_dtypes["__head_seq__"] = _I32
        if emit_qid:
            self.out_dtypes["__qid__"] = _I32
        for r in self._maybe_absent & sel_refs:
            self.out_dtypes[f"__present__.{r}"] = _I32
        for rp in self._unfilled_sel:
            self.out_dtypes[f"__present__.{rp}"] = _I32
        self._block_cache: dict = {}    # (T, M) -> jitted fn

    @staticmethod
    def _group_of(dt) -> str:
        if dt in (jnp.float32, jnp.float64):
            return "f"
        if dt == jnp.int64:
            return "l"
        return "i"

    @property
    def fdt(self):
        return jnp.float64 if self.f64 else jnp.float32

    # -- state ---------------------------------------------------------------

    def init_state(self) -> dict:
        P, A = self.P, self.A
        st = {} if not self.needs_init else \
            {"init": jnp.zeros((P,), dtype=bool)}
        first0 = NO_FIRST if self.needs_init else 0
        st.update({
            "occ": jnp.zeros((A, P), dtype=_I32),
            "first_ts": jnp.full((A, P), int(first0), dtype=_I32),
            "head_seq": jnp.zeros((A, P), dtype=_I32),
            "cnt": jnp.zeros((self.Kc, A, P), dtype=_I32),
            "cnt_on": jnp.zeros((self.Kc, A, P), dtype=bool),
            "narm": jnp.zeros((self.Kc, A, P), dtype=bool),
            "fl": jnp.zeros((self.Kl, A, P), dtype=_I32),
            "dl": jnp.full((self.Ka, A, P), int(NO_DEADLINE), dtype=_I32),
            "caps_f": jnp.zeros((len(self.rows_f), A, P), dtype=self.fdt),
            "caps_i": jnp.zeros((len(self.rows_i), A, P), dtype=_I32),
            "caps_l": jnp.zeros((len(self.rows_l), A, P), dtype=jnp.int64),
            "armed0": jnp.ones((P,), dtype=bool),
            "of_slots": jnp.zeros((P,), dtype=_I32),
            "of_lanes": jnp.zeros((P,), dtype=_I32),
        })
        return st

    def occupancy(self, state) -> dict:
        """Sampled lane/slot occupancy + state-frontier width — the
        quantities that govern throughput on this kernel (state-set
        width / lane utilization; cf. Simultaneous Finite Automata,
        arxiv 1405.0562).  One D2H pull of `occ` (A, P) i32; call from
        a metrics scrape, not the hot path."""
        occ = np.asarray(state["occ"])
        S = self.spec.S
        live = (occ > 0) & (occ <= S)          # stationed partial matches
        per_lane = live.sum(axis=0)
        active = per_lane > 0
        d = {"slots_total": int(occ.size),
             "slots_live": int(per_lane.sum()),
             "slots_parked": int((occ == S + 1).sum()),
             "lanes_total": int(occ.shape[1]),
             "lanes_active": int(active.sum()),
             "frontier_width_max": int(per_lane.max()) if occ.size else 0}
        if d["lanes_active"]:
            d["frontier_width_mean"] = round(
                float(per_lane[active].mean()), 3)
        return d

    # -- env helpers -----------------------------------------------------

    def _caps_env(self, caps: dict) -> dict:
        """Capture rows as named (A, P) views (bool rows decoded)."""
        env = {}
        for k, (g, i) in self._row_of.items():
            col = caps[f"caps_{g}"][i]
            t = self._key_type.get(k)
            if t == ast.AttrType.BOOL:
                col = col != 0
            env[k] = col
        for k, v in self.params.items():
            env[k] = jnp.asarray(v)         # (P,) broadcasts vs (A, P)
        return env

    def _event_env(self, x: dict, n: PNode, base_ts) -> dict:
        """Arriving event's own columns as (P,) arrays (broadcast vs (A,P))."""
        env = {}
        sch = self.spec.schemas[n.ref]
        for a in sch.attributes:
            key = f"{n.scode}.{a.name}"
            if key in x:
                env[f"{n.ref}.{a.name}"] = x[key]
        env["__timestamp__"] = base_ts + x["__ts__"].astype(jnp.int64)
        return env

    def _node_match(self, x: dict, n: PNode, caps_env: dict, base_ts,
                    valid) -> jnp.ndarray:
        """(A, P) mask: does the arriving event satisfy node n's condition
        (stream + filters)?  Independent of slot station."""
        P = self.P
        m = valid
        if len(self.spec.stream_ids) > 1:
            m = m & (x["__scode__"] == n.scode)
        if n.pre_key is not None:
            m = m & x[n.pre_key]
        m = jnp.broadcast_to(m, (self.A, P)) if m.ndim == 1 else m
        for ce in n.step_conjs:
            env = dict(caps_env)
            env.update(self._event_env(x, n, base_ts))
            m = m & jnp.broadcast_to(ce.fn(env), (self.A, P))
        return m

    def _write_caps(self, caps: dict, mask, values: dict) -> dict:
        """Masked write of named values into capture rows; `mask` (A,P);
        values maps cap key -> (P,) / (A,P) array (missing keys skipped)."""
        caps = dict(caps)
        for g, rows in (("f", self.rows_f), ("i", self.rows_i),
                        ("l", self.rows_l)):
            idx, vals = [], []
            arr = caps[f"caps_{g}"]
            for i, k in enumerate(rows):
                if k in values:
                    idx.append(i)
                    v = values[k]
                    if getattr(v, "ndim", 0) < 2:
                        v = jnp.broadcast_to(v, (self.P,))[None, :]
                    vals.append(v.astype(arr.dtype))
            if not idx:
                continue
            if len(idx) == arr.shape[0]:
                new = jnp.stack([jnp.broadcast_to(v, (self.A, self.P))
                                 for v in vals], axis=0)
                caps[f"caps_{g}"] = jnp.where(mask[None], new, arr)
            else:
                for i, v in zip(idx, vals):
                    caps[f"caps_{g}"] = caps[f"caps_{g}"].at[i].set(
                        jnp.where(mask, v, caps[f"caps_{g}"][i]))
        return caps

    # -- the per-event step ----------------------------------------------

    def _step(self, carry: dict, x: dict):
        spec, P, A, E = self.spec, self.P, self.A, self.E
        S = spec.S
        PARK = S + 1
        occ0 = carry["occ"]           # pre-event stations (two-phase commit)
        occ = occ0
        first_ts, head_seq = carry["first_ts"], carry["head_seq"]
        cnt, cnt_on, fl, dl = (carry["cnt"], carry["cnt_on"], carry["fl"],
                               carry["dl"])
        narm = carry["narm"]
        caps = {k: carry[k] for k in ("caps_f", "caps_i", "caps_l")}
        armed0 = carry["armed0"]
        of_slots, of_lanes = carry["of_slots"], carry["of_lanes"]
        base_ts = x["__base_ts__"]

        ts, seq, valid = x["__ts__"], x["__seq__"], x["__valid__"]
        tick = x.get("__tick__")
        timey = valid if tick is None else (valid | tick)
        if self.playback:
            dl_fire = timey
        elif tick is not None:
            dl_fire = tick
        else:
            dl_fire = jnp.zeros((P,), dtype=bool)

        init_flag = carry.get("init")
        if self.needs_init:
            # lazy initial slot (host: PatternMatcher.start registers the
            # entry PM; partition clones start on their key's first event).
            # Slot 0 of a virgin lane is free by construction.
            trigger = (valid | tick) if (self.init_on_tick
                                         and tick is not None) else valid
            act = ~init_flag & trigger                      # (P,)
            init_flag = init_flag | act
            hot0 = (jnp.arange(A, dtype=_I32)[:, None] == 0) & act[None, :]
            # deadline base: unpartitioned plans ship the START anchor
            # (host matcher.start time); partitioned lanes use their
            # first event's timestamp (host clones start per key)
            anchor = x.get("__anchor__")
            arm_ts = ts if anchor is None \
                else jnp.broadcast_to(anchor, ts.shape)
            head = spec.positions[0]
            if head.nodes[0].kind == "absent" or head.op is not None:
                # absent head (or logical head containing an absent):
                # station at the head, arm its deadlines at activation time
                occ0 = jnp.where(hot0, 1, occ0)
                cnt, cnt_on, narm, fl, dl = self._enter_position(
                    0, hot0, cnt, cnt_on, narm, fl, dl, arm_ts)
            else:
                # min-0 count head: collection arms on the head (and any
                # following optional counts); the station lands on the
                # first non-optional position (host: _commit_epsilons)
                land, mids = self._landing_from(-1)
                occ0 = jnp.where(hot0, land + 1, occ0)
                for t in (*mids, land):
                    cnt, cnt_on, narm, fl, dl = self._enter_position(
                        t, hot0, cnt, cnt_on, narm, fl, dl, arm_ts)
            head_seq = jnp.where(hot0, seq[None, :], head_seq)
            occ = occ0

        caps_env = self._caps_env(caps)
        age = ts[None, :] - first_ts
        narm0 = narm      # successor arms as of step START: a min crossing
        #                   and its consumption may not share one event
        #                   (host stages registrations until post-event)
        transitioned = jnp.zeros((A, P), dtype=bool)
        complete = jnp.zeros((A, P), dtype=bool)
        kill = jnp.zeros((A, P), dtype=bool)
        enters: list = []             # (target position index, mask)
        cap_writes: list = []         # (mask, values dict)

        # node-match masks (station-independent; shared below)
        nm: dict = {}
        for pi, pos in enumerate(spec.positions):
            for ni, n in enumerate(pos.nodes):
                nm[(pi, ni)] = self._node_match(x, n, caps_env, base_ts, valid)

        # absent-deadline pre-pass: deadlines at or before this event's
        # timestamp fire BEFORE the event is processed (the host's playback
        # pre-fire loop / scheduler ordering), so the freed slot can consume
        # this very event at its next position.  `every`-wrapped absents
        # fork: the CLONE advances, the standing arm re-arms its deadline
        # one waiting period later (host: on_timer sticky branch).
        for pi, pos in enumerate(spec.positions):
            if pos.op is not None or not pos.dl_rows:
                continue
            n0 = pos.nodes[0]
            if n0.kind != "absent":
                continue
            r = pos.dl_rows[0]
            due = (occ0 == pi + 1) & (dl[r] <= ts[None, :]) & dl_fire[None, :]
            if pos.sticky:
                (occ0, first_ts, head_seq, cnt, cnt_on, narm, fl, dl,
                 caps, adv, lost) = self._fork_slots(
                    due, occ0, first_ts, head_seq, cnt, cnt_on, narm, fl,
                    dl, caps)
                of_slots = of_slots + lost
                # clones inherited the fired deadline value; read it
                # BEFORE re-arming the standing arms one period later
                dl_at = dl[r]
                rearm = jnp.int32(max(n0.waiting_ms or 1, 1))
                dl = dl.at[r].set(jnp.where(due, dl[r] + rearm, dl[r]))
            else:
                adv = due
                dl_at = dl[r]             # fired deadline (emission ts)
            # host: work.first_ts = dl when still unset (timer advance)
            first_ts = jnp.where(adv & (first_ts == NO_FIRST), dl_at,
                                 first_ts)
            if pi == S - 1:
                complete = complete | adv
                cap_writes.append((adv, {
                    "__comp_ts__": dl_at, "__comp_seq__": seq,
                    f"__present__.{n0.ref}": jnp.zeros((P,), _I32)}))
            else:
                land, mids = self._landing_from(pi)
                occ0 = jnp.where(adv, land + 1, occ0)
                for t in (*mids, land):
                    cnt, cnt_on, narm, fl, dl2 = self._enter_position(
                        t, adv, cnt, cnt_on, narm, fl, dl, dl_at)
                    dl = dl2
                zero_e = self._present_zero(
                    {n.ref for t in (*mids, land)
                     for n in spec.positions[t].nodes})
                zero_e[f"__present__.{n0.ref}"] = jnp.zeros((P,), _I32)
                caps = self._write_caps(caps, adv, zero_e)
            # disarm the fired row: the advancing slot (clone, for sticky)
            # left this position — a live slot carrying the stale value
            # would pin the reported min-deadline and wedge the scheduler
            clear = adv if pos.sticky else due
            dl = dl.at[r].set(jnp.where(clear, NO_DEADLINE, dl[r]))
        occ = occ0

        # within expiry per station (lazy, on event/tick time — reference
        # StreamPreStateProcessor.java:102-113)
        expired = jnp.zeros((A, P), dtype=bool)
        at_pos: list = []
        for pi, pos in enumerate(spec.positions):
            at = occ0 == pi + 1
            if pos.within_ms is not None:
                exp = at & timey[None, :] & (age > jnp.int32(pos.within_ms))
                expired = expired | exp
                at = at & ~exp
            at_pos.append(at)

        def advance(pi_from: int, mask):
            nonlocal occ, complete
            if pi_from == S - 1:
                complete = complete | mask
                return
            # epsilon cascade: mid-chain optional counts (min 0) arm
            # collection but the station lands on the first non-optional
            # position (host: _commit_epsilons registers successors at
            # entry; FINAL is never epsilon-reached, so an all-optional
            # suffix stations on the last count without emitting)
            t, mids = self._landing_from(pi_from)
            for mid in mids:
                enters.append((mid, mask))
            occ = jnp.where(mask, t + 1, occ)
            enters.append((t, mask))

        # --- count collection (station-independent: a partial match keeps
        #     absorbing occurrences while waiting further down the chain,
        #     reference CountPreStateProcessor pending lists) -------------
        for pi, pos in enumerate(spec.positions):
            if not pos.is_count:
                continue
            c = pos.cnt_row
            collect = cnt_on[c] & nm[(pi, 0)]
            newc = cnt[c] + collect.astype(_I32)
            vals = self._count_capture_values(x, pos.nodes[0], newc, caps)
            if pi == S - 1:
                vals["__comp_ts__"] = ts
                vals["__comp_seq__"] = seq
            cap_writes.append((collect, vals))
            cnt = cnt.at[c].set(newc)
            cnt_on = cnt_on.at[c].set(
                cnt_on[c] & (newc < jnp.int32(pos.max_count)))
            if pi < S - 1:
                cross = collect & (newc == jnp.int32(pos.min_count))
                narm = narm.at[c].set(narm[c] | cross)
                # epsilon cascade while the station STAYS here: optional
                # counts after this one arm their collection (staged to
                # post-event, like the host's deferred registrations)
                _land, mids_x = self._landing_from(pi)
                for midp in mids_x:
                    enters.append((midp, cross))
            transitioned = transitioned | collect
            if pi == S - 1:
                # count in the final position: every collection at or past
                # min emits (reference _emit_or_stage for count-final)
                complete = complete | (collect
                                       & (newc >= jnp.int32(pos.min_count)))

            # adjacent count positions: the previous count's armed
            # successor IS this count — entry consumes the arm and counts
            # the entering event as occurrence #1
            prevp = spec.positions[pi - 1] if pi else None
            if prevp is not None and prevp.is_count:
                ent = at_pos[pi - 1] & narm0[prevp.cnt_row] & nm[(pi, 0)]
                narm = narm.at[prevp.cnt_row].set(
                    narm[prevp.cnt_row] & ~ent)
                occ = jnp.where(ent, pi + 1, occ)
                transitioned = transitioned | ent
                one = jnp.where(ent, 1, cnt[c])
                cnt = cnt.at[c].set(one)
                cnt_on = cnt_on.at[c].set(
                    jnp.where(ent, pos.max_count > 1, cnt_on[c]))
                caps = self._write_caps(
                    caps, ent, self._present_zero({pos.nodes[0].ref}))
                evals = self._count_capture_values(
                    x, pos.nodes[0], jnp.where(ent, 1, 0), caps)
                if pi == S - 1:
                    evals["__comp_ts__"] = ts
                    evals["__comp_seq__"] = seq
                    complete = complete | (ent
                                           & (pos.min_count <= 1))
                else:
                    narm = narm.at[c].set(
                        narm[c] | (ent & (pos.min_count <= 1)))
                cap_writes.append((ent, evals))

        # --- per-position station logic -----------------------------------
        for pi, pos in enumerate(spec.positions):
            at = at_pos[pi]
            if pos.is_count:
                continue              # handled above
            if pi == 0 and pos.op is None \
                    and pos.nodes[0].kind != "absent":
                continue              # plain stream head: alloc below
                                      # (absent heads hold an init slot
                                      # that forbidden arrivals must kill)

            if pos.op is not None:
                fl, dl, k2, t2 = self._logical_step(
                    pi, pos, at, nm, x, ts, seq, dl, fl, caps,
                    cap_writes, advance, dl_fire)
                kill = kill | k2
                transitioned = transitioned | t2
                continue

            n0 = pos.nodes[0]
            if n0.kind == "absent":
                # forbidden arrival kills (deadline passage is handled by
                # the pre-pass above, reference
                # AbsentStreamPreStateProcessor.java:60-115); an `every`
                # arm re-arms its wait after the offender instead (host:
                # _absent_stream_arrived sticky branch)
                arr = at & nm[(pi, 0)]
                if pos.sticky:
                    r = pos.dl_rows.get(0)
                    if r is not None:
                        dl = dl.at[r].set(jnp.where(
                            arr, ts[None, :] + jnp.int32(n0.waiting_ms or 0),
                            dl[r]))
                else:
                    kill = kill | arr
                continue

            # (1,1) stream position: eligible when stationed here, or via
            # an armed predecessor count (set at its exact min crossing,
            # consumed here) — walking back across a run of OPTIONAL
            # counts, whose arms chain (host: _commit_epsilons keeps the
            # pm pending at every node of the run)
            elig = at
            chain = []               # armed predecessor count positions
            j = pi - 1
            while j >= 0 and spec.positions[j].is_count:
                chain.append(j)
                elig = elig | (at_pos[j]
                               & narm0[spec.positions[j].cnt_row])
                if spec.positions[j].min_count != 0:
                    break
                j -= 1
            m = elig & nm[(pi, 0)]
            for j in chain:
                cr = spec.positions[j].cnt_row
                narm = narm.at[cr].set(narm[cr] & ~m)
            transitioned = transitioned | m
            if pos.sticky:
                # `every` below the head: the slot is a standing arm — a
                # CLONE advances carrying this capture, the original stays
                # armed (host oracle: PM.sticky_at clone in _transition;
                # reference: EveryInnerStateRuntime re-registration)
                (occ, first_ts, head_seq, cnt, cnt_on, narm, fl, dl, caps,
                 m, lost) = self._fork_slots(
                    m, occ, first_ts, head_seq, cnt, cnt_on, narm, fl, dl,
                    caps)
                of_slots = of_slots + lost
                transitioned = transitioned | m
            vals = self._capture_values(x, n0)
            vals["__comp_ts__"] = ts
            vals["__comp_seq__"] = seq
            cap_writes.append((m, vals))
            advance(pi, m)

        dead = expired | kill
        occ = jnp.where(dead, 0, occ)
        if self.Kc:
            cnt_on = cnt_on & ~dead[None]
            narm = narm & ~dead[None]
        if self.Ka:
            dl = jnp.where(dead[None], NO_DEADLINE, dl)
        complete = complete & ~dead

        # --- apply capture writes (post-match) ----------------------------
        for mask, vals in cap_writes:
            caps = self._write_caps(caps, mask & ~dead, vals)

        # --- completion: park (slot freed at drain) or, for completions
        #     whose count is still collecting, direct-emit keeping the slot
        survivor = jnp.zeros((A, P), dtype=bool)
        if self.Kc and spec.positions[S - 1].is_count:
            survivor = cnt_on[spec.positions[S - 1].cnt_row]
        park = complete & ~survivor
        emit_now = complete & survivor
        occ = jnp.where(park, PARK, occ)
        if self.Kc:
            # a parked snapshot must freeze: station-independent collection
            # would otherwise overwrite captures before the drain lane emits
            # (the host's surviving count-pm keeps collecting, but it can
            # never re-emit, so freezing is unobservable)
            cnt_on = cnt_on & ~park[None]
            narm = narm & ~park[None]

        # --- entry writes on advance --------------------------------------
        for tpi, mask in enters:
            mask = mask & ~dead
            tpos = spec.positions[tpi]
            cnt, cnt_on, narm, fl, dl = self._enter_position(
                tpi, mask, cnt, cnt_on, narm, fl, dl, ts)
            # clear stale capture/present rows of the entered position's
            # refs (slots are reused; a previous life's captures must not
            # leak into this match's emission)
            caps = self._write_caps(
                caps, mask, self._present_zero({n.ref for n in tpos.nodes}))

        if self.needs_init:
            # first capture stamps the within-anchor (host: first_ts set on
            # first captures append; init slots start with NO_FIRST)
            stamp = transitioned & (first_ts == NO_FIRST)
            first_ts = jnp.where(stamp, ts[None, :], first_ts)

        # --- sequence strictness ------------------------------------------
        if spec.is_sequence:
            started = (occ > 0) & (occ < PARK) & (first_ts != NO_FIRST)
            kills = started & ~transitioned & valid[None, :]
            occ = jnp.where(kills, 0, occ)
            if self.Kc:
                cnt_on = cnt_on & ~kills[None]
                narm = narm & ~kills[None]

        # --- emission lanes ------------------------------------------------
        if self._parked_emission:
            occ, y, lost = self._drain_done(occ, head_seq, caps, emit_now)
            of_lanes = of_lanes + lost.sum(axis=0, dtype=_I32)

        # --- head: slot alloc (or direct single-position emission) --------
        head = spec.positions[0]
        if self.needs_init:
            ok0 = jnp.zeros((P,), dtype=bool)   # entry = the init slot
        else:
            ok0 = armed0 & self._head_match(x, head, valid)
        if not spec.every_head:
            armed0 = armed0 & ~ok0
        if not self._parked_emission:
            y = self._emit_single(x, head.nodes[0], ts, seq, ok0)
        else:
            free = occ == 0
            has_free = free.any(axis=0)
            do = ok0 & has_free
            of_slots = of_slots + (ok0 & ~has_free).astype(_I32)
            hot = free & (jnp.cumsum(free.astype(_I32), axis=0,
                                     dtype=_I32) == 1) & do[None, :]
            first_ts = jnp.where(hot, ts[None, :], first_ts)
            head_seq = jnp.where(hot, seq[None, :], head_seq)
            occ, cnt, cnt_on, narm, fl, dl, caps = self._alloc_head(
                x, head, hot, occ, cnt, cnt_on, narm, fl, dl, caps, ts, seq,
                PARK)

        carry = {"occ": occ, "first_ts": first_ts, "head_seq": head_seq,
                 "cnt": cnt, "cnt_on": cnt_on, "narm": narm, "fl": fl,
                 "dl": dl,
                 "caps_f": caps["caps_f"], "caps_i": caps["caps_i"],
                 "caps_l": caps["caps_l"], "armed0": armed0,
                 "of_slots": of_slots, "of_lanes": of_lanes}
        if init_flag is not None:
            carry["init"] = init_flag
        return carry, y

    # -- helpers for pieces of the step ----------------------------------

    def _fork_slots(self, src, occ, first_ts, head_seq, cnt, cnt_on, narm,
                    fl, dl, caps):
        """Clone every `src` slot into a free slot (rank-matched); returns
        updated state + the clone mask (the clones are the ones that then
        advance).  Clones that find no free slot count into the overflow
        counter — the host grows A and retries the block exactly."""
        A = self.A
        srci = src.astype(_I32)
        nfork = jnp.cumsum(srci, axis=0)
        src_rank = nfork - srci
        total = nfork[-1]                               # (P,)
        free = occ == 0
        freei = free.astype(_I32)
        dst_rank = jnp.cumsum(freei, axis=0) - freei
        dst = free & (dst_rank < total[None, :])
        lost = jnp.maximum(total - jnp.sum(freei, axis=0), 0).astype(_I32)
        key = jnp.where(src, src_rank, A + 1)
        by_rank = jnp.argsort(key, axis=0)              # (A, P)
        src_of = jnp.take_along_axis(by_rank,
                                     jnp.minimum(dst_rank, A - 1), axis=0)

        def cp(row):
            g = jnp.take_along_axis(row, src_of, axis=0)
            return jnp.where(dst, g, row)

        def cp3(t):
            if t.shape[0] == 0:
                return t
            g = jnp.take_along_axis(
                t, jnp.broadcast_to(src_of[None], t.shape), axis=1)
            return jnp.where(dst[None], g, t)
        occ = cp(occ)
        first_ts = cp(first_ts)
        head_seq = cp(head_seq)
        cnt, cnt_on, narm, fl, dl = (cp3(cnt), cp3(cnt_on), cp3(narm),
                                     cp3(fl), cp3(dl))
        caps = {k: cp3(v) for k, v in caps.items()}
        return (occ, first_ts, head_seq, cnt, cnt_on, narm, fl, dl, caps,
                dst, lost)

    def _landing_from(self, pi_from: int):
        """Station landing after pi_from, skipping mid-chain optional
        counts (min 0): returns (landing_pi, [skipped positions])."""
        t = pi_from + 1
        mids = []
        S = self.spec.S
        while (t < S - 1 and self.spec.positions[t].is_count
               and self.spec.positions[t].min_count == 0):
            mids.append(t)
            t += 1
        return t, mids

    def _present_zero(self, refs: Optional[set] = None) -> dict:
        """Zero-writes for presence rows (base + per-index) — applied when
        a slot is reused or advances into a position, so a previous life's
        captures can't leak.  refs=None clears every presence row."""
        out = {}
        for k in self.rows_i:
            if not k.startswith("__present__."):
                continue
            if refs is None or _base_ref(k[len("__present__."):])[0] in refs:
                out[k] = jnp.zeros((self.P,), _I32)
        return out

    def _enter_position(self, tpi, mask, cnt, cnt_on, narm, fl, dl, ts):
        """State-row resets/arms when slots advance into position tpi."""
        tpos = self.spec.positions[tpi]
        if tpos.is_count:
            cnt = cnt.at[tpos.cnt_row].set(jnp.where(mask, 0, cnt[tpos.cnt_row]))
            cnt_on = cnt_on.at[tpos.cnt_row].set(
                jnp.where(mask, True, cnt_on[tpos.cnt_row]))
            # min-0 counts arm their successor from entry (epsilon)
            eps = tpos.min_count == 0 and tpi < len(self.spec.positions) - 1
            narm = narm.at[tpos.cnt_row].set(
                jnp.where(mask, eps, narm[tpos.cnt_row]))
        if tpos.log_row is not None:
            fl = fl.at[tpos.log_row].set(jnp.where(mask, 0, fl[tpos.log_row]))
        for ni, r in (tpos.dl_rows or {}).items():
            w = tpos.nodes[ni].waiting_ms
            base = ts[None, :] if getattr(ts, "ndim", 1) == 1 else ts
            dl = dl.at[r].set(jnp.where(mask, base + jnp.int32(w), dl[r]))
        return cnt, cnt_on, narm, fl, dl

    def _capture_values(self, x, n: PNode) -> dict:
        """Values written when node n's event is captured into a slot."""
        vals: dict = {}
        for a in self.spec.schemas[n.ref].attributes:
            key = f"{n.scode}.{a.name}"
            if key not in x:
                continue
            vals[f"{n.ref}.{a.name}"] = x[key]
            vals[f"{n.ref}[last].{a.name}"] = x[key]
        vals[f"__present__.{n.ref}"] = jnp.ones((self.P,), _I32)
        return vals

    def _count_capture_values(self, x, n, newc, caps) -> dict:
        """Capture writes for a count collection: plain/[last]/[last-1]/[i]."""
        vals: dict = {}
        for a in self.spec.schemas[n.ref].attributes:
            key = f"{n.scode}.{a.name}"
            if key not in x:
                continue
            v = x[key]
            lk = f"{n.ref}[last].{a.name}"
            pk = f"{n.ref}[last-1].{a.name}"
            if pk in self._row_of and lk in self._row_of:
                g, i = self._row_of[lk]
                vals[pk] = caps[f"caps_{g}"][i]
            vals[f"{n.ref}.{a.name}"] = v
            vals[lk] = v
        vals[f"__present__.{n.ref}"] = jnp.ones((self.P,), _I32)
        # indexed rows e1[i].attr: written when this collection is the i-th
        for k in self._row_of:
            if k.startswith("__"):
                continue
            refpart, attr = k.split(".", 1)
            base, cidx = _base_ref(refpart)
            if base != n.ref or cidx is None or not cidx.isdigit():
                continue
            want = int(cidx) + 1
            keyx = f"{n.scode}.{attr}"
            if keyx in x:
                g, i = self._row_of[k]
                cur = caps[f"caps_{g}"][i]
                vals[k] = jnp.where(newc == jnp.int32(want),
                                    jnp.broadcast_to(x[keyx], cur.shape
                                                     ).astype(cur.dtype), cur)
        # per-index presence bits (host nulls unfilled indexed captures)
        for rp in self._unfilled_sel:
            pkey = f"__present__.{rp}"
            if pkey not in self._row_of:
                continue
            base, cidx = _base_ref(rp)
            if base != n.ref:
                continue
            want = (1 if cidx == "last"
                    else 2 if cidx == "last-1" else int(cidx) + 1)
            g, i = self._row_of[pkey]
            cur = caps[f"caps_{g}"][i]
            vals[pkey] = jnp.where(newc >= jnp.int32(want), jnp.int32(1), cur)
        return vals

    def _logical_step(self, pi, pos, at, nm, x, ts, seq, dl, fl, caps,
                      cap_writes, advance, dl_fire):
        """and/or partner pair at position pi (station mask `at`).
        Returns (fl', dl', kill, transitioned)."""
        A, P = self.A, self.P
        r = pos.log_row
        kill = jnp.zeros((A, P), dtype=bool)
        trans = jnp.zeros((A, P), dtype=bool)
        newbits = fl[r]
        side_due = jnp.zeros((A, P), dtype=bool)
        for ni, n in enumerate(pos.nodes):
            m = at & nm[(pi, ni)]
            if n.kind == "absent":
                dr = pos.dl_rows.get(ni)
                if pos.op == "or":
                    # arrival disarms this side (can no longer complete it)
                    if dr is not None:
                        dl = dl.at[dr].set(jnp.where(m, NO_DEADLINE, dl[dr]))
                else:
                    kill = kill | m
                if dr is not None:
                    due = at & (dl[dr] <= ts[None, :]) & dl_fire[None, :]
                    side_due = side_due | due
                    dl = dl.at[dr].set(jnp.where(due, NO_DEADLINE, dl[dr]))
                continue
            newbits = jnp.where(m, newbits | (1 << ni), newbits)
            trans = trans | m
            vals = self._capture_values(x, n)
            vals["__comp_ts__"] = ts
            vals["__comp_seq__"] = seq
            cap_writes.append((m & ~kill, vals))
        if pos.op == "or":
            done = at & ((newbits != 0) | side_due) & ~kill
        else:
            need = 0
            for ni, n in enumerate(pos.nodes):
                if n.kind != "absent":
                    need |= (1 << ni)
            # an absent partner is satisfied by not-having-arrived; a
            # deadline passage also advances the pair (host semantics)
            done = at & (((newbits & need) == need) | side_due) & ~kill
        advance(pi, done)
        trans = trans | done
        for dr in (pos.dl_rows or {}).values():
            dl = dl.at[dr].set(jnp.where(done | kill, NO_DEADLINE, dl[dr]))
        fl = fl.at[r].set(jnp.where(done, 0, newbits))
        return fl, dl, kill, trans

    def _head_match(self, x, head: Position, valid):
        """(P,) mask: does this event arm a new partial match?  Head
        filters are always pre-evaluated (lower_chain enforces it)."""
        P = self.P
        ok = jnp.zeros((P,), dtype=bool)
        for n in head.nodes:
            if n.kind == "absent":
                continue
            m = valid
            if len(self.spec.stream_ids) > 1:
                m = m & (x["__scode__"] == n.scode)
            if n.pre_key is not None:
                m = m & x[n.pre_key]
            ok = ok | m
        cs = x.get("__can_start__")
        if cs is not None:
            # chunked-halo mode: halo events extend pending matches but
            # never arm new heads (the lane that OWNS the event arms it)
            ok = ok & cs
        return ok

    def _alloc_head(self, x, head: Position, hot, occ, cnt, cnt_on, narm,
                    fl, dl, caps, ts, seq, PARK):
        """Entry writes for a freshly allocated slot (mask `hot`)."""
        # clear stale capture/present/deadline rows from the slot's
        # previous life (a stale armed deadline on a live slot would wedge
        # the timer scheduler in a fire-nothing loop)
        caps = self._write_caps(caps, hot, self._present_zero())
        if self.Ka:
            dl = jnp.where(hot[None], NO_DEADLINE, dl)

        if head.op is not None:
            r = head.log_row
            bits = jnp.zeros((self.A, self.P), dtype=_I32)
            for ni, n in enumerate(head.nodes):
                if n.kind == "absent":
                    continue
                m0 = x["__valid__"]
                if len(self.spec.stream_ids) > 1:
                    m0 = m0 & (x["__scode__"] == n.scode)
                if n.pre_key is not None:
                    m0 = m0 & x[n.pre_key]
                mm = hot & m0[None, :]
                bits = jnp.where(mm, bits | (1 << ni), bits)
                vals = self._capture_values(x, n)
                vals["__comp_ts__"] = ts
                vals["__comp_seq__"] = seq
                caps = self._write_caps(caps, mm, vals)
            occ = jnp.where(hot, 1, occ)
            fl = fl.at[r].set(jnp.where(hot, bits, fl[r]))
            if head.op == "or":
                # one side suffices: complete (S==1) or advance immediately
                done = hot & (bits != 0)
                land, mids = self._landing_from(0)
                occ = jnp.where(done,
                                PARK if self.spec.S == 1 else land + 1, occ)
                if self.spec.S > 1:
                    for t in (*mids, land):
                        cnt, cnt_on, narm, fl, dl = self._enter_position(
                            t, done, cnt, cnt_on, narm, fl, dl, ts)
        elif head.is_count:
            c = head.cnt_row
            occ = jnp.where(hot, 1, occ)
            one = jnp.where(hot, 1, cnt[c])
            cnt = cnt.at[c].set(one)
            cnt_on = cnt_on.at[c].set(
                jnp.where(hot, head.max_count > 1, cnt_on[c]))
            if self.spec.S > 1:
                narm = narm.at[c].set(
                    jnp.where(hot, head.min_count <= 1, narm[c]))
            vals = self._count_capture_values(x, head.nodes[0], one, caps)
            if self.spec.S == 1:
                vals["__comp_ts__"] = ts
                vals["__comp_seq__"] = seq
            caps = self._write_caps(caps, hot, vals)
            if self.spec.S == 1 and head.min_count <= 1:
                occ = jnp.where(hot, PARK, occ)   # immediate first emission
        else:
            land, mids = self._landing_from(0)
            occ = jnp.where(hot, land + 1, occ)
            vals = self._capture_values(x, head.nodes[0])
            caps = self._write_caps(caps, hot, vals)
            if self.spec.S > 1:
                for t in (*mids, land):
                    cnt, cnt_on, narm, fl, dl = self._enter_position(
                        t, hot, cnt, cnt_on, narm, fl, dl, ts)
        return occ, cnt, cnt_on, narm, fl, dl, caps

    def _emit_single(self, x, n: PNode, ts, seq, ok0):
        """Single-(1,1)-stream-position chain: direct lane emission."""
        P = self.P
        ev_env = {}
        for a in self.spec.schemas[n.ref].attributes:
            key = f"{n.scode}.{a.name}"
            if key in x:
                ev_env[f"{n.ref}.{a.name}"] = x[key]
                ev_env[f"{n.ref}[last].{a.name}"] = x[key]
        ev_env[f"__present__.{n.ref}"] = jnp.ones((P,), _I32)
        irows = [ok0.astype(_I32)[None, :]]
        frows = []
        for k in self.rows_f:
            v = ev_env.get(k, jnp.zeros((P,), self.fdt))
            frows.append(jnp.broadcast_to(v, (P,)).astype(self.fdt)[None, :])
        for k in self.rows_i:
            v = ev_env.get(k, jnp.zeros((P,), _I32))
            irows.append(jnp.broadcast_to(v, (P,)).astype(_I32)[None, :])
        irows.append(seq[None, :])      # __head_seq__
        if self.emit_qid:
            irows.append(jnp.arange(P, dtype=_I32)[None, :])
        for k in self.rows_l:
            v = jnp.broadcast_to(ev_env.get(k, jnp.zeros((P,), jnp.int64)),
                                 (P,)).astype(jnp.int64)
            irows.append(_hi32(v)[None, :])
            irows.append(_lo32(v)[None, :])
        irows.append(ts[None, :])       # __comp_ts__ (tail rows)
        irows.append(seq[None, :])      # __comp_seq__
        y = {"i": jnp.stack(irows, axis=0)}           # (Ci, 1=E, P)
        if frows:
            y["f"] = jnp.stack(frows, axis=0)
        return y

    def _drain_done(self, occ, head_seq, caps, emit_now=None):
        """Emit up to E parked completions (freed) + direct emissions
        (count survivors, not freed) per partition from slot storage.
        Returns (occ', y, lost): lost marks direct emissions that found
        no lane (host doubles E and retries the block)."""
        spec, P, A, E = self.spec, self.P, self.A, self.E
        PARK = spec.S + 1
        parked = occ == PARK
        done = parked if emit_now is None else (parked | emit_now)
        rank = jnp.cumsum(done.astype(_I32), axis=0, dtype=_I32) - done
        sels = [done & (rank == e) for e in range(E)]       # one-hot over A
        lv = jnp.stack([s.any(axis=0) for s in sels], axis=0)   # (E, P)
        igrid = [caps["caps_i"], head_seq[None]]
        if self.emit_qid:
            igrid.append(jnp.broadcast_to(
                jnp.arange(P, dtype=_I32)[None, :], (A, P))[None])
        if self.rows_l:
            cl = caps["caps_l"]
            igrid.append(_hi32(cl))
            igrid.append(_lo32(cl))
        igrid = jnp.concatenate(igrid, axis=0)              # (Ki', A, P)
        ilanes = jnp.stack(
            [jnp.where(s[None], igrid, 0).sum(axis=1, dtype=_I32)
             for s in sels], axis=1)                        # (Ki', E, P)
        y = {"i": jnp.concatenate([lv.astype(_I32)[None], ilanes], axis=0)}
        if self.rows_f:
            fgrid = caps["caps_f"]
            y["f"] = jnp.stack(
                [jnp.where(s[None], fgrid, 0).sum(axis=1, dtype=fgrid.dtype)
                 for s in sels], axis=1)                    # (Kf, E, P)
        emitted = done & (rank < E)
        freed = parked & emitted
        lost = (jnp.zeros((A, P), bool) if emit_now is None
                else (emit_now & ~parked & ~emitted))
        return jnp.where(freed, 0, occ), y, lost

    # lane-grid row order for y["i"] (after the lv row)
    def _ilane_names(self) -> list:
        names = list(self.rows_i) + ["__head_seq__"]
        if self.emit_qid:
            names.append("__qid__")
        for k in self.rows_l:
            names += [f"{k}.hi", f"{k}.lo"]
        if not self._parked_emission:
            names += ["__comp_ts__", "__comp_seq__"]
        return names

    # -- block ---------------------------------------------------------------

    def raw_block_fn(self, M: int) -> Callable:
        """Unjitted block(state, ev) — the framework's 'forward step' for
        compile checks and mesh-sharded execution."""
        return self._make_block(M)

    def block_fn(self, T: int, M: int) -> Callable:
        key = (T, M)
        fn = self._block_cache.get(key)
        if fn is None:
            fn = self._block_cache[key] = jax.jit(self._make_block(M, T))
        return fn

    def _pre_masks(self, ev: dict) -> dict:
        """Evaluate event-only filter conjuncts over the whole (T, P) block
        in one fused pass (outside the scan)."""
        out = {}
        for gi, n in enumerate(self.spec.all_nodes):
            if not n.pre_conjs:
                n.pre_key = None
                continue
            env = {}
            for a in self.spec.schemas[n.ref].attributes:
                key = f"{n.scode}.{a.name}"
                if key in ev:
                    env[f"{n.ref}.{a.name}"] = ev[key]
            env["__timestamp__"] = ev["__base_ts__"] \
                + ev["__ts__"].astype(jnp.int64)
            for k, v in self.params.items():
                env[k] = jnp.asarray(v)     # (P,) broadcasts vs (T, P)
            m = None
            for ce in n.pre_conjs:
                p = ce.fn(env)
                m = p if m is None else (m & p)
            n.pre_key = f"__pre{gi}__"
            # per-lane params make pre-masks (T, P) even when event grids
            # are broadcast (T, 1)
            out[n.pre_key] = jnp.broadcast_to(
                m, (ev["__ts__"].shape[0], self.P))
        return out

    def _make_block(self, M: int, T: Optional[int] = None) -> Callable:
        def block(state, ev):
            with compute_dtypes(self._mode):
                return self._block_impl(state, ev, M, T)
        return block

    def _chunk_dedup_row(self) -> int:
        """Row index (within the packed lane grid, after the lv row) of
        __comp_seq__ — used to suppress replayed-tail completions on
        device so they never cross the tunnel."""
        return 1 + self._ilane_names().index("__comp_seq__")

    def _expand_flat(self, ev: dict, T: int) -> dict:
        """Chunked-halo mode: the host ships events once as flat (F,)
        arrays; lane grids are gathered ON DEVICE (lane l reads events
        [l*CS, l*CS + T)), so the tunnel never carries the halo-duplicated
        (T, P) grids.  `__can_start__` marks each lane's OWN range (the
        first CS steps); trailing reads past the event count are invalid
        cells.  Events past a lane's halo are harmless: `within` expires
        every owned instance before they could matter (pattern_plan sizes
        T to cover the worst-case halo)."""
        P = self.P
        cs = ev["__cs__"].astype(_I32)          # own-chunk length
        nev = ev["__nev__"].astype(_I32)        # flat event count
        lane = jnp.arange(P, dtype=_I32)[None, :]
        t = jnp.arange(T, dtype=_I32)[:, None]
        idx = lane * cs + t                     # (T, P) global positions
        F = ev["__flat.__ts__"].shape[0]
        safe = jnp.clip(idx, 0, F - 1)
        out = {}
        for k, v in ev.items():
            if k.startswith("__flat."):
                out[k[len("__flat."):]] = v[safe]
        if "__seq__" not in out:
            # single-stream flushes have consecutive seqs: derive instead
            # of shipping another (F,) array through the tunnel
            out["__seq__"] = ev["__seq0__"].astype(_I32) + idx
        out["__valid__"] = idx < nev
        out["__can_start__"] = jnp.broadcast_to(t < cs, (T, P))
        out["__base_ts__"] = ev["__base_ts__"]
        out["__base_seq__"] = ev["__base_seq__"]
        return out

    def _block_impl(self, state, ev, M: int, T_static: Optional[int] = None):
        spec = self.spec
        ev = dict(ev)
        prev_seq = ev.pop("__prev_seq__", None)
        if "__cs__" in ev:
            ev = self._expand_flat(ev, T_static)
        ev.update(self._pre_masks(ev))
        base_ts = ev["__base_ts__"]
        anchor = ev.get("__anchor__")
        xs = {k: v for k, v in ev.items()
              if k not in ("__base_ts__", "__base_seq__", "__anchor__")}
        T = xs["__ts__"].shape[0]

        def step(carry, x):
            x = dict(x)
            x["__base_ts__"] = base_ts
            if anchor is not None:
                x["__anchor__"] = anchor
            return self._step(carry, x)

        carry, ys = lax.scan(step, dict(state), xs)
        if self._parked_emission:
            def drain_step(c, _):
                occ2, y2, _lost = self._drain_done(
                    c["occ"], c["head_seq"],
                    {k: c[k] for k in ("caps_f", "caps_i", "caps_l")})
                c2 = dict(c)
                c2["occ"] = occ2
                return c2, y2
            rounds = -(-self.A // self.E)
            carry, ys2 = lax.scan(drain_step, carry, None, length=rounds)
            ys = jax.tree_util.tree_map(
                lambda a, b: jnp.concatenate([a, b], axis=0), ys, ys2)

        # compact the (T', C, E, P) lane grids into flat (M,) buffers: one
        # i32 cumsum for positions + ONE scatter per row (searchsorted+
        # gather lowers to an O(M)-serialized loop on TPU: 460 ms at M=131k
        # vs 0.1 ms for the scatter form)
        ys_i = ys["i"]                        # (T', Ci, E, P) i32
        ys_f = ys.get("f")                    # (T', Cf, E, P) f32
        lv = ys_i[:, 0].reshape(-1) != 0      # (T'*E*P,)
        if prev_seq is not None:
            # chunked-halo replay: completions at or before the previous
            # flush's last seq already emitted — drop them BEFORE the
            # compaction so they never occupy the M buffer or the tunnel
            lv = lv & (ys_i[:, self._chunk_dedup_row()].reshape(-1)
                       > prev_seq.astype(_I32))
        pos = jnp.cumsum(lv.astype(_I32), dtype=_I32) - lv
        n = pos[-1] + lv[-1]
        wpos = jnp.where(lv & (pos < M), pos, M)
        cols = {}
        for r, name in enumerate(self._ilane_names()):
            cols[name] = jnp.zeros((M,), _I32).at[wpos].set(
                ys_i[:, r + 1].reshape(-1), mode="drop")
        if ys_f is not None:
            for r, name in enumerate(self.rows_f):
                cols[name] = jnp.zeros((M,), ys_f.dtype).at[wpos].set(
                    ys_f[:, r].reshape(-1), mode="drop")

        # rebuild typed env for selector/having
        env = {}
        for k, t in self._key_type.items():
            g, _i = self._row_of[k]
            if g == "l":
                env[k] = _join64(cols[f"{k}.hi"], cols[f"{k}.lo"])
            elif t == ast.AttrType.BOOL:
                env[k] = cols[k] != 0
            else:
                env[k] = cols[k].astype(jnp_dtype(t))
        env["__timestamp__"] = base_ts + cols["__comp_ts__"].astype(jnp.int64)
        if self.params:
            qid = jnp.clip(cols["__qid__"], 0, self.P - 1)
            for k, v in self.params.items():
                env[k] = jnp.asarray(v)[qid]
        sel = {name: jnp.broadcast_to(ce.fn(env), (M,))
               for name, ce in self.sel_fns.items()}
        valid = jnp.arange(1, M + 1, dtype=_I32) <= n
        if self.having is not None:
            henv = dict(env)
            henv.update(sel)
            valid = valid & jnp.broadcast_to(self.having.fn(henv), (M,))
        sel["__timestamp__"] = cols["__comp_ts__"]
        sel["__seq__"] = cols["__comp_seq__"]
        sel["__head_seq__"] = cols["__head_seq__"]
        if self.emit_qid:
            sel["__qid__"] = cols["__qid__"]
        for name in self.out_names:
            if name.startswith("__present__."):
                sel[name] = cols.get(name, jnp.ones((M,), _I32))

        # earliest pending deadline (for the host scheduler's next_wakeup)
        if self.Ka:
            live = (carry["occ"] > 0) & (carry["occ"] <= spec.S)
            min_dl = jnp.where(live[None], carry["dl"],
                               NO_DEADLINE).min().astype(_I32)
        else:
            min_dl = jnp.int32(NO_DEADLINE)

        # pack ALL outputs into ONE i32 matrix: the device->host pull through
        # a tunneled TPU costs ~100 ms of fixed latency per transfer, so one
        # pull per block, not one per column.  f32 rows travel bitcast to
        # i32; LONG as hi/lo pairs.  (f64 mode keeps a separate float pack —
        # correct but slower, documented.)
        meta = (jnp.zeros((M,), _I32)
                .at[0].set(n)
                .at[1].set(carry["of_slots"].sum(dtype=_I32))
                .at[2].set(carry["of_lanes"].sum(dtype=_I32))
                .at[3].set(min_dl))
        irows = [meta]
        if self.having is not None:     # else the host derives valid from n
            irows.append(valid.astype(_I32))
        frows = []
        for name in self.out_names:
            col = sel[name]
            if col.dtype == jnp.float64:
                frows.append(col)
            elif col.dtype == jnp.float32:
                irows.append(lax.bitcast_convert_type(col, _I32))
            elif col.dtype == jnp.int64:
                irows.append(_hi32(col))
                irows.append(_lo32(col))
            else:
                irows.append(col.astype(_I32))
        out = {"i": jnp.stack(irows, axis=0)}
        if frows:
            out["f"] = jnp.stack(frows, axis=0)
        return carry, out

def pow2_at_least(n: int, lo: int = 8) -> int:
    return max(lo, 1 << max(0, math.ceil(math.log2(max(1, n)))))


def _hi32(v):
    return lax.shift_right_arithmetic(v, jnp.int64(32)).astype(_I32)


def _lo32(v):
    return lax.bitcast_convert_type(
        v.astype(jnp.uint64).astype(jnp.uint32), _I32)


def _join64(hi, lo):
    return (hi.astype(jnp.int64) << jnp.int64(32)) | \
        lax.bitcast_convert_type(lo, jnp.uint32).astype(jnp.int64)


def join64_np(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return (hi.astype(np.int64) << 32) | lo.view(np.uint32).astype(np.int64)
