"""Batched device NFA — the TPU pattern/sequence matching kernel.

The north-star component (SURVEY §3.3): the reference walks per-event
pending-StateEvent lists through Pre/PostStateProcessor chains
(reference: core:query/input/stream/state/StreamPreStateProcessor.java:292,
StreamPostStateProcessor.java:53).  Here the whole matcher is ONE fused
array program:

  * the partition axis P (reference: core:partition/PartitionRuntime.java
    clones the query graph per key) becomes a batch axis — thousands of
    independent NFA instances evaluated in lockstep and shardable over a
    `jax.sharding.Mesh`;
  * pending partial matches become A fixed "slots" per partition:
    `active/state_idx/first_ts` plus capture columns `ref.attr -> (P, A)`;
  * a micro-batch becomes a dense (T, P) block — one event per partition
    per `lax.scan` step, so in-partition order (the sequential semantics)
    is preserved while all partitions and slots advance in parallel;
  * `every` heads are an always-armed flag (re-arming is free — the
    reference's trickiest corner, addEveryState + within expiry, reduces
    to a mask);
  * `within` expiry, sequence strictness, and match emission are masked
    vector ops.  Completing slots park their match snapshot in slot
    storage (sentinel state) and drain through E narrow emission lanes
    per step (masked one-hot reductions — TPU scatters serialize), so
    bursts of simultaneous completions lose nothing; after the scan, one
    scatter per column compacts the lane grid into a flat match buffer
    whose capacity the host doubles-and-retries on overflow (state is
    functional, so a retry is exact), and slot capacity A grows the same
    way when heads find no free slot.

Supported device subset (everything else falls back to the sequential
host matcher, interp/nfa.py): linear chains of single-count stream states
with an optional `every` head and per-element/query `within`; predicates
may reference any earlier capture (e2[price > e1.price]).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..query import ast
from .expr import (CompiledExpr, ExprError, MultiStreamContext,
                   compile_expression, jnp_dtype)
from .schema import TIMESTAMP_DTYPE, StreamSchema, StringTable, dtype_of

BIG_MS = np.int64(2**62)


class DeviceNFAUnsupported(Exception):
    """Raised when a pattern shape needs the sequential fallback."""


class PatternFilterContext(MultiStreamContext):
    """Filter compile context for one chain state: unqualified attributes
    resolve to the state's own (arriving) event first — mirroring the
    reference, where a condition's unqualified variables read the current
    event (reference: core:util/parser/ExpressionParser variable binding
    for state elements)."""

    def __init__(self, schemas: dict, strings, own_ref: str):
        super().__init__(schemas, strings)
        self.own_ref = own_ref

    def resolve(self, var: ast.Variable):
        if var.stream_ref is None and var.index is None \
                and var.attribute in self.schemas[self.own_ref].types:
            return (f"{self.own_ref}.{var.attribute}",
                    self.schemas[self.own_ref].type_of(var.attribute))
        return super().resolve(var)


@dataclass
class ChainState:
    ref: str
    stream_id: str
    scode: int                      # index into spec.stream_ids
    filter: Optional[CompiledExpr]  # env -> bool array
    within_ms: Optional[int]


@dataclass
class ChainSpec:
    states: list                     # [ChainState]
    stream_ids: list                 # distinct stream ids, scode order
    schemas: dict                    # ref -> StreamSchema
    is_sequence: bool
    every_head: bool

    @property
    def S(self) -> int:
        return len(self.states)


def lower_chain(state_input, schemas_by_stream: dict, strings: StringTable,
                filters_by_node: list) -> ChainSpec:
    """Validate + lower a StateInputStream into a linear device chain.

    Reuses the host NFACompiler lowering so device and host agree on
    structure; anything non-linear raises DeviceNFAUnsupported.
    """
    from ..interp.nfa import NFACompiler
    from ..query.ast import StateType

    comp = NFACompiler()
    entries, _exits = comp.lower(state_input.state)
    nodes = comp.nodes
    if len(entries) != 1 or entries[0].id != nodes[0].id:
        raise DeviceNFAUnsupported("non-single-entry pattern")
    order = []
    nid = nodes[0].id
    while nid is not None:
        order.append(nodes[nid])
        nid = nodes[nid].next_id
    if len(order) != len(nodes):
        raise DeviceNFAUnsupported("non-linear state graph")
    qw = state_input.within.millis if state_input.within else None
    stream_ids, scode_of = [], {}
    states = []
    for i, n in enumerate(order):
        if n.kind != "stream" or n.partner_id is not None:
            raise DeviceNFAUnsupported("absent/logical states")
        if n.min_count != 1 or n.max_count != 1:
            raise DeviceNFAUnsupported("count quantifiers")
        if n.sticky and i != 0:
            raise DeviceNFAUnsupported("`every` on a non-head state")
        if n.stream_id not in schemas_by_stream:
            raise DeviceNFAUnsupported(f"unknown stream {n.stream_id!r}")
        if n.stream_id not in scode_of:
            scode_of[n.stream_id] = len(stream_ids)
            stream_ids.append(n.stream_id)
        w = n.within_ms if n.within_ms is not None else qw
        states.append(ChainState(n.ref, n.stream_id, scode_of[n.stream_id],
                                 None, w))
    spec = ChainSpec(states, stream_ids,
                     {s.ref: schemas_by_stream[s.stream_id] for s in states},
                     state_input.type == StateType.SEQUENCE,
                     bool(order[0].sticky))
    # compile filters (indices follow NFACompiler node creation order ==
    # chain order for linear chains)
    for st, elem_filters in zip(spec.states, filters_by_node):
        if not elem_filters:
            continue
        f = elem_filters[0].expr
        for g in elem_filters[1:]:
            f = ast.And(f, g.expr)
        ctx = PatternFilterContext(spec.schemas, strings, st.ref)
        try:
            ce = compile_expression(f, ctx)
        except ExprError as e:
            raise DeviceNFAUnsupported(f"filter not device-compilable: {e}")
        if ce.type != ast.AttrType.BOOL:
            raise DeviceNFAUnsupported("non-boolean filter")
        st.filter = ce
    return spec


# ---------------------------------------------------------------------------
# kernel builder
# ---------------------------------------------------------------------------

class NFAKernel:
    """Builds the jitted block function for one ChainSpec.

    state pytree (persistent across blocks):
      active   (P, A) bool      slot holds a live partial match
      sidx     (P, A) int32     chain state the slot waits at (1..S-1)
      first_ts (P, A) int64     head-capture timestamp (within anchor)
      slot_seq (P, A) int64     head-capture seq (emission ordering)
      armed0   (P,)  bool       entry arm (always True for `every`)
      caps     {"ref.attr": (P, A)}   captures for every ref + completion
                                snapshot (final-ref attrs, __comp_seq__)
      of_slots (P,)  int32      slot-exhaustion events (head drops; the
                                host grows A and retries, so only nonzero
                                once the A_CAP ceiling is hit)

    block(state, ev) -> (state', out): ev holds (T, P) columns; out packs
    the match buffer into an int64 matrix + f64 matrix (2 host transfers).
    """

    def __init__(self, spec: ChainSpec, sel_fns: dict, having: Optional[CompiledExpr],
                 P: int, A: int, E: Optional[int] = None):
        self.spec = spec
        self.sel_fns = sel_fns          # out name -> CompiledExpr (over ref.attr env)
        self.having = having
        self.P, self.A = P, A
        # emission lanes: max completions recorded per partition per step.
        # TPU scatter is slow, so the scan emits into E dense lanes via
        # masked reductions; ONE scatter per column compacts the (T, E)
        # lane grid into the output ring after the scan.
        # small defaults: the host retries a block exactly (functional state)
        # with doubled E/A when the overflow counters move, so capacity
        # adapts to the workload without ever losing a match
        self.E = E if E is not None else (1 if spec.S == 1 else min(A, 2))
        self.out_names = list(sel_fns) + ["__timestamp__", "__seq__",
                                          "__head_seq__"]
        self.f64_names = {name for name, ce in sel_fns.items()
                          if ce.type == ast.AttrType.DOUBLE}
        # match-row layout (order mirrors _emit_values) — used to pack the
        # per-step scan outputs into two dense arrays (one dynamic-update-
        # slice each per step instead of one per column)
        self.emit_layout: list = [("__head_seq__", jnp.int64)]
        for s in spec.states:
            sch = spec.schemas[s.ref]
            for a in sch.attributes:
                self.emit_layout.append((f"{s.ref}.{a.name}", jnp_dtype(a.type)))
            self.emit_layout.append((f"{s.ref}.__ts__", jnp.int64))
        self.emit_layout += [("__timestamp__", jnp.int64), ("__seq__", jnp.int64)]
        self._block_cache: dict = {}    # (T, M) -> jitted fn

    # -- state ---------------------------------------------------------------

    def init_state(self) -> dict:
        spec, P, A = self.spec, self.P, self.A
        caps = {}
        # all states (incl. the final one) get capture storage: a completing
        # slot parks its completion snapshot here (sidx == S sentinel) and
        # drains through the emission lanes over following steps — bursts of
        # simultaneous completions never drop matches nor need wide lanes
        for s in spec.states:
            sch = spec.schemas[s.ref]
            for a in sch.attributes:
                caps[f"{s.ref}.{a.name}"] = jnp.zeros((P, A), dtype=jnp_dtype(a.type))
            caps[f"{s.ref}.__ts__"] = jnp.zeros((P, A), dtype=jnp.int64)
        if spec.S > 1:
            caps["__comp_seq__"] = jnp.zeros((P, A), dtype=jnp.int64)
        return {
            "active": jnp.zeros((P, A), dtype=bool),
            "sidx": jnp.zeros((P, A), dtype=jnp.int32),
            "first_ts": jnp.zeros((P, A), dtype=jnp.int64),
            "slot_seq": jnp.zeros((P, A), dtype=jnp.int64),
            "armed0": jnp.ones((P,), dtype=bool),
            "caps": caps,
            "of_slots": jnp.zeros((P,), dtype=jnp.int32),
        }

    # -- the per-event step --------------------------------------------------

    def _event_env(self, x: dict, st: ChainState, caps: dict) -> dict:
        """env for state st's predicate: captures (P,A) + current event (P,1)."""
        env = dict(caps)
        sch = self.spec.schemas[st.ref]
        for a in sch.attributes:
            env[f"{st.ref}.{a.name}"] = x[f"{st.scode}.{a.name}"][:, None]
        env["__timestamp__"] = x["__ts__"][:, None]
        return env

    def _step(self, carry: dict, x: dict):
        spec, P, A, E = self.spec, self.P, self.A, self.E
        S = spec.S
        active, sidx = carry["active"], carry["sidx"]
        first_ts, slot_seq = carry["first_ts"], carry["slot_seq"]
        armed0, caps = carry["armed0"], dict(carry["caps"])
        of_slots = carry["of_slots"]

        ts, seq = x["__ts__"], x["__seq__"]
        scode, valid = x["__scode__"], x["__valid__"]
        single_stream = len(spec.stream_ids) == 1

        # 1+2. within expiry (now = event ts; lazy, reference
        #    StreamPreStateProcessor.java:102-113) folded into the per-state
        #    match pass; matches are against PRE-event state (two-phase
        #    commit: one event can't climb two chained states)
        age = ts[:, None] - first_ts
        expired = jnp.zeros((P, A), dtype=bool)
        total_match = jnp.zeros((P, A), dtype=bool)
        complete = jnp.zeros((P, A), dtype=bool)
        cap_writes = []    # (mask (P,A), state)
        for si in range(1, S):
            st = spec.states[si]
            at_s = active & (sidx == si) & valid[:, None]
            if st.within_ms is not None:
                exp_s = at_s & (age > jnp.int64(st.within_ms))
                expired = expired | exp_s
                at_s = at_s & ~exp_s
            ok = at_s if single_stream else at_s & (scode == st.scode)[:, None]
            if st.filter is not None:
                pred = st.filter.fn(self._event_env(x, st, caps))
                ok = ok & jnp.broadcast_to(pred, (P, A))
            total_match = total_match | ok
            if si == S - 1:
                complete = ok
            else:
                cap_writes.append((ok, st))
        active = active & ~expired

        # 3. head match (entry arm)
        h = spec.states[0]
        ok0 = armed0 & valid if single_stream \
            else armed0 & (scode == h.scode) & valid
        if h.filter is not None:
            pred0 = h.filter.fn(self._event_env(x, h, caps))
            if getattr(pred0, "ndim", 0) == 2:
                if pred0.shape[1] != 1:
                    raise DeviceNFAUnsupported(
                        "head filter references later captures")
                pred0 = pred0[:, 0]
            ok0 = ok0 & jnp.broadcast_to(pred0, (P,))
        if not spec.every_head:
            armed0 = armed0 & ~ok0

        # 4. apply advances + captures
        sidx = jnp.where(total_match, sidx + 1, sidx)
        for ok, st in cap_writes:
            sch = spec.schemas[st.ref]
            for a in sch.attributes:
                k = f"{st.ref}.{a.name}"
                caps[k] = jnp.where(ok, x[f"{st.scode}.{a.name}"][:, None], caps[k])
            caps[f"{st.ref}.__ts__"] = jnp.where(ok, ts[:, None],
                                                 caps[f"{st.ref}.__ts__"])

        # 5. emission.  Completing slots advance to the sentinel state
        #    sidx == S ("done": step 4 already moved them there) and park
        #    their completion snapshot in slot storage; each step drains up
        #    to E done slots through dense lanes (masked one-hot reductions,
        #    scatter-free — TPU scatters serialize).  Bursts larger than E
        #    stay parked and drain on later steps / the post-scan drain, so
        #    no match is ever lost and lanes stay narrow.  The host
        #    re-orders same-event ties by the emitted __head_seq__.
        if S > 1:
            last = spec.states[-1]
            for a in spec.schemas[last.ref].attributes:
                k = f"{last.ref}.{a.name}"
                caps[k] = jnp.where(complete, x[f"{last.scode}.{a.name}"][:, None],
                                    caps[k])
            caps[f"{last.ref}.__ts__"] = jnp.where(complete, ts[:, None],
                                                   caps[f"{last.ref}.__ts__"])
            caps["__comp_seq__"] = jnp.where(complete, seq[:, None],
                                             caps["__comp_seq__"])
            active, y = self._drain_done(active, sidx, slot_seq, caps)
        else:
            # single-state chain: head match emits directly (one lane)
            vals = self._emit_direct(x, ts, seq)
            iy = [ok0.astype(jnp.int64)[:, None]]
            fy = []
            for nm, dt in self.emit_layout:
                col = jnp.broadcast_to(vals[nm], (P,))[:, None]
                (fy if dt == jnp.float64 else iy).append(
                    col if dt == jnp.float64 else _pack_i64(col))
            y = {"i": jnp.stack(iy, axis=0)}
            if fy:
                y["f"] = jnp.stack(fy, axis=0)

        # 6. sequence strictness: any valid event kills non-transitioned
        #    started slots (reference StreamPreStateProcessor.java:317-330);
        #    parked completions (sidx == S) already matched — exempt
        if spec.is_sequence:
            active = active & (total_match | (sidx == S) | ~valid[:, None])

        # 7. allocate a slot for the head match (at most one per step).
        #    One-hot where-writes, not scatters: scatters each compile to
        #    their own kernel and serialize the step; wheres fuse.
        if S > 1:
            free = ~active
            has_free = free.any(axis=1)
            slot = jnp.argmax(free, axis=1)                    # first free
            do = ok0 & has_free
            of_slots = of_slots + (ok0 & ~has_free).astype(jnp.int32)
            hot = (jnp.arange(A)[None, :] == slot[:, None]) & do[:, None]  # (P,A)
            active = active | hot
            sidx = jnp.where(hot, 1, sidx)
            first_ts = jnp.where(hot, ts[:, None], first_ts)
            slot_seq = jnp.where(hot, seq[:, None], slot_seq)
            sch = spec.schemas[h.ref]
            for a in sch.attributes:
                k = f"{h.ref}.{a.name}"
                caps[k] = jnp.where(hot, x[f"{h.scode}.{a.name}"][:, None],
                                    caps[k])
            caps[f"{h.ref}.__ts__"] = jnp.where(hot, ts[:, None],
                                                caps[f"{h.ref}.__ts__"])

        carry = {"active": active, "sidx": sidx, "first_ts": first_ts,
                 "slot_seq": slot_seq, "armed0": armed0, "caps": caps,
                 "of_slots": of_slots}
        return carry, y

    def _drain_done(self, active, sidx, slot_seq, caps):
        """Emit up to E parked completions per partition from slot storage;
        returns (active', y) with y the packed (Ci/Cf, P, E) lane grids."""
        spec, P, A, E = self.spec, self.P, self.A, self.E
        done = active & (sidx == spec.S)
        rank = jnp.cumsum(done, axis=1) - done
        sels = [done & (rank == e) for e in range(E)]       # one-hot over A
        lv = jnp.stack([s.any(axis=1) for s in sels], axis=1)   # (P, E)
        vals = self._emit_from_storage(caps, slot_seq)
        igrid = jnp.stack(
            [_pack_i64(jnp.broadcast_to(vals[nm], (P, A)))
             for nm, dt in self.emit_layout if dt != jnp.float64], axis=0)
        fcols = [jnp.broadcast_to(vals[nm], (P, A))
                 for nm, dt in self.emit_layout if dt == jnp.float64]
        # whole-row grids: one masked reduction per LANE, not per column
        ilanes = jnp.stack(
            [jnp.where(s[None], igrid, 0).sum(axis=-1) for s in sels],
            axis=-1)                                        # (Ci', P, E)
        y = {"i": jnp.concatenate([lv.astype(jnp.int64)[None], ilanes], axis=0)}
        if fcols:
            fgrid = jnp.stack(fcols, axis=0)
            y["f"] = jnp.stack(
                [jnp.where(s[None], fgrid, 0.0).sum(axis=-1) for s in sels],
                axis=-1)                                    # (Cf, P, E)
        emitted = done & (rank < E)
        return active & ~emitted, y

    def _emit_from_storage(self, caps: dict, slot_seq) -> dict:
        """Match-row (P,A) columns for parked completions (layout order)."""
        spec = self.spec
        last = spec.states[-1]
        vals: dict = {"__head_seq__": slot_seq}
        for s in spec.states:
            sch = spec.schemas[s.ref]
            for a in sch.attributes:
                k = f"{s.ref}.{a.name}"
                vals[k] = caps[k]
            vals[f"{s.ref}.__ts__"] = caps[f"{s.ref}.__ts__"]
        vals["__timestamp__"] = caps[f"{last.ref}.__ts__"]
        vals["__seq__"] = caps["__comp_seq__"]
        return vals

    def _emit_direct(self, x: dict, ts, seq) -> dict:
        """Match-row (P,) columns for single-state chains (layout order)."""
        st = self.spec.states[0]
        vals: dict = {"__head_seq__": seq}
        for a in self.spec.schemas[st.ref].attributes:
            vals[f"{st.ref}.{a.name}"] = x[f"{st.scode}.{a.name}"]
        vals[f"{st.ref}.__ts__"] = ts
        vals["__timestamp__"] = ts
        vals["__seq__"] = seq
        return vals

    # -- block ---------------------------------------------------------------

    def raw_block_fn(self, M: int) -> Callable:
        """Unjitted block(state, ev) — the framework's 'forward step' for
        compile checks and mesh-sharded execution."""
        return self._make_block(M)

    def block_fn(self, T: int, M: int) -> Callable:
        key = (T, M)
        fn = self._block_cache.get(key)
        if fn is None:
            fn = self._block_cache[key] = jax.jit(self._make_block(M))
        return fn

    def _make_block(self, M: int) -> Callable:
        """M = flat match-buffer capacity for the whole block (host retries
        with 2M on overflow; state is functional so a retry is exact)."""

        def block(state, ev):
            # unroll: the per-event body is latency-bound (small (P,A) ops);
            # unrolling amortizes loop overhead across several events
            carry, ys = lax.scan(self._step, dict(state), ev)
            if self.spec.S > 1:
                # drain parked completions so a flush returns every match
                # produced by its events: ceil(A/E) lane rounds empty any
                # backlog (each round frees E slots per partition)
                def drain_step(c, _):
                    act, y2 = self._drain_done(c["active"], c["sidx"],
                                               c["slot_seq"], c["caps"])
                    c2 = dict(c)
                    c2["active"] = act
                    return c2, y2
                rounds = -(-self.A // self.E)
                carry, ys2 = lax.scan(drain_step, carry, None, length=rounds)
                ys = jax.tree_util.tree_map(
                    lambda a, b: jnp.concatenate([a, b], axis=0), ys, ys2)
            # compact the packed (T, C, P, E) lane grids into ONE flat (M,)
            # buffer per column — a single scatter each, and the transfer
            # carries only matches instead of a sparse ring
            ys_i = ys["i"]                        # (T, Ci, P, E) int64
            ys_f = ys.get("f")                    # (T, Cf, P, E) f64

            def flatten(arr):                     # (T, P, E) -> (T*P*E,)
                # time-major flat order, NO transpose (the grids are large);
                # the host re-sorts matches by (__seq__, __head_seq__)
                return arr.reshape(-1)

            lv = flatten(ys_i[:, 0]) != 0
            pos = jnp.cumsum(lv) - lv
            wpos = jnp.where(lv & (pos < M), pos, M)
            out = {}
            ii, fi = 1, 0
            for name, dt in self.emit_layout:
                if dt == jnp.float64:
                    flat = flatten(ys_f[:, fi]); fi += 1
                    col = jnp.zeros((M,), dt).at[wpos].set(flat, mode="drop")
                else:
                    flat = flatten(ys_i[:, ii]); ii += 1
                    col = _unpack_jnp(
                        jnp.zeros((M,), jnp.int64).at[wpos].set(flat, mode="drop"),
                        dt)
                out[name] = col
            n = lv.sum(dtype=jnp.int64)
            # selector + having over the match buffer
            sel = {name: ce.fn(out) for name, ce in self.sel_fns.items()}
            valid = jnp.arange(M) < jnp.minimum(n, M)
            if self.having is not None:
                henv = dict(out)
                henv.update(sel)
                valid = valid & self.having.fn(henv)
            sel["__timestamp__"] = out["__timestamp__"]
            sel["__seq__"] = out["__seq__"]
            sel["__head_seq__"] = out["__head_seq__"]
            # pack the outputs into TWO matrices so the device->host pull is
            # two transfers total (vs one RPC per column): an int64 pack
            # (row 0 = [n, of_slots, ...], row 1 = valid, then the
            # non-f64 columns) and an f64 stack (TPU's emulated f64 can't
            # bitcast into the int pack)
            meta = (jnp.zeros((M,), jnp.int64)
                    .at[0].set(n)
                    .at[1].set(carry["of_slots"].sum(dtype=jnp.int64)))
            irows = [meta, valid.astype(jnp.int64)]
            frows = []
            for name in self.out_names:
                col = sel[name]
                if col.dtype == jnp.float64:
                    frows.append(col)
                else:
                    irows.append(_pack_i64(col))
            out2 = {"i": jnp.stack(irows, axis=0)}
            if frows:
                out2["f"] = jnp.stack(frows, axis=0)
            return carry, out2
        return block


def pow2_at_least(n: int, lo: int = 8) -> int:
    return max(lo, 1 << max(0, math.ceil(math.log2(max(1, n)))))


def _pack_i64(col):
    """Bitcast a non-f64 column dtype into an int64 lane (see _unpack_i64);
    f64 travels in its own pack — TPU emulates f64 and can't bitcast it."""
    if col.dtype == jnp.float32:
        return lax.bitcast_convert_type(col, jnp.int32).astype(jnp.int64)
    return col.astype(jnp.int64)


def _unpack_jnp(col, dtype):
    """Device-side inverse of _pack_i64."""
    if dtype == jnp.float32:
        return lax.bitcast_convert_type(col.astype(jnp.int32), jnp.float32)
    if dtype == jnp.bool_:
        return col != 0
    return col.astype(dtype)


def _unpack_i64(row: np.ndarray, dtype) -> np.ndarray:
    dtype = np.dtype(dtype)
    if dtype == np.float32:
        return row.astype(np.int32).view(np.float32)
    if dtype == np.bool_:
        return row != 0
    return row.astype(dtype)
