"""Batched device NFA — the TPU pattern/sequence matching kernel.

The north-star component (SURVEY §3.3): the reference walks per-event
pending-StateEvent lists through Pre/PostStateProcessor chains
(reference: core:query/input/stream/state/StreamPreStateProcessor.java:292,
StreamPostStateProcessor.java:53).  Here the whole matcher is ONE fused
array program:

  * the partition axis P (reference: core:partition/PartitionRuntime.java
    clones the query graph per key) becomes the minor (lane) axis —
    thousands of independent NFA instances evaluated in lockstep and
    shardable over a `jax.sharding.Mesh`;
  * pending partial matches become A fixed "slots" per partition laid out
    (A, P): `sidx` (0 = free, 1..S-1 = waiting, S = parked completion)
    plus capture rows `ref.attr -> (A, P)`;
  * a micro-batch becomes a dense (T, P) block — one event per partition
    per `lax.scan` step, so in-partition order (the sequential semantics)
    is preserved while all partitions and slots advance in parallel;
  * `every` heads are an always-armed flag; `within` expiry, sequence
    strictness, and match emission are masked vector ops.

TPU-economics of this kernel (what round-2 got wrong and this design
fixes; measured on v5e):
  * NO f64/i64 inside the scan.  x64 arrays are emulated as f32/u32
    pairs, which (a) doubles every carry/output buffer and (b) made XLA
    choose mismatched layouts for the big scan-output accumulators,
    copying ~30 GB of HBM per block (~2 ms/step).  Timestamps and seqs
    travel as i32 offsets from per-plan bases, rebased host-side before
    they can overflow; DOUBLE computes in f32 by default
    (`@app:devicePrecision('f64')` opts out, documented slower).
  * capture storage holds ONLY the columns some predicate / selector /
    having actually reads (CompiledExpr.reads), grouped per-dtype into
    stacked (K, A, P) arrays so writes/emissions are one masked select
    per group instead of one per column.
  * predicates that read only the arriving event (no captures) are
    evaluated for the WHOLE block outside the scan as fused (T, P)
    vector ops; only capture-dependent conjuncts run per-step.
  * completing slots park their snapshot in slot storage (sentinel
    state) and drain through E narrow i32/f32 lanes per step (masked
    one-hot reductions — TPU scatters serialize); after the scan,
    ceil(A/E) drain rounds empty any backlog, then ONE
    cumsum+searchsorted+gather per lane-grid row compacts matches into
    a flat (M,) buffer (capacity doubled-and-retried on overflow —
    state is functional, so a retry is exact).

Supported device subset (everything else falls back to the sequential
host matcher, interp/nfa.py): linear chains of single-count stream states
with an optional `every` head and per-element/query `within`; predicates
may reference any earlier capture (e2[price > e1.price]).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..query import ast
from .expr import (CompiledExpr, ExprError, MultiStreamContext, compute_dtypes,
                   F32_MODE, compile_expression, jnp_dtype)
from .schema import StreamSchema, StringTable

# local-offset budget: rebase when offsets approach this (i32 headroom)
LOCAL_SPAN = 1 << 30


class DeviceNFAUnsupported(Exception):
    """Raised when a pattern shape needs the sequential fallback."""


class PatternFilterContext(MultiStreamContext):
    """Filter compile context for one chain state: unqualified attributes
    resolve to the state's own (arriving) event first — mirroring the
    reference, where a condition's unqualified variables read the current
    event (reference: core:util/parser/ExpressionParser variable binding
    for state elements)."""

    def __init__(self, schemas: dict, strings, own_ref: str):
        super().__init__(schemas, strings)
        self.own_ref = own_ref

    def resolve(self, var: ast.Variable):
        if var.stream_ref is None and var.index is None \
                and var.attribute in self.schemas[self.own_ref].types:
            return (f"{self.own_ref}.{var.attribute}",
                    self.schemas[self.own_ref].type_of(var.attribute))
        return super().resolve(var)


@dataclass
class ChainState:
    ref: str
    stream_id: str
    scode: int                      # index into spec.stream_ids
    within_ms: Optional[int]
    # filter conjuncts, split by what they read:
    pre_conjs: list = field(default_factory=list)   # event-only -> (T,P) pre-pass
    step_conjs: list = field(default_factory=list)  # capture-referencing -> in-scan


@dataclass
class ChainSpec:
    states: list                     # [ChainState]
    stream_ids: list                 # distinct stream ids, scode order
    schemas: dict                    # ref -> StreamSchema
    is_sequence: bool
    every_head: bool

    @property
    def S(self) -> int:
        return len(self.states)


def _conjuncts(e: ast.Expression) -> list:
    if isinstance(e, ast.And):
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def lower_chain(state_input, schemas_by_stream: dict, strings: StringTable,
                filters_by_node: list) -> ChainSpec:
    """Validate + lower a StateInputStream into a linear device chain.

    Reuses the host NFACompiler lowering so device and host agree on
    structure; anything non-linear raises DeviceNFAUnsupported.
    """
    from ..interp.nfa import NFACompiler
    from ..query.ast import StateType

    comp = NFACompiler()
    entries, _exits = comp.lower(state_input.state)
    nodes = comp.nodes
    if len(entries) != 1 or entries[0].id != nodes[0].id:
        raise DeviceNFAUnsupported("non-single-entry pattern")
    order = []
    nid = nodes[0].id
    while nid is not None:
        order.append(nodes[nid])
        nid = nodes[nid].next_id
    if len(order) != len(nodes):
        raise DeviceNFAUnsupported("non-linear state graph")
    qw = state_input.within.millis if state_input.within else None
    stream_ids, scode_of = [], {}
    states = []
    for i, n in enumerate(order):
        if n.kind != "stream" or n.partner_id is not None:
            raise DeviceNFAUnsupported("absent/logical states")
        if n.min_count != 1 or n.max_count != 1:
            raise DeviceNFAUnsupported("count quantifiers")
        if n.sticky and i != 0:
            raise DeviceNFAUnsupported("`every` on a non-head state")
        if n.stream_id not in schemas_by_stream:
            raise DeviceNFAUnsupported(f"unknown stream {n.stream_id!r}")
        if n.stream_id not in scode_of:
            scode_of[n.stream_id] = len(stream_ids)
            stream_ids.append(n.stream_id)
        w = n.within_ms if n.within_ms is not None else qw
        if w is not None and w >= LOCAL_SPAN:
            raise DeviceNFAUnsupported("within > ~12 days (i32 ms offsets)")
        states.append(ChainState(n.ref, n.stream_id, scode_of[n.stream_id], w))
    spec = ChainSpec(states, stream_ids,
                     {s.ref: schemas_by_stream[s.stream_id] for s in states},
                     state_input.type == StateType.SEQUENCE,
                     bool(order[0].sticky))
    # compile filters (indices follow NFACompiler node creation order ==
    # chain order for linear chains), split into event-only vs capture-
    # referencing conjuncts
    for si, (st, elem_filters) in enumerate(zip(spec.states, filters_by_node)):
        conjs: list = []
        for f in elem_filters:
            conjs.extend(_conjuncts(f.expr))
        ctx = PatternFilterContext(spec.schemas, strings, st.ref)
        for c in conjs:
            try:
                ce = compile_expression(c, ctx)
            except ExprError as e:
                raise DeviceNFAUnsupported(f"filter not device-compilable: {e}")
            if ce.type != ast.AttrType.BOOL:
                raise DeviceNFAUnsupported("non-boolean filter")
            own = {f"{st.ref}.{a.name}" for a in spec.schemas[st.ref].attributes}
            own.add("__timestamp__")
            if set(ce.reads) <= own:
                st.pre_conjs.append(ce)
            else:
                if si == 0:
                    raise DeviceNFAUnsupported(
                        "head filter references later captures")
                st.step_conjs.append(ce)
    return spec


# ---------------------------------------------------------------------------
# kernel builder
# ---------------------------------------------------------------------------

_I32 = jnp.int32


class NFAKernel:
    """Builds the jitted block function for one ChainSpec.

    state pytree (persistent across blocks; all (A, P) with P minor):
      sidx     (A, P) i32      0 = free, si = waiting at chain state si,
                               S = parked completion awaiting a drain lane
      first_ts (A, P) i32      head-capture ts offset (within anchor)
      head_seq (A, P) i32      head-capture seq offset (emission tie order)
      caps_f   (Kf, A, P) f32  float capture rows (see self.rows_f)
      caps_i   (Ki, A, P) i32  int/string/bool capture rows + parked
                               completion ts/seq (self.rows_i)
      caps_l   (Kl, A, P) i64  LONG capture rows (self.rows_l; emitted as
                               hi/lo i32 lane pairs)
      armed0   (P,)  bool      entry arm (always True for `every`)
      of_slots (P,)  i32       slot-exhaustion events (head drops; the host
                               grows A and retries, so only nonzero once
                               the A_CAP ceiling is hit)

    block(state, ev) -> (state', out): ev holds (T, P) i32/f32 grids plus
    0-d base scalars; out packs the compacted match buffer into an i32
    matrix + f32 matrix (two host transfers).
    """

    def __init__(self, spec: ChainSpec, sel_fns: dict, having: Optional[CompiledExpr],
                 P: int, A: int, E: Optional[int] = None, f64: bool = False):
        self.spec = spec
        self.sel_fns = sel_fns          # out name -> CompiledExpr (over ref.attr env)
        self.having = having
        self.P, self.A = P, A
        self.f64 = f64
        self._mode = None if f64 else F32_MODE
        # emission lanes: completions drained per partition per step; parked
        # backlog drains on later steps / post-scan rounds, so E stays narrow
        # without ever losing a match.
        self.E = E if E is not None else (1 if spec.S == 1 else min(A, 2))

        # ---- capture rows: only columns something downstream reads -------
        cap_keys: set = set()
        for st in spec.states:
            for ce in st.step_conjs:
                for k in ce.reads:
                    if k == "__timestamp__":
                        continue
                    ref = k.split(".", 1)[0]
                    if ref != st.ref:
                        cap_keys.add(k)
        for ce in list(sel_fns.values()) + ([having] if having else []):
            for k in ce.reads:
                if "." in k and not k.startswith("__"):
                    cap_keys.add(k)
        self._key_type: dict = {}
        for k in sorted(cap_keys):
            ref, attr = k.split(".", 1)
            if ref not in spec.schemas:
                raise DeviceNFAUnsupported(f"unresolvable capture key {k!r}")
            self._key_type[k] = spec.schemas[ref].type_of(attr)
        with compute_dtypes(self._mode):
            grp = {k: self._group_of(jnp_dtype(t))
                   for k, t in self._key_type.items()}
        self.rows_f = [k for k in sorted(cap_keys) if grp[k] == "f"]
        self.rows_l = [k for k in sorted(cap_keys) if grp[k] == "l"]
        self.rows_i = [k for k in sorted(cap_keys) if grp[k] == "i"]
        if spec.S > 1:
            self.rows_i += ["__comp_ts__", "__comp_seq__"]
        self._row_of = {k: ("f", i) for i, k in enumerate(self.rows_f)}
        self._row_of.update({k: ("i", i) for i, k in enumerate(self.rows_i)})
        self._row_of.update({k: ("l", i) for i, k in enumerate(self.rows_l)})

        # ---- output rows (post-selector) ----------------------------------
        self.out_names = list(sel_fns) + ["__timestamp__", "__seq__",
                                          "__head_seq__"]
        with compute_dtypes(self._mode):
            self.out_dtypes = {n: jnp_dtype(ce.type)
                               for n, ce in sel_fns.items()}
        self.out_dtypes["__timestamp__"] = _I32   # local offsets
        self.out_dtypes["__seq__"] = _I32
        self.out_dtypes["__head_seq__"] = _I32
        self._block_cache: dict = {}    # (T, M) -> jitted fn

    @staticmethod
    def _group_of(dt) -> str:
        if dt in (jnp.float32, jnp.float64):
            return "f"
        if dt == jnp.int64:
            return "l"
        return "i"

    @property
    def fdt(self):
        return jnp.float64 if self.f64 else jnp.float32

    # -- state ---------------------------------------------------------------

    def init_state(self) -> dict:
        P, A = self.P, self.A
        return {
            "sidx": jnp.zeros((A, P), dtype=_I32),
            "first_ts": jnp.zeros((A, P), dtype=_I32),
            "head_seq": jnp.zeros((A, P), dtype=_I32),
            "caps_f": jnp.zeros((len(self.rows_f), A, P), dtype=self.fdt),
            "caps_i": jnp.zeros((len(self.rows_i), A, P), dtype=_I32),
            "caps_l": jnp.zeros((len(self.rows_l), A, P), dtype=jnp.int64),
            "armed0": jnp.ones((P,), dtype=bool),
            "of_slots": jnp.zeros((P,), dtype=_I32),
        }

    # -- env helpers -----------------------------------------------------

    def _caps_env(self, caps: dict) -> dict:
        """Capture rows as named (A, P) views (bool rows decoded)."""
        env = {}
        for k, (g, i) in self._row_of.items():
            col = caps[f"caps_{g}"][i]
            t = self._key_type.get(k)
            if t == ast.AttrType.BOOL:
                col = col != 0
            env[k] = col
        return env

    def _event_env(self, x: dict, st: ChainState, base_ts) -> dict:
        """Arriving event's own columns as (P,) arrays (broadcast vs (A,P))."""
        env = {}
        sch = self.spec.schemas[st.ref]
        for a in sch.attributes:
            key = f"{st.scode}.{a.name}"
            if key in x:
                env[f"{st.ref}.{a.name}"] = x[key]
        env["__timestamp__"] = base_ts + x["__ts__"].astype(jnp.int64)
        return env

    def _write_caps(self, caps: dict, mask, st: ChainState, x: dict,
                    extra: Optional[dict] = None) -> dict:
        """Masked write of state st's captured event columns into slot
        storage; `mask` is (A, P).  One select per dtype group."""
        caps = dict(caps)
        ev_env = {}
        sch = self.spec.schemas[st.ref]
        for a in sch.attributes:
            key = f"{st.scode}.{a.name}"
            if key in x:
                ev_env[f"{st.ref}.{a.name}"] = x[key]
        if extra:
            ev_env.update(extra)
        for g in ("f", "i", "l"):
            rows = {"f": self.rows_f, "i": self.rows_i, "l": self.rows_l}[g]
            idx, vals = [], []
            for i, k in enumerate(rows):
                if k in ev_env:
                    idx.append(i)
                    v = ev_env[k]
                    dt = caps[f"caps_{g}"].dtype
                    vals.append(jnp.broadcast_to(v, (self.P,)).astype(dt))
            if not idx:
                continue
            arr = caps[f"caps_{g}"]
            if len(idx) == arr.shape[0]:
                new = jnp.stack(vals, axis=0)[:, None, :]        # (K,1,P)
                caps[f"caps_{g}"] = jnp.where(mask[None], new, arr)
            else:
                for i, v in zip(idx, vals):
                    caps[f"caps_{g}"] = caps[f"caps_{g}"].at[i].set(
                        jnp.where(mask, v[None, :], caps[f"caps_{g}"][i]))
        return caps

    # -- the per-event step ----------------------------------------------

    def _step(self, carry: dict, x: dict):
        spec, P, A, E = self.spec, self.P, self.A, self.E
        S = spec.S
        sidx = carry["sidx"]
        first_ts, head_seq = carry["first_ts"], carry["head_seq"]
        caps = {k: carry[k] for k in ("caps_f", "caps_i", "caps_l")}
        armed0, of_slots = carry["armed0"], carry["of_slots"]
        base_ts = x["__base_ts__"]

        ts, seq, valid = x["__ts__"], x["__seq__"], x["__valid__"]
        scode = x.get("__scode__")
        single_stream = scode is None

        # 1+2. within expiry (now = event ts; lazy, reference
        #    StreamPreStateProcessor.java:102-113) folded into the per-state
        #    match pass; matches are against PRE-event state (two-phase
        #    commit: one event can't climb two chained states)
        age = ts[None, :] - first_ts
        expired = jnp.zeros((A, P), dtype=bool)
        total_match = jnp.zeros((A, P), dtype=bool)
        complete = jnp.zeros((A, P), dtype=bool)
        cap_writes = []    # (mask (A,P), state)
        caps_env = self._caps_env(caps)
        for si in range(1, S):
            st = spec.states[si]
            at_s = (sidx == si) & valid[None, :]
            if st.within_ms is not None:
                exp_s = at_s & (age > jnp.int32(st.within_ms))
                expired = expired | exp_s
                at_s = at_s & ~exp_s
            ok = at_s if single_stream else at_s & (scode == st.scode)[None, :]
            if st.pre_conjs:
                ok = ok & x[f"__pre{si}__"][None, :]
            for ce in st.step_conjs:
                env = dict(caps_env)
                env.update(self._event_env(x, st, base_ts))
                pred = ce.fn(env)
                ok = ok & jnp.broadcast_to(pred, (A, P))
            total_match = total_match | ok
            if si == S - 1:
                complete = ok
            else:
                cap_writes.append((ok, st))
        sidx = jnp.where(expired, 0, sidx)

        # 3. head match (entry arm; head filters are all pre-evaluated)
        h = spec.states[0]
        ok0 = armed0 & valid if single_stream \
            else armed0 & (scode == h.scode) & valid
        if h.pre_conjs:
            ok0 = ok0 & x["__pre0__"]
        if not spec.every_head:
            armed0 = armed0 & ~ok0

        # 4. apply advances + captures
        sidx = jnp.where(total_match, sidx + 1, sidx)
        for ok, st in cap_writes:
            caps = self._write_caps(caps, ok, st, x)

        # 5. emission.  Completing slots advance to the sentinel state
        #    sidx == S ("done": step 4 already moved them there) and park
        #    their completion snapshot in slot storage; each step drains up
        #    to E done slots through dense lanes (masked one-hot reductions,
        #    scatter-free — TPU scatters serialize).  Bursts larger than E
        #    stay parked and drain on later steps / the post-scan drain, so
        #    no match is ever lost and lanes stay narrow.  The host
        #    re-orders same-event ties by the emitted __head_seq__.
        if S > 1:
            caps = self._write_caps(
                caps, complete, spec.states[-1], x,
                extra={"__comp_ts__": ts, "__comp_seq__": seq})
            sidx, y = self._drain_done(sidx, head_seq, caps)
        else:
            # single-state chain: head match emits directly (one lane)
            ev_env = self._event_env(x, h, base_ts)
            irows = [ok0.astype(_I32)[None, :]]
            frows = []
            for k in self.rows_f:
                frows.append(jnp.broadcast_to(ev_env[k], (P,)).astype(self.fdt)[None, :])
            for k in self.rows_i:
                v = ev_env.get(k, jnp.zeros((P,), _I32))
                irows.append(jnp.broadcast_to(v, (P,)).astype(_I32)[None, :])
            irows.append(seq[None, :])      # __head_seq__
            for k in self.rows_l:
                v = jnp.broadcast_to(ev_env[k], (P,)).astype(jnp.int64)
                irows.append(_hi32(v)[None, :])
                irows.append(_lo32(v)[None, :])
            irows.append(ts[None, :])       # __comp_ts__ (S==1 tail rows)
            irows.append(seq[None, :])      # __comp_seq__
            y = {"i": jnp.stack(irows, axis=0)}           # (Ci, 1=E, P)
            if frows:
                y["f"] = jnp.stack(frows, axis=0)

        # 6. sequence strictness: any valid event kills non-transitioned
        #    started slots (reference StreamPreStateProcessor.java:317-330);
        #    parked completions (sidx == S) already matched — exempt
        if spec.is_sequence:
            started = (sidx > 0) & (sidx < S)
            kill = started & ~total_match & valid[None, :]
            sidx = jnp.where(kill, 0, sidx)

        # 7. allocate a slot for the head match (at most one per step).
        #    One-hot where-writes, not scatters: scatters each compile to
        #    their own kernel and serialize the step; wheres fuse.
        if S > 1:
            free = sidx == 0
            has_free = free.any(axis=0)
            do = ok0 & has_free
            of_slots = of_slots + (ok0 & ~has_free).astype(_I32)
            hot = free & (jnp.cumsum(free.astype(_I32), axis=0, dtype=_I32) == 1) \
                & do[None, :]                                    # (A,P)
            sidx = jnp.where(hot, 1, sidx)
            first_ts = jnp.where(hot, ts[None, :], first_ts)
            head_seq = jnp.where(hot, seq[None, :], head_seq)
            caps = self._write_caps(caps, hot, h, x)

        carry = {"sidx": sidx, "first_ts": first_ts, "head_seq": head_seq,
                 "caps_f": caps["caps_f"], "caps_i": caps["caps_i"],
                 "caps_l": caps["caps_l"], "armed0": armed0,
                 "of_slots": of_slots}
        return carry, y

    def _drain_done(self, sidx, head_seq, caps):
        """Emit up to E parked completions per partition from slot storage;
        returns (sidx', y) with y the packed (C, E, P) lane grids."""
        spec, P, A, E = self.spec, self.P, self.A, self.E
        done = sidx == spec.S
        rank = jnp.cumsum(done.astype(_I32), axis=0, dtype=_I32) - done
        sels = [done & (rank == e) for e in range(E)]       # one-hot over A
        lv = jnp.stack([s.any(axis=0) for s in sels], axis=0)   # (E, P)
        # i-grid: i32 cap rows + head_seq + hi/lo pairs of LONG rows
        igrid = [caps["caps_i"], head_seq[None]]
        if self.rows_l:
            cl = caps["caps_l"]
            igrid.append(_hi32(cl))
            igrid.append(_lo32(cl))
        igrid = jnp.concatenate(igrid, axis=0)              # (Ki', A, P)
        ilanes = jnp.stack(
            [jnp.where(s[None], igrid, 0).sum(axis=1, dtype=_I32) for s in sels],
            axis=1)                                         # (Ki', E, P)
        y = {"i": jnp.concatenate([lv.astype(_I32)[None], ilanes], axis=0)}
        if self.rows_f:
            fgrid = caps["caps_f"]
            y["f"] = jnp.stack(
                [jnp.where(s[None], fgrid, 0).sum(axis=1, dtype=fgrid.dtype) for s in sels],
                axis=1)                                     # (Kf, E, P)
        emitted = done & (rank < E)
        return jnp.where(emitted, 0, sidx), y

    # lane-grid row order for y["i"] (after the lv row)
    def _ilane_names(self) -> list:
        names = list(self.rows_i) + ["__head_seq__"]
        for k in self.rows_l:
            names += [f"{k}.hi", f"{k}.lo"]
        if self.spec.S == 1:
            names += ["__comp_ts__", "__comp_seq__"]
        return names

    # -- block ---------------------------------------------------------------

    def raw_block_fn(self, M: int) -> Callable:
        """Unjitted block(state, ev) — the framework's 'forward step' for
        compile checks and mesh-sharded execution."""
        return self._make_block(M)

    def block_fn(self, T: int, M: int) -> Callable:
        key = (T, M)
        fn = self._block_cache.get(key)
        if fn is None:
            fn = self._block_cache[key] = jax.jit(self._make_block(M))
        return fn

    def _pre_masks(self, ev: dict) -> dict:
        """Evaluate event-only filter conjuncts over the whole (T, P) block
        in one fused pass (outside the scan)."""
        out = {}
        for si, st in enumerate(self.spec.states):
            if not st.pre_conjs:
                continue
            env = {}
            for a in self.spec.schemas[st.ref].attributes:
                key = f"{st.scode}.{a.name}"
                if key in ev:
                    env[f"{st.ref}.{a.name}"] = ev[key]
            env["__timestamp__"] = ev["__base_ts__"] \
                + ev["__ts__"].astype(jnp.int64)
            m = None
            for ce in st.pre_conjs:
                p = ce.fn(env)
                m = p if m is None else (m & p)
            out[f"__pre{si}__"] = jnp.broadcast_to(m, ev["__ts__"].shape)
        return out

    def _make_block(self, M: int) -> Callable:
        """M = flat match-buffer capacity for the whole block (host retries
        with 2M on overflow; state is functional so a retry is exact)."""

        def block(state, ev):
            with compute_dtypes(self._mode):
                return self._block_impl(state, ev, M)
        return block

    def _block_impl(self, state, ev, M: int):
        spec = self.spec
        ev = dict(ev)
        ev.update(self._pre_masks(ev))
        base_ts = ev["__base_ts__"]
        base_seq = ev["__base_seq__"]
        xs = {k: v for k, v in ev.items()
              if k not in ("__base_ts__", "__base_seq__")}
        T = xs["__ts__"].shape[0]

        def step(carry, x):
            x = dict(x)
            x["__base_ts__"] = base_ts
            return self._step(carry, x)

        carry, ys = lax.scan(step, dict(state), xs)
        if spec.S > 1:
            # drain parked completions so a flush returns every match
            # produced by its events: ceil(A/E) lane rounds empty any
            # backlog (each round frees E slots per partition)
            def drain_step(c, _):
                sidx2, y2 = self._drain_done(c["sidx"], c["head_seq"],
                                             {k: c[k] for k in
                                              ("caps_f", "caps_i", "caps_l")})
                c2 = dict(c)
                c2["sidx"] = sidx2
                return c2, y2
            rounds = -(-self.A // self.E)
            carry, ys2 = lax.scan(drain_step, carry, None, length=rounds)
            ys = jax.tree_util.tree_map(
                lambda a, b: jnp.concatenate([a, b], axis=0), ys, ys2)

        # compact the (T', C, E, P) lane grids into flat (M,) buffers: one
        # i32 cumsum for positions + ONE scatter per row.  (searchsorted+
        # gather lowers to an O(M)-serialized loop on TPU — measured 460 ms
        # at M=131k vs 0.1 ms for the scatter form; i32 everywhere keeps
        # XLA from the x64 pair-splitting that made round-2's scatters
        # trigger whole-buffer layout copies.)
        ys_i = ys["i"]                        # (T', Ci, E, P) i32
        ys_f = ys.get("f")                    # (T', Cf, E, P) f32
        lv = ys_i[:, 0].reshape(-1) != 0      # (T'*E*P,)
        pos = jnp.cumsum(lv.astype(_I32), dtype=_I32) - lv
        n = pos[-1] + lv[-1]
        wpos = jnp.where(lv & (pos < M), pos, M)
        cols = {}
        for r, name in enumerate(self._ilane_names()):
            cols[name] = jnp.zeros((M,), _I32).at[wpos].set(
                ys_i[:, r + 1].reshape(-1), mode="drop")
        if ys_f is not None:
            for r, name in enumerate(self.rows_f):
                cols[name] = jnp.zeros((M,), ys_f.dtype).at[wpos].set(
                    ys_f[:, r].reshape(-1), mode="drop")

        # rebuild typed env for selector/having
        env = {}
        for k, t in self._key_type.items():
            g, _i = self._row_of[k]
            if g == "l":
                env[k] = _join64(cols[f"{k}.hi"], cols[f"{k}.lo"])
            elif t == ast.AttrType.BOOL:
                env[k] = cols[k] != 0
            else:
                env[k] = cols[k].astype(jnp_dtype(t))
        env["__timestamp__"] = base_ts + cols["__comp_ts__"].astype(jnp.int64)
        sel = {name: jnp.broadcast_to(ce.fn(env), (M,))
               for name, ce in self.sel_fns.items()}
        valid = jnp.arange(1, M + 1, dtype=_I32) <= n
        if self.having is not None:
            henv = dict(env)
            henv.update(sel)
            valid = valid & jnp.broadcast_to(self.having.fn(henv), (M,))
        sel["__timestamp__"] = cols["__comp_ts__"]
        sel["__seq__"] = cols["__comp_seq__"]
        sel["__head_seq__"] = cols["__head_seq__"]

        # pack ALL outputs into ONE i32 matrix: the device->host pull through
        # a tunneled TPU costs ~100 ms of fixed latency per transfer, so one
        # pull per block, not one per column.  f32 rows travel bitcast to
        # i32; LONG as hi/lo pairs.  (f64 mode keeps a separate float pack —
        # correct but slower, documented.)
        meta = (jnp.zeros((M,), _I32)
                .at[0].set(n)
                .at[1].set(carry["of_slots"].sum(dtype=_I32)))
        irows = [meta]
        if self.having is not None:     # else the host derives valid from n
            irows.append(valid.astype(_I32))
        frows = []
        for name in self.out_names:
            col = sel[name]
            if col.dtype == jnp.float64:
                frows.append(col)
            elif col.dtype == jnp.float32:
                irows.append(lax.bitcast_convert_type(col, _I32))
            elif col.dtype == jnp.int64:
                irows.append(_hi32(col))
                irows.append(_lo32(col))
            else:
                irows.append(col.astype(_I32))
        out = {"i": jnp.stack(irows, axis=0)}
        if frows:
            out["f"] = jnp.stack(frows, axis=0)
        return carry, out


def pow2_at_least(n: int, lo: int = 8) -> int:
    return max(lo, 1 << max(0, math.ceil(math.log2(max(1, n)))))


def _hi32(v):
    return lax.shift_right_arithmetic(v, jnp.int64(32)).astype(_I32)


def _lo32(v):
    return lax.bitcast_convert_type(
        v.astype(jnp.uint64).astype(jnp.uint32), _I32)


def _join64(hi, lo):
    return (hi.astype(jnp.int64) << jnp.int64(32)) | \
        lax.bitcast_convert_type(lo, jnp.uint32).astype(jnp.int64)


def join64_np(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return (hi.astype(np.int64) << 32) | lo.view(np.uint32).astype(np.int64)
