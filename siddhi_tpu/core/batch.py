"""Columnar event micro-batches (struct-of-arrays) + host-side accumulator.

The TPU replacement for the reference's pooled linked-list event chunks
(reference: core:event/ComplexEventChunk.java:29, StreamEventPool.java:26):
instead of borrowing pooled row objects per event, the host accumulates rows
into per-attribute numpy buffers; `freeze()` yields an immutable EventBatch
whose columns ship to device as one contiguous array each.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from .schema import STRING_CODE_DTYPE, TIMESTAMP_DTYPE, StreamSchema, StringTable, dtype_of
from ..query.ast import AttrType


@dataclass
class EventBatch:
    """One micro-batch of events for a single stream. Immutable."""
    schema: StreamSchema
    timestamps: np.ndarray            # (n,) int64 ms
    columns: dict                     # name -> (n,) ndarray
    n: int
    # global arrival sequence numbers (n,) int64 — preserve cross-stream
    # ordering for patterns/sequences/joins (the reference gets this for free
    # from synchronous per-event dispatch)
    seqs: Optional[np.ndarray] = None
    # validity: name -> (n,) bool where True marks a NULL value (outer-join
    # misses, absent-pattern refs).  None when the batch has no nulls; device
    # kernels see the neutral fill value, host decode restores real None.
    nulls: Optional[dict] = None

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def rows(self, strings: Optional[StringTable] = None) -> list[tuple]:
        """Decode back to row tuples (strings decoded if table given).

        Columnar decode (ndarray.tolist + zip) and memoized: N subscribed
        host plans share one decode per batch instead of N — the dominant
        cost of the 1k-concurrent-query host path."""
        cache = self.__dict__.get("_rows_cache")
        if cache is not None and cache[0] is strings:
            return cache[1]
        nulls = self.nulls or {}
        cols = []
        for a in self.schema.attributes:
            arr = self.columns[a.name]
            if a.type == AttrType.STRING and strings is not None:
                dec = strings._to_str
                col = [dec[c] if 0 <= c < len(dec) else None
                       for c in arr.tolist()]
            else:
                col = arr.tolist()      # C-speed; yields python scalars
            a_nulls = nulls.get(a.name)
            if a_nulls is not None and a_nulls.any():
                col = [None if nn else v
                       for v, nn in zip(col, a_nulls.tolist())]
            cols.append(col)
        out = list(zip(*cols)) if cols else [()] * self.n
        self.__dict__["_rows_cache"] = (strings, out)
        return out

    # -- shared device-upload pads ---------------------------------------
    #
    # Device plans pad columns to a pow2 grid T before upload.  The pads
    # are memoized per batch so N plans subscribed to one stream build
    # each (column, T) pad ONCE per flush instead of N times, and the
    # backing buffers come from a rotating PadPool (see pipeline.py) so
    # steady-state flushes stop allocating.

    def padded(self, name: str, T: int, dtype=None, pool=None,
               min_slots: int = 2) -> np.ndarray:
        """Zero-tail (T,) pad of a column (memoized per (name, T, dtype)).
        Callers must treat the result as read-only — it is shared across
        every plan subscribed to this batch."""
        cache = self.__dict__.setdefault("_pad_cache", {})
        dt = np.dtype(dtype) if dtype is not None else None
        key = (name, T, dt)
        hit = cache.get(key)
        if hit is not None:
            buf, poolkey = hit
            if pool is not None and poolkey is not None:
                # a later caller may need a deeper rotation (per-plan
                # pipeline depths): the memo must not swallow its request
                pool.reserve(poolkey, T, buf.dtype, min_slots)
            return buf
        col = self.timestamps if name == "__timestamp__" \
            else self.columns[name]
        if dt is not None and col.dtype != dt:
            col = col.astype(dt)
        poolkey = (self.schema.id, name, T, col.dtype) \
            if pool is not None else None
        buf = self._pad_buf(poolkey, T, col.dtype, pool, min_slots)
        buf[:self.n] = col
        cache[key] = (buf, poolkey)
        return buf

    def padded_ts_offsets(self, T: int, pool=None, min_slots: int = 2):
        """(offsets, base): timestamps as a zero-tail (T,) offset array
        from an int64 base (i32 normally, i64 for rare wide batches) —
        the compact upload form device window plans consume.  Memoized
        per (T,) like padded()."""
        cache = self.__dict__.setdefault("_ts_off_cache", {})
        hit = cache.get(T)
        if hit is not None:
            buf, base, poolkey = hit
            if pool is not None and poolkey is not None:
                pool.reserve(poolkey, T, buf.dtype, min_slots)
            return buf, base
        base = int(self.timestamps[0]) if self.n else 0
        off = self.timestamps - base
        wide = bool(self.n and (off.max() >= 2**31 or off.min() < -2**31))
        dt = np.dtype(np.int64 if wide else np.int32)
        poolkey = (self.schema.id, "__ts_off__", T, dt) \
            if pool is not None else None
        buf = self._pad_buf(poolkey, T, dt, pool, min_slots)
        buf[:self.n] = off
        cache[T] = (buf, base, poolkey)
        return buf, base

    def _pad_buf(self, key, T: int, dt, pool, min_slots: int) -> np.ndarray:
        if pool is None:
            return np.zeros(T, dtype=dt)
        buf = pool.take(key, T, dt, min_slots)
        buf[self.n:] = 0        # recycled buffer: stale tail from a
        return buf              # previous (larger) flush must clear

    @classmethod
    def empty(cls, schema: StreamSchema) -> "EventBatch":
        cols = {a.name: np.empty(0, dtype=dtype_of(a.type)) for a in schema.attributes}
        return cls(schema, np.empty(0, dtype=TIMESTAMP_DTYPE), cols, 0)

    @classmethod
    def from_rows(cls, schema: StreamSchema, rows: Sequence[tuple],
                  timestamps: Sequence[int], strings: StringTable) -> "EventBatch":
        b = BatchBuilder(schema, strings)
        for ts, row in zip(timestamps, rows):
            b.append(ts, row)
        return b.freeze()


def rows_of_columns(schema: StreamSchema, timestamps, columns: dict,
                    strings: Optional[StringTable] = None) -> list:
    """Columnar arrays -> [(ts_ms, row_tuple), ...] with string codes
    decoded back to str.  The serving plane's shed/capture path: a
    frame that admission drops is decoded ONCE here so the ErrorStore
    entry is replayable through the normal row ingest (`rt.send`)."""
    cols = []
    for a in schema.attributes:
        arr = np.asarray(columns[a.name])
        if a.type == AttrType.STRING and strings is not None \
                and arr.dtype.kind in "iu":
            dec = strings._to_str
            cols.append([dec[c] if 0 <= c < len(dec) else None
                         for c in arr.tolist()])
        else:
            cols.append(arr.tolist())
    ts = np.asarray(timestamps).tolist()
    return list(zip(ts, (tuple(r) for r in zip(*cols)))) if cols else []


class BatchBuilder:
    """Mutable row accumulator -> EventBatch.  The per-stream ingest buffer
    behind InputHandler (analog of the junction's ring slot filling,
    reference: core:stream/StreamJunction.java:150-275)."""

    def __init__(self, schema: StreamSchema, strings: StringTable,
                 capacity: int = 1024):
        self.schema = schema
        self.strings = strings
        self.capacity = capacity
        self._ts: list[int] = []
        self._seqs: list[int] = []
        self._cols: dict[str, list] = {a.name: [] for a in schema.attributes}
        self._nulls: dict[str, list] = {}   # name -> [row indices], lazily
        # already-columnar segments (the send_batch fast path): ordered
        # (ts, cols, seqs, nulls, n) tuples interleaved with row appends;
        # freeze() concatenates in arrival order, and a single segment
        # with no row leftovers freezes zero-copy
        self._pieces: list = []

    def __len__(self) -> int:
        return len(self._ts) + sum(p[4] for p in self._pieces)

    @property
    def full(self) -> bool:
        return len(self._ts) >= self.capacity

    def append(self, timestamp: int, row: Sequence[Any],
               seq: Optional[int] = None) -> None:
        attrs = self.schema.attributes
        if len(row) != len(attrs):
            raise ValueError(
                f"stream {self.schema.id!r} expects {len(attrs)} attributes "
                f"{self.schema.names}, got {len(row)}: {row!r}")
        self._ts.append(int(timestamp))
        self._seqs.append(seq if seq is not None else len(self._seqs))
        for a, v in zip(attrs, row):
            if v is None:
                # null value (outer-join miss, absent-pattern ref): typed
                # columns carry a neutral fill; the null mask preserves
                # true None through host decode (reference emits null)
                self._nulls.setdefault(a.name, []).append(len(self._ts) - 1)
            if a.type == AttrType.STRING:
                v = self.strings.encode(v)
            elif v is None:
                v = (float("nan") if a.type in (AttrType.FLOAT, AttrType.DOUBLE)
                     else False if a.type == AttrType.BOOL
                     else 0 if a.type in (AttrType.INT, AttrType.LONG)
                     else None)
            self._cols[a.name].append(v)

    def append_columnar(self, timestamps: np.ndarray, columns: dict,
                        seqs: Optional[np.ndarray] = None,
                        nulls: Optional[dict] = None) -> None:
        """Adopt an already-columnar segment without the per-row Python
        append: `columns` must map every schema attribute to an (n,)
        array in its device dtype (strings pre-encoded to int32 codes)
        — the caller (runtime.send_columnar) does the coercion.  Arrays
        are adopted as-is (no copy); callers must not mutate them."""
        n = int(len(timestamps))
        if n == 0:
            return
        self._seal_rows()
        self._pieces.append((timestamps, columns, seqs, nulls, n))

    def _seal_rows(self) -> None:
        """Convert buffered row appends into a columnar piece."""
        n = len(self._ts)
        if not n:
            return
        cols = {}
        for a in self.schema.attributes:
            dt = dtype_of(a.type)
            if dt == np.dtype(object):
                cols[a.name] = np.asarray(self._cols[a.name], dtype=object)
            else:
                cols[a.name] = np.asarray(self._cols[a.name], dtype=dt)
        nulls = None
        if self._nulls:
            nulls = {}
            for name, idxs in self._nulls.items():
                m = np.zeros(n, dtype=bool)
                m[idxs] = True
                nulls[name] = m
        self._pieces.append((np.asarray(self._ts, dtype=TIMESTAMP_DTYPE),
                             cols, np.asarray(self._seqs, dtype=np.int64),
                             nulls, n))
        self._ts = []
        self._seqs = []
        self._cols = {a.name: [] for a in self.schema.attributes}
        self._nulls = {}

    def freeze_and_clear(self) -> EventBatch:
        b = self.freeze()
        self._ts = []
        self._seqs = []
        self._cols = {a.name: [] for a in self.schema.attributes}
        self._nulls = {}
        self._pieces = []
        return b

    def freeze(self) -> EventBatch:
        self._seal_rows()
        pieces = self._pieces
        if not pieces:
            b = EventBatch.empty(self.schema)
            b.seqs = np.empty(0, dtype=np.int64)
            return b
        if len(pieces) == 1:                     # fast path: zero-copy
            ts, cols, seqs, nulls, n = pieces[0]
            if seqs is None:
                seqs = np.arange(n, dtype=np.int64)
            return EventBatch(self.schema, ts, cols, n, seqs, nulls)
        n = sum(p[4] for p in pieces)
        ts = np.concatenate([p[0] for p in pieces])
        seqs = np.concatenate(
            [p[2] if p[2] is not None else np.arange(p[4], dtype=np.int64)
             for p in pieces])
        cols = {a.name: np.concatenate([p[1][a.name] for p in pieces])
                for a in self.schema.attributes}
        nulls = None
        if any(p[3] for p in pieces):
            nulls = {}
            names = {nm for p in pieces if p[3] for nm in p[3]}
            for nm in names:
                nulls[nm] = np.concatenate(
                    [(p[3] or {}).get(nm, np.zeros(p[4], bool))
                     for p in pieces])
        return EventBatch(self.schema, ts, cols, n, seqs, nulls)
