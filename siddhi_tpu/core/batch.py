"""Columnar event micro-batches (struct-of-arrays) + host-side accumulator.

The TPU replacement for the reference's pooled linked-list event chunks
(reference: core:event/ComplexEventChunk.java:29, StreamEventPool.java:26):
instead of borrowing pooled row objects per event, the host accumulates rows
into per-attribute numpy buffers; `freeze()` yields an immutable EventBatch
whose columns ship to device as one contiguous array each.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from .schema import STRING_CODE_DTYPE, TIMESTAMP_DTYPE, StreamSchema, StringTable, dtype_of
from ..query.ast import AttrType


@dataclass
class EventBatch:
    """One micro-batch of events for a single stream. Immutable."""
    schema: StreamSchema
    timestamps: np.ndarray            # (n,) int64 ms
    columns: dict                     # name -> (n,) ndarray
    n: int
    # global arrival sequence numbers (n,) int64 — preserve cross-stream
    # ordering for patterns/sequences/joins (the reference gets this for free
    # from synchronous per-event dispatch)
    seqs: Optional[np.ndarray] = None
    # validity: name -> (n,) bool where True marks a NULL value (outer-join
    # misses, absent-pattern refs).  None when the batch has no nulls; device
    # kernels see the neutral fill value, host decode restores real None.
    nulls: Optional[dict] = None

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def rows(self, strings: Optional[StringTable] = None) -> list[tuple]:
        """Decode back to row tuples (strings decoded if table given).

        Columnar decode (ndarray.tolist + zip) and memoized: N subscribed
        host plans share one decode per batch instead of N — the dominant
        cost of the 1k-concurrent-query host path."""
        cache = self.__dict__.get("_rows_cache")
        if cache is not None and cache[0] is strings:
            return cache[1]
        nulls = self.nulls or {}
        cols = []
        for a in self.schema.attributes:
            arr = self.columns[a.name]
            if a.type == AttrType.STRING and strings is not None:
                dec = strings._to_str
                col = [dec[c] if 0 <= c < len(dec) else None
                       for c in arr.tolist()]
            else:
                col = arr.tolist()      # C-speed; yields python scalars
            a_nulls = nulls.get(a.name)
            if a_nulls is not None and a_nulls.any():
                col = [None if nn else v
                       for v, nn in zip(col, a_nulls.tolist())]
            cols.append(col)
        out = list(zip(*cols)) if cols else [()] * self.n
        self.__dict__["_rows_cache"] = (strings, out)
        return out

    @classmethod
    def empty(cls, schema: StreamSchema) -> "EventBatch":
        cols = {a.name: np.empty(0, dtype=dtype_of(a.type)) for a in schema.attributes}
        return cls(schema, np.empty(0, dtype=TIMESTAMP_DTYPE), cols, 0)

    @classmethod
    def from_rows(cls, schema: StreamSchema, rows: Sequence[tuple],
                  timestamps: Sequence[int], strings: StringTable) -> "EventBatch":
        b = BatchBuilder(schema, strings)
        for ts, row in zip(timestamps, rows):
            b.append(ts, row)
        return b.freeze()


class BatchBuilder:
    """Mutable row accumulator -> EventBatch.  The per-stream ingest buffer
    behind InputHandler (analog of the junction's ring slot filling,
    reference: core:stream/StreamJunction.java:150-275)."""

    def __init__(self, schema: StreamSchema, strings: StringTable,
                 capacity: int = 1024):
        self.schema = schema
        self.strings = strings
        self.capacity = capacity
        self._ts: list[int] = []
        self._seqs: list[int] = []
        self._cols: dict[str, list] = {a.name: [] for a in schema.attributes}
        self._nulls: dict[str, list] = {}   # name -> [row indices], lazily

    def __len__(self) -> int:
        return len(self._ts)

    @property
    def full(self) -> bool:
        return len(self._ts) >= self.capacity

    def append(self, timestamp: int, row: Sequence[Any],
               seq: Optional[int] = None) -> None:
        attrs = self.schema.attributes
        if len(row) != len(attrs):
            raise ValueError(
                f"stream {self.schema.id!r} expects {len(attrs)} attributes "
                f"{self.schema.names}, got {len(row)}: {row!r}")
        self._ts.append(int(timestamp))
        self._seqs.append(seq if seq is not None else len(self._seqs))
        for a, v in zip(attrs, row):
            if v is None:
                # null value (outer-join miss, absent-pattern ref): typed
                # columns carry a neutral fill; the null mask preserves
                # true None through host decode (reference emits null)
                self._nulls.setdefault(a.name, []).append(len(self._ts) - 1)
            if a.type == AttrType.STRING:
                v = self.strings.encode(v)
            elif v is None:
                v = (float("nan") if a.type in (AttrType.FLOAT, AttrType.DOUBLE)
                     else False if a.type == AttrType.BOOL
                     else 0 if a.type in (AttrType.INT, AttrType.LONG)
                     else None)
            self._cols[a.name].append(v)

    def freeze_and_clear(self) -> EventBatch:
        b = self.freeze()
        self._ts = []
        self._seqs = []
        self._cols = {a.name: [] for a in self.schema.attributes}
        self._nulls = {}
        return b

    def freeze(self) -> EventBatch:
        n = len(self._ts)
        cols = {}
        for a in self.schema.attributes:
            dt = dtype_of(a.type)
            if dt == np.dtype(object):
                cols[a.name] = np.asarray(self._cols[a.name], dtype=object)
            else:
                cols[a.name] = np.asarray(self._cols[a.name], dtype=dt)
        nulls = None
        if self._nulls:
            nulls = {}
            for name, idxs in self._nulls.items():
                m = np.zeros(n, dtype=bool)
                m[idxs] = True
                nulls[name] = m
        return EventBatch(self.schema, np.asarray(self._ts, dtype=TIMESTAMP_DTYPE),
                          cols, n, np.asarray(self._seqs, dtype=np.int64), nulls)
