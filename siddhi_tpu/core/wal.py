"""Write-ahead log of admitted columnar frames — the durability half of
exactly-once serving (docs/RELIABILITY.md "Durability & exactly-once
recovery").

Admitted frames are already CRC'd, self-contained, and replayable on
the wire (net/frame.py); this module makes them SURVIVABLE: every frame
the runtime admits (one record per frozen micro-batch, row-path and
columnar ingest alike) appends to a segmented, CRC-per-record,
append-only log BEFORE it is processed.  Snapshot revisions record the
per-stream durable watermark (the last frame seq the snapshot's state
already reflects), so crash recovery is:

    restore newest loadable snapshot
      -> replay the WAL suffix, skipping frames at-or-below the
         watermark
      -> zero duplicates, zero loss

Record layout (little-endian; one record per admitted frame):

    offset  size  field
    0       2     magic   0x4C57 ("WL")
    2       1     version (1)
    3       1     type    (1 = FRAME)
    4       4     payload length N
    8       4     CRC32 of payload (zlib.crc32)
    12      N     payload:
                    u64 per-stream frame seq
                    u16 stream-id utf-8 length + bytes
                    pickle({"ts": int64 array, "cols": {name: array}})

String columns are stored DECODED (object arrays of str) so a record is
self-contained: replay re-encodes through the restored StringTable in
arrival order, reproducing the original dictionary codes byte-for-byte.

Segments (`wal-<n>.seg` under the WAL directory) seal at
`segment_bytes`; a snapshot barrier rotates to a fresh segment and
truncates every sealed segment whose frames are all at-or-below the
snapshot's watermark (per-stream seqs are monotonic and segments are
ordered, so whole-segment deletion is exact).

Corruption policy — the restore_chain philosophy (persistence.py)
applied to a log: replay applies the longest VALID PREFIX.  A torn tail
(crash mid-append), a CRC mismatch, a bad magic, or a missing segment
number each end the replay there, counted in `corrupt_skipped`; opening
for append heals the log back to that prefix (torn tail truncated,
unreachable later segments quarantined) so the next crash's replay
never dead-ends at an old scar.

Sync policies (`@app:durability('off'|'batch'|'fsync')`):

    off    no WAL at all (the pre-durability engine)
    batch  append + OS-buffer flush per frame; fsync at barriers
           (snapshot, PING/ACK, rotate, close).  Survives process
           kill; an OS crash can lose the post-barrier tail.
    fsync  fsync after EVERY append before the ingest call returns —
           an ACK'd frame survives power loss.

Fault-injection points (faults.FaultInjector): `wal.append` (armed
mid-record, after the first half of the bytes reached the OS — a
SIGKILL there leaves a torn tail; a raised fault self-heals the file
and propagates so the net feed path captures the frame whole),
`wal.fsync`, and `wal.truncate`.
"""
from __future__ import annotations

import os
import pickle
import re
import struct
import threading
import time
import zlib
from typing import Callable, Iterator, Optional

import numpy as np

from ..utils.locks import new_rlock
from .telemetry import Histogram

MAGIC = 0x4C57
VERSION = 1
FRAME = 1
HEADER = struct.Struct("<HBBII")        # magic, version, type, len, crc
SEG_RE = re.compile(r"^wal-(\d{8})\.seg$")
GEN_FILE = "generation"                 # replication fencing token

POLICIES = ("off", "batch", "fsync")


class WalError(Exception):
    """A WAL append/scan failure that must not be silently swallowed."""


def read_generation(directory: str) -> int:
    """The log directory's replication fencing token (0 = never
    fenced).  A promoted standby writes a HIGHER generation; remote
    appends stamped with an older one are rejected loudly."""
    try:
        with open(os.path.join(directory, GEN_FILE), "r") as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def write_generation(directory: str, generation: int) -> None:
    """Persist the fencing token durably (atomic publish + fsync): a
    promote that crashed mid-write must not resurrect the deposed
    generation."""
    path = os.path.join(directory, GEN_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(int(generation)))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:                     # platform without dir fsync
        pass


def _string_delta(codes: np.ndarray, strings) -> dict:
    """{code: str} for the DISTINCT codes of one string column — the
    self-containment delta logged beside the raw code array, so a
    record replays into a FRESH StringTable without pickling a
    per-event object array (frames repeat a few symbols thousands of
    times; the dictionary is consulted once per distinct code)."""
    table = strings._to_str
    return {int(c): (table[c] if 0 <= c < len(table) else None)
            for c in np.unique(np.asarray(codes)).tolist()}


def _apply_string_delta(codes: np.ndarray, delta: dict) -> np.ndarray:
    """codes + logged {code: str} -> object array of str/None for
    re-encoding through the (possibly different) live StringTable."""
    arr = np.asarray(codes)
    lut = np.empty((max(delta) + 1) if delta else 1, dtype=object)
    for c, s in delta.items():
        lut[c] = s
    return lut[arr]


class WriteAheadLog:
    """One app's segmented frame log.  Thread-safe: appends are already
    serialized by the runtime lock, but barriers/scrapes arrive from
    scheduler and connection threads."""

    def __init__(self, directory: str, policy: str = "batch",
                 segment_bytes: int = 8 << 20,
                 inject: Optional[Callable[[str, str], None]] = None,
                 armed: Optional[Callable[[], bool]] = None,
                 on_stall: Optional[Callable[[float], None]] = None,
                 stall_budget_s: Optional[float] = None):
        if policy not in POLICIES or policy == "off":
            raise WalError(f"unknown WAL sync policy {policy!r} "
                           f"(have: batch | fsync)")
        self.dir = directory
        self.policy = policy
        self.segment_bytes = int(segment_bytes)
        self.inject = inject or (lambda point, detail="": None)
        # `armed()` true -> a fault injector may fire: append takes the
        # split-write path (flush + inject between the record's halves,
        # so a SIGKILL there leaves a deterministic torn tail).  The
        # unarmed fast path is ONE buffered write — the per-frame cost
        # the <=15% 'batch' overhead budget is built on.
        self.armed = armed or (lambda: False)
        # barrier-stall observability (core/tracing.py trigger registry):
        # a durability barrier slower than the budget fires `on_stall`
        # AFTER the lock is released — the callback (a trace-dump
        # trigger) must never run under the WAL lock
        self.on_stall = on_stall
        self.stall_budget_s = stall_budget_s if stall_budget_s is not None \
            else float(os.environ.get("SIDDHI_WAL_STALL_S", "0.25"))
        self._lock = new_rlock("WriteAheadLog._lock")
        self._f = None                  # open segment file object
        self._seg_no = 0
        self._seg_len = 0
        # per-stream monotonic frame seq, assigned at admission (freeze)
        self.seqs: dict = {}
        # per-open-segment max seq per stream; sealed segments keep
        # theirs in _sealed so truncation never has to rescan files
        self._seg_max: dict = {}
        self._sealed: list = []         # [(seg_no, {stream: max_seq})]
        # counters (statistics()["durability"] + siddhi_tpu_wal_*)
        self.appended_frames = 0
        self.appended_events = 0
        self.appended_bytes = 0
        self.fsyncs = 0
        self.fsync_hist = Histogram()
        self.corrupt_skipped = 0        # records/segments dropped by scans
        self.truncated_segments = 0
        self._unsynced = False
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            self._open_for_append_locked()

    # -- segment bookkeeping -------------------------------------------------

    def _seg_path(self, n: int) -> str:
        return os.path.join(self.dir, f"wal-{n:08d}.seg")

    def _segments(self) -> list:
        """Existing segment numbers, ascending."""
        out = []
        for name in os.listdir(self.dir):
            m = SEG_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _open_for_append_locked(self) -> None:
        """Scan the existing log once: recover the per-stream seq
        counters and per-segment maxima, heal the valid prefix (truncate
        the torn tail, quarantine segments unreachable past a corrupt
        record — replay stops at the first scar, so anything after it
        could never be applied again), and open a FRESH segment."""
        segs = self._segments()
        stop_at: Optional[int] = None   # first unreadable segment number
        for i, n in enumerate(segs):
            if stop_at is not None:
                # unreachable: replay can never pass the scar before it
                os.replace(self._seg_path(n),
                           self._seg_path(n) + ".quarantined")
                self.corrupt_skipped += 1
                continue
            if i and n != segs[i - 1] + 1:
                # numbering gap (a deleted/lost segment): same policy
                self.corrupt_skipped += 1
                stop_at = n
                os.replace(self._seg_path(n),
                           self._seg_path(n) + ".quarantined")
                continue
            maxima, valid_end, clean = self._scan_segment_locked(
                n, apply=True)
            self._sealed.append((n, maxima))
            if not clean:
                # torn tail / CRC scar: heal the file back to the prefix
                with open(self._seg_path(n), "r+b") as f:
                    f.truncate(valid_end)
                self.corrupt_skipped += 1
                stop_at = n + 1
        # the fresh segment numbers CONTIGUOUSLY after the kept prefix —
        # numbering from segs[-1]+1 after a quarantine would leave a
        # permanent gap that every later open reads as corruption,
        # quarantining (and losing) everything appended after the heal
        last_kept = self._sealed[-1][0] if self._sealed else 0
        self._seg_no = last_kept + 1
        self._f = open(self._seg_path(self._seg_no), "ab")
        self._seg_len = 0
        self._seg_max = {}

    def _scan_segment_locked(self, n: int, apply: bool = False):
        """-> ({stream: max_seq}, valid_end_offset, clean).  `apply`
        folds the maxima into self.seqs (open-time recovery of the
        counters)."""
        maxima: dict = {}
        off = 0
        clean = True
        try:
            with open(self._seg_path(n), "rb") as f:
                data = f.read()
        except OSError:
            return maxima, 0, False
        while True:
            rec = self._parse_record(data, off)
            if rec is None:
                clean = off == len(data)
                break
            stream, seq, _body, end = rec
            maxima[stream] = max(maxima.get(stream, 0), seq)
            off = end
        if apply:
            for sid, s in maxima.items():
                self.seqs[sid] = max(self.seqs.get(sid, 0), s)
        return maxima, off, clean

    @staticmethod
    def _parse_record(data: bytes, off: int):
        """One record at `off` -> (stream, seq, pickled_body_bytes,
        end_off), or None when truncated/corrupt (the caller decides
        whether that is a clean EOF)."""
        if len(data) - off < HEADER.size:
            return None
        magic, ver, rtype, n, crc = HEADER.unpack_from(data, off)
        if magic != MAGIC or ver != VERSION or rtype != FRAME:
            return None
        start = off + HEADER.size
        if start + n > len(data):
            return None                 # torn tail
        payload = data[start:start + n]
        if crc != (zlib.crc32(payload) & 0xFFFFFFFF):
            return None
        (seq,) = struct.unpack_from("<Q", payload, 0)
        (slen,) = struct.unpack_from("<H", payload, 8)
        stream = payload[10:10 + slen].decode()
        return stream, seq, payload[10 + slen:], start + n

    # -- append --------------------------------------------------------------

    def append(self, stream_id: str, timestamps: np.ndarray,
               columns: dict, strings, schema=None) -> int:
        """Log one admitted frame; returns its per-stream seq.  String
        columns stay as their int32 code arrays; the record carries a
        {code: str} delta for the frame's DISTINCT codes, so it is
        self-contained without pickling a per-event object array.
        Raises on any write failure AFTER restoring the file to the
        previous record boundary — a failed append never leaves a scar
        the next append would bury."""
        from ..query.ast import AttrType
        cols = {}
        strs = {}
        str_names = ()
        if schema is not None:
            str_names = {a.name for a in schema.attributes
                         if a.type == AttrType.STRING}
        for name, arr in columns.items():
            cols[name] = np.asarray(arr)
            if name in str_names:
                strs[name] = _string_delta(arr, strings)
        body = pickle.dumps(
            {"ts": np.asarray(timestamps, dtype=np.int64), "cols": cols,
             "strs": strs},
            protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            seq = self.seqs.get(stream_id, 0) + 1
            sid = stream_id.encode()
            payload = struct.pack("<QH", seq, len(sid)) + sid + body
            blob = HEADER.pack(MAGIC, VERSION, FRAME, len(payload),
                               zlib.crc32(payload) & 0xFFFFFFFF) + payload
            base = self._seg_len        # tracked: tell() is a syscall
            try:
                if self.armed():
                    # split write with the injection point between the
                    # halves, first half flushed to the OS: an armed
                    # `wal.append` fault (or a SIGKILL there) leaves a
                    # deterministic torn record for the recovery scan
                    half = len(blob) // 2
                    self._f.write(blob[:half])
                    self._f.flush()
                    self.inject("wal.append", stream_id)
                    self._f.write(blob[half:])
                else:
                    self._f.write(blob)
                self._f.flush()
                if self.policy == "fsync":
                    self._fsync_locked()
                else:
                    self._unsynced = True
            except BaseException:
                # self-heal: the partial record must not poison the log
                try:
                    self._f.truncate(base)
                    self._f.flush()
                except OSError:
                    pass
                raise
            self.seqs[stream_id] = seq
            self._seg_max[stream_id] = seq
            self._seg_len += len(blob)
            self.appended_frames += 1
            self.appended_events += int(np.asarray(timestamps).shape[0])
            self.appended_bytes += len(blob)
            if self._seg_len >= self.segment_bytes:
                self._rotate_locked()
            return seq

    def append_raw(self, record: bytes) -> tuple:
        """Append one REPLICATED record verbatim (already framed and
        CRC'd by the primary — the standby's log stays byte-identical).
        Returns (stream, seq, applied): seq at-or-below the current
        counter is an idempotent re-ship (applied=False, e.g. a
        reconnect re-sending from the last ack); seq exactly current+1
        appends; anything further ahead is a replication GAP — the
        shipper must catch the standby up through a snapshot first —
        and raises WalError loudly."""
        rec = self._parse_record(record, 0)
        if rec is None or rec[3] != len(record):
            raise WalError("corrupt replicated record (CRC/framing)")
        stream, seq, body, _end = rec
        with self._lock:
            cur = self.seqs.get(stream, 0)
            if seq <= cur:
                return stream, seq, False
            if seq != cur + 1:
                raise WalError(
                    f"replication gap on stream {stream!r}: got seq "
                    f"{seq}, expected {cur + 1} (snapshot catch-up "
                    f"required)")
            base = self._seg_len
            try:
                self._f.write(record)
                self._f.flush()
                if self.policy == "fsync":
                    self._fsync_locked()
                else:
                    self._unsynced = True
            except BaseException:
                try:
                    self._f.truncate(base)
                    self._f.flush()
                except OSError:
                    pass
                raise
            self.seqs[stream] = seq
            self._seg_max[stream] = seq
            self._seg_len += len(record)
            self.appended_frames += 1
            self.appended_bytes += len(record)
            try:
                self.appended_events += int(
                    np.asarray(pickle.loads(body)["ts"]).shape[0])
            except Exception:
                pass                    # counters only; the bytes landed
            if self._seg_len >= self.segment_bytes:
                self._rotate_locked()
            return stream, seq, True

    # -- replication fencing -------------------------------------------------

    def generation(self) -> int:
        """This log's persisted fencing token (see read_generation)."""
        return read_generation(self.dir)

    def fence(self, minimum: int = 0) -> int:
        """Bump the fencing token past both the local value and
        `minimum` (the highest generation seen from a peer) and persist
        it durably.  Returns the new generation — every replicated
        record the deposed generation ships after this is rejected."""
        with self._lock:
            gen = max(self.generation(), int(minimum)) + 1
            write_generation(self.dir, gen)
            return gen

    def _fsync_locked(self) -> None:
        self.inject("wal.fsync", "")
        t0 = time.perf_counter()
        # blocking appenders until the disk confirms is the sync
        # policy's whole point (docs/RELIABILITY.md): appends must not
        # interleave with the barrier, so the fsync sits under the lock
        # lint: allow (fsync under the WAL lock IS the durability contract)
        os.fsync(self._f.fileno())
        self.fsync_hist.record(time.perf_counter() - t0)
        self.fsyncs += 1
        self._unsynced = False

    def barrier(self) -> None:
        """Make everything appended so far durable (the PING/ACK and
        snapshot barrier).  Cheap when nothing new was appended.  A
        barrier slower than `stall_budget_s` reports through `on_stall`
        (outside the lock) — the ACK path is blocked exactly that long,
        which is the latency the trigger's trace dump attributes."""
        t0 = time.perf_counter()
        with self._lock:
            if self._f is None or not self._unsynced:
                return
            self._f.flush()
            self._fsync_locked()
        dt = time.perf_counter() - t0
        if self.on_stall is not None and dt > self.stall_budget_s:
            try:
                self.on_stall(dt)
            except Exception:
                # the observability hook must never fail a durability
                # barrier that already succeeded
                pass

    # -- rotation / truncation -----------------------------------------------

    def _rotate_locked(self) -> None:
        self._f.flush()
        self._fsync_locked()
        self._f.close()
        self._sealed.append((self._seg_no, self._seg_max))
        self._seg_no += 1
        self._seg_max = {}
        self._seg_len = 0
        self._f = open(self._seg_path(self._seg_no), "ab")

    def rotate(self) -> None:
        """Seal the open segment and start a fresh one (called at
        snapshot barriers so truncation can drop whole sealed
        segments)."""
        with self._lock:
            if self._seg_len:
                self._rotate_locked()

    def truncate(self, watermark: dict) -> int:
        """Delete sealed segments whose every frame is at-or-below the
        per-stream `watermark` (a snapshot's durable point).  Returns
        the number of segments removed."""
        removed = 0
        with self._lock:
            keep = []
            for seg_no, maxima in self._sealed:
                disposable = maxima and all(
                    s <= watermark.get(sid, 0) for sid, s in maxima.items())
                if not maxima:
                    disposable = True   # empty segment: nothing to lose
                if disposable:
                    self.inject("wal.truncate", str(seg_no))
                    try:
                        os.remove(self._seg_path(seg_no))
                    except FileNotFoundError:
                        pass
                    removed += 1
                    self.truncated_segments += 1
                else:
                    keep.append((seg_no, maxima))
            self._sealed = keep
        return removed

    def floor_seqs(self, wm: Optional[dict]) -> None:
        """Raise per-stream seq counters to at least `wm` — the
        restored snapshot watermark (or the previous generation's
        counters) after snapshot-barrier truncation emptied the log:
        the open-scan alone would restart seqs at 1, numbering new
        frames at-or-below the watermark so the NEXT recovery's skip
        would silently swallow them."""
        with self._lock:
            for sid, s in (wm or {}).items():
                if int(s) > self.seqs.get(sid, 0):
                    self.seqs[sid] = int(s)

    def watermark(self) -> dict:
        """Per-stream last-appended frame seq — what a snapshot taken
        NOW (after a flush) already reflects."""
        with self._lock:
            return dict(self.seqs)

    # -- replay --------------------------------------------------------------

    def replay(self) -> Iterator[tuple]:
        """Yield (stream_id, seq, timestamps, columns) for the longest
        valid prefix of the log, in append order.  Stops — counting
        `corrupt_skipped` — at the first torn/corrupt record or missing
        segment: per-stream seqs are monotonic and frames must apply in
        order, so nothing past a scar can be applied exactly-once."""
        with self._lock:
            if self._unsynced:
                self.barrier()
            segs = self._segments()

        def scar():                     # the RLock guards the counter
            with self._lock:            # against scrapes; replay itself
                self.corrupt_skipped += 1       # is single-consumer

        prev = None
        for n in segs:
            if prev is not None and n != prev + 1:
                scar()
                return                  # missing segment: stop here
            prev = n
            try:
                with open(self._seg_path(n), "rb") as f:
                    data = f.read()
            except OSError:
                scar()
                return
            off = 0
            while True:
                rec = self._parse_record(data, off)
                if rec is None:
                    if off != len(data):
                        scar()
                        return          # torn/corrupt: stop the replay
                    break
                stream, seq, body, off = rec
                rd = pickle.loads(body)
                cols = rd["cols"]
                for name, delta in (rd.get("strs") or {}).items():
                    # codes -> str via the record's own delta, so the
                    # replay re-encodes through the LIVE StringTable
                    cols[name] = _apply_string_delta(cols[name], delta)
                yield stream, seq, rd["ts"], cols

    # -- tailing (replication) -----------------------------------------------

    def tail(self, watermark: Optional[dict] = None) -> "WalTail":
        """A shipper's cursor over this log: raw records strictly after
        the per-stream `watermark`, in append order.  See WalTail for
        the gap/scar semantics."""
        return WalTail(self, watermark)

    # -- lifecycle / telemetry -----------------------------------------------

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                if self._unsynced:
                    self._fsync_locked()
                self._f.close()
                self._f = None

    def metrics(self) -> dict:
        with self._lock:
            m = {"policy": self.policy,
                 "segments": len(self._sealed) + 1,
                 "open_segment_bytes": self._seg_len,
                 "appended_frames": self.appended_frames,
                 "appended_events": self.appended_events,
                 "appended_bytes": self.appended_bytes,
                 "fsyncs": self.fsyncs,
                 "corrupt_skipped": self.corrupt_skipped,
                 "truncated_segments": self.truncated_segments,
                 "last_seq": dict(self.seqs)}
            if self.fsync_hist.count:
                fs = {"batches": self.fsync_hist.count,
                      "seconds": self.fsync_hist.sum}
                for p in (50, 95, 99):
                    v = self.fsync_hist.percentile(p)
                    if v is not None:
                        fs[f"p{p}_ms"] = round(v * 1e3, 4)
                m["fsync"] = fs
            return m


class WalTail:
    """A replication cursor over a LIVE WriteAheadLog.  The shipper
    polls it for raw records strictly after a per-stream watermark,
    reading segment files directly (appends flush a complete record
    before releasing the lock, so a half-visible record parses as None
    and is simply retried — the tail never takes the append lock for
    file I/O).

    Semantics, in order of precedence per record:

    * seq < expected  -> already shipped (or covered by a snapshot the
      standby restored): consumed silently.
    * seq == expected -> emitted; the cursor advances.
    * seq >  expected -> a GAP: snapshot-barrier truncation deleted
      records the standby still needed.  `poll` reports gap=True
      WITHOUT consuming the record — the shipper ships a Revision,
      calls `advance_to(snapshot_watermark)`, and re-polls from the
      same position.
    * torn / CRC-scarred record -> the tail WAITS at the scar forever
      (an in-flight append completes it; a sealed scar is the heal
      boundary and nothing past it may ever ship — replay could not
      apply it either).
    * missing segment file below the open one -> gap=True (truncated
      beneath the cursor)."""

    def __init__(self, wal: WriteAheadLog, watermark: Optional[dict]):
        self.wal = wal
        self._next = {str(s): int(v) + 1
                      for s, v in (watermark or {}).items()}
        self._seg: Optional[int] = None  # segment under the cursor
        self._off = 0                    # byte offset within it
        self.emitted_records = 0
        self.emitted_bytes = 0

    def position(self) -> dict:
        """Per-stream seq of the last record emitted (the shipped
        watermark)."""
        return {s: v - 1 for s, v in self._next.items() if v > 1}

    def advance_to(self, watermark: Optional[dict]) -> None:
        """Raise the cursor's expectations to a shipped snapshot's
        watermark — records at-or-below it are now covered and will be
        skipped, closing the gap that triggered the catch-up."""
        for s, v in (watermark or {}).items():
            if int(v) + 1 > self._next.get(str(s), 1):
                self._next[str(s)] = int(v) + 1

    def _sealed_done(self, maxima: dict) -> bool:
        """True when a sealed segment's every frame is below the
        cursor's expectations (skip it without reading the file)."""
        return bool(maxima) and all(
            s < self._next.get(sid, 1) for sid, s in maxima.items())

    def poll(self, max_records: int = 256) -> tuple:
        """-> (records, gap): up to `max_records` of
        (stream, seq, raw_record_bytes) ready to ship, plus whether the
        cursor hit a truncation gap (ship a snapshot, `advance_to`,
        re-poll).  Empty records + gap=False means caught up (or
        parked at a scar/in-flight record)."""
        records: list = []
        while len(records) < max_records:
            with self.wal._lock:
                open_seg = self.wal._seg_no
                sealed = dict(self.wal._sealed)
                # snapshot BEFORE reading files: a seq present here is
                # already flushed (append updates seqs after the write,
                # under the lock), so any of these still missing after
                # a clean read-to-EOF was truncated, not in flight
                seqs = dict(self.wal.seqs)
            if self._seg is None:
                segs = self.wal._segments()
                if not segs:
                    return records, False
                self._seg = segs[0]
                self._off = 0
            if self._off == 0 and self._seg in sealed \
                    and self._sealed_done(sealed[self._seg]):
                if not self._advance_segment(open_seg):
                    return records, False
                continue
            try:
                with open(self.wal._seg_path(self._seg), "rb") as f:
                    if self._off:
                        f.seek(self._off)
                    data = f.read()
            except OSError:
                if self._seg < open_seg:
                    return records, True    # truncated beneath the tail
                return records, False
            off = 0
            gap = False
            while len(records) < max_records:
                rec = WriteAheadLog._parse_record(data, off)
                if rec is None:
                    break
                stream, seq, _body, end = rec
                exp = self._next.get(stream, 1)
                if seq > exp:
                    gap = True              # do NOT consume the record
                    break
                if seq == exp:
                    raw = bytes(data[off:end])
                    records.append((stream, seq, raw))
                    self._next[stream] = seq + 1
                    self.emitted_records += 1
                    self.emitted_bytes += len(raw)
                self._off += end - off
                off = end
            if gap:
                return records, True
            if len(records) >= max_records:
                return records, False
            if off != len(data):
                # torn tail (in-flight append) or a sealed scar: wait —
                # nothing past a scar may ever ship
                return records, False
            # clean EOF: follow into the next segment, or report
            # caught-up on the open one — unless the log's own counters
            # say records we still owe existed and are GONE (truncation
            # emptied the log entirely, e.g. a fresh subscriber after a
            # snapshot barrier): that is a gap too, even with no record
            # left to reveal it
            if self._seg >= open_seg:
                if any(v >= self._next.get(s, 1)
                       for s, v in seqs.items()):
                    return records, True
                return records, False
            if not self._advance_segment(open_seg):
                return records, False
        return records, False

    def _advance_segment(self, open_seg: int) -> bool:
        """Move the cursor to the next existing segment; False when
        there is nowhere to go yet."""
        segs = [n for n in self.wal._segments() if n > self._seg]
        if not segs:
            return False
        self._seg = segs[0]
        self._off = 0
        return True
