"""Device (TPU) window + aggregation plans.

Reference semantics: core:query/processor/stream/window/{Length,Time,
LengthBatch}WindowProcessor.java + core:query/selector/attribute/
aggregator/{Sum,Count,Avg,Min,Max}AttributeAggregator — the reference
updates aggregates event-at-a-time via current/expired event pairs.

TPU-first reformulation: a micro-batch of T events is ONE fused array
program; the per-event "add current, remove expired, read aggregate"
loop becomes closed-form range reductions over the concatenated
[carry | batch] sequence:

  * sliding windows — each event's aggregate is a contiguous-range
    reduction ending at that event.  The left edge is rank arithmetic
    for length(L) and a vectorized `searchsorted` for time(D);
    sums/counts/avgs read prefix-sum differences (O(T)), min/max read
    a log2 sparse table (O(T log T) build, O(1) per query).
  * group-by — per-group prefixes come from one sort by (segment,
    position) + segmented cumsum + two searchsorted rank lookups; no
    per-group state is kept at all for sliding windows.
  * lengthBatch(N) tumbling — per-event running aggregates restart at
    bucket boundaries: a segmented scan keyed by (bucket, group); rows
    emit only when their bucket completes (reference emits the whole
    chunk at batch boundary), so the incomplete bucket's raw events
    ride in the carry.

Carry state is a fixed-capacity device buffer packed at the right edge
(so [carry | batch] keeps global arrival order contiguous); a capacity
overflow sets a flag and the host doubles C and retries — the same
adaptive protocol as the pattern kernel (pattern_plan.py).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..query import ast
from ..query.ast import AttrType
from .batch import EventBatch
from .expr import (CompiledExpr, ExprError, SingleStreamContext,
                   compile_expression, compute_dtypes, F32_MODE, jnp_dtype)
from .planner import (AGGREGATOR_NAMES, OutputBatch, PlanError, QueryPlan,
                      selector_has_aggregators)
from .schema import StreamSchema, TIMESTAMP_DTYPE, dtype_of
from .telemetry import call_kernel, env_nbytes


class DeviceWindowUnsupported(Exception):
    pass


_INCR = {"sum", "count", "avg", "min", "max"}

F64 = jnp.float64
NEG = -jnp.inf
POS = jnp.inf
_TS_PAD = jnp.int64(2 ** 62)


def pow2_at_least(n: int, lo: int = 8) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


# ---------------------------------------------------------------------------
# vectorized building blocks
# ---------------------------------------------------------------------------

def _floor_log2(x: jnp.ndarray) -> jnp.ndarray:
    """floor(log2(x)) for int64 x >= 1, exact (no float rounding)."""
    res = jnp.zeros_like(x)
    for shift in (32, 16, 8, 4, 2, 1):
        m = x >= (jnp.int64(1) << shift)
        res = jnp.where(m, res + shift, res)
        x = jnp.where(m, x >> shift, x)
    return res


def _sparse_table(v: jnp.ndarray, is_max: bool) -> jnp.ndarray:
    """(J, N) table: row j reduces [i, i + 2^j)."""
    n = v.shape[0]
    neutral = NEG if is_max else POS
    op = jnp.maximum if is_max else jnp.minimum
    rows = [v]
    w = 1
    while w < n:
        prev = rows[-1]
        shifted = jnp.concatenate([prev[w:], jnp.full(w, neutral)])
        rows.append(op(prev, shifted))
        w *= 2
    return jnp.stack(rows)


def _range_reduce(table: jnp.ndarray, l: jnp.ndarray, r: jnp.ndarray,
                  is_max: bool) -> jnp.ndarray:
    """Reduce over inclusive ranges [l, r]; requires r >= l."""
    op = jnp.maximum if is_max else jnp.minimum
    j = _floor_log2(jnp.maximum(r - l + 1, 1))
    j = jnp.minimum(j, table.shape[0] - 1)
    half = jnp.left_shift(jnp.int64(1), j)
    return op(table[j, l], table[j, r - half + 1])


def _segmented_prefix(seg: jnp.ndarray, v: jnp.ndarray) -> tuple:
    """Inclusive per-segment prefix sums over arrival order.

    seg: (N,) int64 segment id (invalid entries: large id, zero value).
    Returns (ks, segpfx): sorted (seg*N + pos) keys and the per-segment
    inclusive prefix at each sorted slot."""
    n = seg.shape[0]
    key = seg * n + jnp.arange(n, dtype=jnp.int64)
    order = jnp.argsort(key)
    ks = key[order]
    ss = seg[order]
    cs = jnp.cumsum(v[order])
    is_start = jnp.concatenate([jnp.array([True]), ss[1:] != ss[:-1]])
    start_idx = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start, jnp.arange(n), 0))
    base = jnp.where(start_idx > 0, cs[jnp.maximum(start_idx - 1, 0)], 0.0)
    return ks, cs - base


def _seg_prefix_at(ks, segpfx, seg, pos, n):
    """Inclusive prefix at an existing (seg, pos) entry."""
    r = jnp.searchsorted(ks, seg * n + pos)
    return segpfx[r]


def _seg_prefix_before(ks, segpfx, seg, bound, n):
    """Prefix over entries of `seg` with position < bound (0.0 if none)."""
    lo = jnp.searchsorted(ks, seg * n)
    p = jnp.searchsorted(ks, seg * n + bound)
    return jnp.where(p > lo, segpfx[jnp.maximum(p - 1, 0)], 0.0)


def _seg_window_sum(seg, v, left, gpos, n):
    """Per-entry sum over its segment's members in positions [left, gpos]."""
    ks, segpfx = _segmented_prefix(seg, v)
    incl = _seg_prefix_at(ks, segpfx, seg, gpos, n)
    return incl - _seg_prefix_before(ks, segpfx, seg, left, n)


def _seg_window_minmax(seg, v, left, gpos, n, is_max):
    """Per-entry min/max over its segment's members in positions
    [left, gpos]: one sort by (segment, position) + a log2 sparse table +
    two searchsorted bound lookups (the grouped analog of the ungrouped
    range-reduce; v must carry the neutral at invalid entries)."""
    key = seg * n + jnp.arange(n, dtype=jnp.int64)
    order = jnp.argsort(key)
    ks = key[order]
    vs = v[order]
    table = _sparse_table(vs, is_max)
    l = jnp.searchsorted(ks, seg * n + left)
    r = jnp.searchsorted(ks, seg * n + gpos)
    return _range_reduce(table, jnp.minimum(l, r), r, is_max)


def _seg_running_sum(seg, v, n):
    ks, segpfx = _segmented_prefix(seg, v)
    return _seg_prefix_at(ks, segpfx, seg, jnp.arange(n, dtype=jnp.int64), n)


def _seg_running_minmax(seg, v, is_max, n):
    """Per-entry running min/max within its segment, arrival order."""
    key = seg * n + jnp.arange(n, dtype=jnp.int64)
    order = jnp.argsort(key)
    ks = key[order]
    ss = seg[order]
    vs = v[order]
    is_start = jnp.concatenate([jnp.array([True]), ss[1:] != ss[:-1]])
    op = jnp.maximum if is_max else jnp.minimum

    def comb(a, b):
        af, av = a
        bf, bv = b
        return (af | bf, jnp.where(bf, bv, op(av, bv)))
    _f, run = jax.lax.associative_scan(comb, (is_start, vs))
    return run[jnp.searchsorted(ks, key)]


# monotone-segment variants: when segment ids are nondecreasing in arrival
# order (no group-by, or bucket-only keys) the sort is a no-op — skip it

def _mono_running_sum(seg, v):
    n = seg.shape[0]
    cs = jnp.cumsum(v)
    is_start = jnp.concatenate([jnp.array([True]), seg[1:] != seg[:-1]])
    start_idx = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start, jnp.arange(n), 0))
    base = jnp.where(start_idx > 0, cs[jnp.maximum(start_idx - 1, 0)], 0.0)
    return cs - base


def _mono_running_minmax(seg, v, is_max):
    is_start = jnp.concatenate([jnp.array([True]), seg[1:] != seg[:-1]])
    op = jnp.maximum if is_max else jnp.minimum

    def comb(a, b):
        af, av = a
        bf, bv = b
        return (af | bf, jnp.where(bf, bv, op(av, bv)))
    _f, run = jax.lax.associative_scan(comb, (is_start, v))
    return run


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------

class DeviceWindowAggPlan(QueryPlan):
    """`from S[f]#window.{length|time|lengthBatch}(..) select <aggs>
    [group by ...] [having ...] insert into O` as one fused device step."""

    C_START = 1024          # initial carry capacity for time windows
    L_CAP = 1 << 16         # larger length windows stay on host
    # device state commits only after a successful dispatch, so process()
    # is safe to retry with split batches (degradation ladder)
    retryable_process = True

    def __init__(self, name: str, rt, q: ast.Query,
                 inp: ast.SingleInputStream, target: Optional[str]):
        from ..interp.engine import extract_aggregators
        from ..interp.expr import PyExprContext

        self.name = name
        self.rt = rt
        self.output_target = target
        prec = ast.find_annotation(rt.app.annotations, "app:devicePrecision")
        self.f64 = prec is not None and str(prec.element()).lower() == "f64"
        self._mode = None if self.f64 else F32_MODE
        self.fdt = jnp.float64 if self.f64 else jnp.float32
        if q.rate is not None:
            raise DeviceWindowUnsupported("output rate limiting")
        if getattr(q.output, "events_for", ast.OutputEventsFor.CURRENT) \
                != ast.OutputEventsFor.CURRENT:
            raise DeviceWindowUnsupported("expired-events output")
        self._order_by = list(q.selector.order_by)
        self.limit, self.offset = q.selector.limit, q.selector.offset
        if any(isinstance(h, ast.StreamFunction) for h in inp.handlers):
            raise DeviceWindowUnsupported("stream functions")
        if inp.stream_id in rt.named_windows:
            raise DeviceWindowUnsupported("named-window input")

        schema = rt.schemas[inp.stream_id]
        self.in_schema = schema
        self.input_streams = (inp.stream_id,)
        if any(a.type == AttrType.OBJECT for a in schema.attributes):
            raise DeviceWindowUnsupported("object columns")

        # -- window spec ------------------------------------------------------
        wh = inp.window
        if wh is None:
            raise DeviceWindowUnsupported("no window")
        wname = wh.name.lower()
        if wh.namespace is not None:
            raise DeviceWindowUnsupported(f"namespaced window {wname}")

        def _const(i):
            a = wh.args[i]
            if isinstance(a, ast.TimeConstant):
                return a.millis
            if isinstance(a, ast.Constant):
                return a.value
            raise DeviceWindowUnsupported("non-constant window arg")

        self._ext_ts_attr = None
        if wname == "length":
            self.kind = "length"
            self.L = int(_const(0))
            if self.L <= 0 or self.L > self.L_CAP:
                raise DeviceWindowUnsupported(f"length({self.L})")
            self.C = pow2_at_least(self.L)
        elif wname == "time":
            self.kind = "time"
            self.D = int(_const(0))
            self.C = self.C_START
        elif wname == "externaltime":
            # sliding event-time window: same closed-form range reduction
            # as `time`, with the window clock read from the declared
            # timestamp ATTRIBUTE instead of arrival time — no scheduler
            # at all (reference: ExternalTimeWindowProcessor.java expires
            # purely on arriving timestamps; meaningful expiry assumes
            # non-decreasing event time, as in the reference)
            self.kind = "time"
            var = wh.args[0]
            if not isinstance(var, ast.Variable):
                raise DeviceWindowUnsupported(
                    "externalTime timestamp must be an attribute")
            at = schema.type_of(var.attribute) \
                if var.attribute in schema.types else None
            if at not in (AttrType.INT, AttrType.LONG):
                raise DeviceWindowUnsupported(
                    "externalTime timestamp attribute must be int/long")
            self._ext_ts_attr = var.attribute
            self.D = int(_const(1))
            self.C = self.C_START
        elif wname == "externaltimebatch":
            # tumbling over an event-time attribute: lengthBatch's
            # segmented-scan machinery with ts-derived bucket ids
            # (reference: ExternalTimeBatchWindowProcessor.java:520 —
            # bucket boundaries at start + k*duration, flushed when an
            # arriving timestamp crosses the boundary)
            self.kind = "externaltimebatch"
            var = wh.args[0]
            if not isinstance(var, ast.Variable):
                raise DeviceWindowUnsupported(
                    "externalTimeBatch timestamp must be an attribute")
            at = schema.type_of(var.attribute) \
                if var.attribute in schema.types else None
            if at not in (AttrType.INT, AttrType.LONG):
                raise DeviceWindowUnsupported(
                    "externalTimeBatch timestamp attribute must be int/long")
            if len(wh.args) > 2:
                raise DeviceWindowUnsupported(
                    "externalTimeBatch start-time/timeout args")
            self._ext_ts_attr = var.attribute
            self.D = int(_const(1))
            self.C = self.C_START
        elif wname == "lengthbatch":
            self.kind = "lengthbatch"
            self.L = int(_const(0))
            if self.L <= 0 or self.L > self.L_CAP:
                raise DeviceWindowUnsupported(f"lengthBatch({self.L})")
            self.C = pow2_at_least(self.L)
        else:
            raise DeviceWindowUnsupported(f"window {wname}")

        # -- expressions ------------------------------------------------------
        ctx = SingleStreamContext(schema, rt.strings, inp.alias)
        try:
            self._filter = None
            if inp.filters:
                f = inp.filters[0].expr
                for g in inp.filters[1:]:
                    f = ast.And(f, g.expr)
                self._filter = compile_expression(f, ctx)
                if self._filter.type != AttrType.BOOL:
                    raise PlanError(f"filter must be boolean in {name!r}")

            self.group_keys: list[str] = []
            for g in q.selector.group_by:
                key, t = ctx.resolve(g)
                if t == AttrType.OBJECT:
                    raise DeviceWindowUnsupported("object group key")
                self.group_keys.append(key)

            pyctx = PyExprContext({inp.alias: schema, inp.stream_id: schema},
                                  default_ref=inp.alias)
            raw_sites: list = []
            rewritten = []
            sel = q.selector
            if sel.select_all:
                raise DeviceWindowUnsupported("select * with aggregation")
            for oa in sel.attributes:
                rewritten.append(
                    (oa.name, extract_aggregators(oa.expr, raw_sites, pyctx)))
            n_sel_sites = len(raw_sites)
            having_re = None
            if sel.having is not None:
                having_re = extract_aggregators(sel.having, raw_sites, pyctx)
            if not raw_sites:
                raise DeviceWindowUnsupported("no aggregates")

            site_args: list = []
            _collect_site_args([oa.expr for oa in sel.attributes]
                               + ([sel.having] if sel.having is not None
                                  else []), site_args)
            assert len(site_args) == len(raw_sites)
            self.sites = []
            for s, arg_ast in zip(raw_sites, site_args):
                if s.name not in _INCR:
                    raise DeviceWindowUnsupported(f"aggregator {s.name}()")
                arg_ce = (compile_expression(arg_ast, ctx)
                          if arg_ast is not None else None)
                # strings are dictionary codes on device: min()/max() would
                # compare codes, not lexicographic order, and sum()/avg()
                # would aggregate codes — fall back to the host interpreter
                # (advisor r2 HIGH finding)
                if arg_ce is not None and s.name in ("min", "max", "sum", "avg") \
                        and arg_ce.type not in (AttrType.INT, AttrType.LONG,
                                                AttrType.FLOAT, AttrType.DOUBLE):
                    raise DeviceWindowUnsupported(
                        f"{s.name}() over non-numeric ({arg_ce.type.name}) column")
                self.sites.append((s.name, arg_ce, s.out_type))

            extra = {f"__agg{i}": (f"__agg{i}", s.out_type)
                     for i, s in enumerate(raw_sites)}
            octx = SingleStreamContext(schema, rt.strings, inp.alias, extra)
            self.out_fns: list[CompiledExpr] = []
            names, types = [], []
            for nm, expr in rewritten:
                ce = compile_expression(expr, octx)
                self.out_fns.append(ce)
                names.append(nm)
                types.append(ce.type)
            self.having = None
            if having_re is not None:
                hextra = dict(extra)
                hextra.update({n: (n, t) for n, t in zip(names, types)})
                hctx = SingleStreamContext(schema, rt.strings, inp.alias,
                                           hextra)
                self.having = compile_expression(having_re, hctx)
                if self.having.type != AttrType.BOOL:
                    raise PlanError("having must be boolean")
        except ExprError as e:
            raise DeviceWindowUnsupported(str(e))

        self._out_names = names
        for ob in self._order_by:
            if ob.var.attribute not in names:
                raise DeviceWindowUnsupported(
                    f"order by {ob.var.attribute!r}: not an output column")
        self.out_schema = StreamSchema(target or f"#{name}", tuple(
            ast.Attribute(n, t) for n, t in zip(names, types)))

        # event columns the kernel reads
        reads: set = set()
        for ce in self.out_fns:
            reads |= set(ce.reads)
        if self._filter is not None:
            reads |= set(self._filter.reads)
        if self.having is not None:
            # output attribute names are injected into the having env
            reads |= set(self.having.reads) - set(names)
        for _nm, arg, _t in self.sites:
            if arg is not None:
                reads |= set(arg.reads)
        reads |= set(self.group_keys)
        # the sliding length kind never consults time (position-bounded,
        # and slim output rows reconstruct timestamps host-side): skip
        # the ts upload unless some expression reads __timestamp__.
        # lengthBatch still needs it — its non-slim output rows carry
        # device-side timestamps for events carried from prior batches.
        # externalTime reads its clock from an uploaded event COLUMN.
        if self._ext_ts_attr is not None and self.kind == "time" \
                and "__timestamp__" in reads:
            # sliding externalTime: the external column drives the window
            # CLOCK; expressions reading __timestamp__ must see the
            # ARRIVAL time (host parity) — carrying both per event isn't
            # worth it.  (externalTimeBatch carries arrival ts anyway for
            # its non-slim row stamps, so both are available there.)
            raise DeviceWindowUnsupported(
                "externalTime with __timestamp__-reading expressions")
        self._needs_ts = ((self.kind == "externaltimebatch")
                          or (self.kind != "length"
                              and self._ext_ts_attr is None)
                          or "__timestamp__" in reads)
        if self._ext_ts_attr is not None:
            reads.add(self._ext_ts_attr)
        reads.discard("__timestamp__")
        unknown = [k for k in reads
                   if k not in schema.types and not k.startswith("__agg")]
        if unknown:
            raise DeviceWindowUnsupported(f"unresolved columns {unknown}")
        self.cols = sorted(k for k in reads if k in schema.types)

        from .autotune import pipeline_depth_for
        from .pipeline import DispatchPipeline
        self.pipeline_depth = pipeline_depth_for(rt, "window", q)
        self._pipe = DispatchPipeline(name, self._materialize,
                                      depth=self.pipeline_depth)

        # multi-chip: @app:deviceMesh('always') shards the batch axis T
        # over the mesh — XLA partitions the prefix/segmented scans and
        # inserts the cross-shard collectives (the jax way: annotate
        # shardings, let the partitioner place psum/permute chains).
        # Carry state replicates (it is O(window), not O(batch)).
        from .planner import mesh_for
        self.mesh = mesh_for(rt, "t")

        self.state = self._init_state()
        jax.eval_shape(self._step_fn(8, self.C), self.state, self._dummy(8))

    # -- state ---------------------------------------------------------------

    def _carry_cols(self) -> list:
        """Event columns that must ride in the carry buffer."""
        if self.kind in ("lengthbatch", "externaltimebatch"):
            return list(self.cols)      # rows emit later: full env needed
        need = set(self.group_keys)
        for _nm, arg, _t in self.sites:
            if arg is not None:
                need |= set(arg.reads) & set(self.in_schema.types)
        return sorted(need)

    EXT_START_SENTINEL = -(2 ** 62)

    def _init_state(self) -> dict:
        C = self.C
        st = {"ts": jnp.full(C, -_TS_PAD),
              "valid": jnp.zeros(C, dtype=bool),
              "seen": jnp.int64(0)}
        if self.kind == "externaltimebatch":
            st["start"] = jnp.int64(self.EXT_START_SENTINEL)
        for k in self._carry_cols():
            with compute_dtypes(self._mode):
                st[f"c.{k}"] = jnp.zeros(
                    C, dtype=jnp_dtype(self.in_schema.types[k]))
        return st

    def _dummy(self, T: int) -> dict:
        env = {"__nvalid__": jnp.int32(0)}
        if self._needs_ts:
            env["__ts_off__"] = jnp.zeros(T, jnp.int32)
            env["__ts_base__"] = jnp.int64(0)
        for k in self.cols:
            env[k] = jnp.zeros(T, dtype=jnp_dtype(self.in_schema.types[k]))
        return env

    def _grow(self, new_c: int) -> None:
        old = {k: np.asarray(v) for k, v in self.state.items()}
        self.C = new_c
        fresh = self._init_state()
        st = {}
        for k, f in fresh.items():
            o = old[k]
            if np.ndim(o) == 0:
                st[k] = jnp.asarray(o)
            else:
                pad = np.asarray(f).copy()
                pad[-o.shape[0]:] = o       # keep right-packing
                st[k] = jnp.asarray(pad)
        self.state = st

    # -- kernel --------------------------------------------------------------

    def _step_fn(self, T: int, C: int) -> Callable:
        """Per-instance cache (an lru_cache on the bound method would pin
        the plan instance and its compiled fns forever — advisor r2).
        Offset dtype (i32 vs rare i64 wide batches) needs no cache key:
        jit re-specializes on the __ts_off__ dtype."""
        cache = getattr(self, "_step_cache", None)
        if cache is None:
            cache = self._step_cache = {}
        fn = cache.get((T, C))
        if fn is None:
            fn = cache[(T, C)] = self._build_step_fn(T, C)
        return fn

    def _build_step_fn(self, T: int, C: int) -> Callable:
        kind = self.kind
        sites = self.sites
        group_keys = self.group_keys
        filt = self._filter
        out_fns = self.out_fns
        out_names = self._out_names
        having = self.having
        carry_cols = self._carry_cols()
        cols = self.cols
        ext_ts = self._ext_ts_attr
        L = getattr(self, "L", 0)
        D = getattr(self, "D", 0)
        N = C + T
        FDT = self.fdt
        out_types = [a.type for a in self.out_schema.attributes]

        def site_vals(env_all, n):
            out = []
            for nm, arg, _t in sites:
                if arg is None or nm == "count":
                    out.append(jnp.ones(n, FDT))
                else:
                    out.append(arg.fn(env_all).astype(FDT))
            return out

        def group_seg(env_all, gvalid, n):
            """Dense group-segment id per entry (invalid -> n)."""
            if not group_keys:
                return jnp.where(gvalid, 0, n).astype(jnp.int64)
            keys = []
            for g in group_keys:
                c = env_all[g]
                if c.dtype.kind == "f":
                    c = c.astype(jnp.float64)
                    c = jnp.where(c == 0.0, 0.0, c).view(jnp.int64)
                else:
                    c = c.astype(jnp.int64)
                keys.append(c)
            order = jnp.lexsort(keys[::-1])
            diff = jnp.zeros(n, dtype=bool)
            for kk in keys:
                ks = kk[order]
                diff = diff | jnp.concatenate(
                    [jnp.array([True]), ks[1:] != ks[:-1]])
            seg_sorted = jnp.cumsum(diff) - 1
            seg = jnp.zeros(n, dtype=jnp.int64).at[order].set(seg_sorted)
            return jnp.where(gvalid, seg, n)

        def finish(env_all, aggs, row_ok):
            """Select + having over an aligned env; returns (outs, ok)."""
            env2 = dict(env_all)
            for i, a in enumerate(aggs):
                _nm, _arg, ot = sites[i]
                env2[f"__agg{i}"] = _cast_site(a, ot)
            outs = [ce.fn(env2) for ce in out_fns]
            if having is not None:
                henv = dict(env2)
                for nm2, col in zip(out_names, outs):
                    henv[nm2] = col
                row_ok = row_ok & having.fn(henv)
            return outs, row_ok

        def step_sliding(state, bts, bvalid, bcols, k):
            raw_bts = bts
            all_ts = jnp.concatenate([state["ts"], bts])
            all_ts = jax.lax.associative_scan(jnp.maximum, all_ts)  # monotone
            all_valid = jnp.concatenate([state["valid"], bvalid])
            env_all = {c: jnp.concatenate([state[f"c.{c}"], bcols[c]])
                       for c in carry_cols}
            env_all["__timestamp__"] = all_ts
            gpos = jnp.arange(N, dtype=jnp.int64)
            vcnt = jnp.cumsum(all_valid.astype(jnp.int64))
            if kind == "length":
                want = jnp.maximum(vcnt - L, 0)
                left = jnp.searchsorted(vcnt, want, side="right")
            else:
                left = jnp.searchsorted(all_ts, all_ts - D, side="right")
            seg = group_seg(env_all, all_valid, N) if group_keys else None
            vals = site_vals(env_all, N)

            def wsum(v):
                """Windowed sum over [left, gpos] — per-group via the
                segmented machinery, else one prefix-difference (no sort)."""
                if group_keys:
                    return _seg_window_sum(seg, v, left, gpos, N)
                c = jnp.cumsum(v)
                before = jnp.where(left > 0, c[jnp.maximum(left - 1, 0)], 0.0)
                return c - before

            aggs_full = []
            for i, (nm, _arg, _ot) in enumerate(sites):
                if nm in ("min", "max"):
                    neutral = NEG if nm == "max" else POS
                    vv = jnp.where(all_valid, vals[i], neutral)
                    if group_keys:
                        aggs_full.append(_seg_window_minmax(
                            seg, vv, left, gpos, N, nm == "max"))
                        continue
                    table = _sparse_table(vv, nm == "max")
                    aggs_full.append(_range_reduce(
                        table, jnp.minimum(left, gpos), gpos, nm == "max"))
                    continue
                v = (all_valid.astype(FDT) if nm == "count"
                     else jnp.where(all_valid, vals[i], 0.0))
                s = wsum(v)
                if nm == "avg":
                    s = s / jnp.maximum(wsum(all_valid.astype(FDT)), 1.0)
                aggs_full.append(s)

            # rows align with the compacted batch part (raw timestamps:
            # the monotonic clamp is internal to expiry math only)
            aggs = [a[C:] for a in aggs_full]
            benv = {c: bcols[c] for c in cols}
            benv["__timestamp__"] = raw_bts
            outs, row_ok = finish(benv, aggs, bvalid)
            row_ts = raw_bts

            # carry = last C entries ending at C+k, minus departed ones
            if kind == "length":
                total_v = vcnt[N - 1]
                start_k = jnp.searchsorted(
                    vcnt, jnp.maximum(total_v - L, 0), side="right")
            else:
                last_ts = all_ts[jnp.maximum(C + k - 1, 0)]
                start_k = jnp.searchsorted(all_ts, last_ts - D, side="right")
            keep = (gpos >= start_k) & all_valid
            sl = lambda a: jax.lax.dynamic_slice(a, (k,), (C,))
            nst = {"seen": state["seen"] + k,
                   "ts": sl(all_ts),
                   "valid": sl(keep)}
            for c in carry_cols:
                nst[f"c.{c}"] = sl(env_all[c])
            overflow = (jnp.sum(keep) > C).astype(jnp.int32)
            return nst, outs, row_ok, row_ts, overflow

        def step_lengthbatch(state, bts, bvalid, bcols, k):
            all_ts = jnp.concatenate([state["ts"], bts])
            all_valid = jnp.concatenate([state["valid"], bvalid])
            env_all = {c: jnp.concatenate([state[f"c.{c}"], bcols[c]])
                       for c in carry_cols}
            env_all["__timestamp__"] = all_ts
            # admission index: carried events resume their old positions
            base = state["seen"] - jnp.sum(state["valid"])   # multiple of L
            vrank = jnp.cumsum(all_valid.astype(jnp.int64)) - 1
            gidx = base + vrank
            brel = jnp.where(all_valid, (gidx - base) // L, -1)
            if group_keys:
                seg = group_seg(env_all, all_valid, N)
                segb = jnp.where(all_valid, brel * (N + 1) + seg,
                                 jnp.int64((N + 2) * (N + 1)))
            else:
                segb = None
            vals = site_vals(env_all, N)
            # no group-by: bucket ids are nondecreasing over [carry | batch]
            # (the carry holds only the lowest incomplete bucket), so the
            # sort inside the segmented scans is a no-op — skip it
            rsum = ((lambda s_, v_: _mono_running_sum(s_, v_))
                    if not group_keys else
                    (lambda s_, v_: _seg_running_sum(s_, v_, N)))
            rmm = ((lambda s_, v_, mx: _mono_running_minmax(s_, v_, mx))
                   if not group_keys else
                   (lambda s_, v_, mx: _seg_running_minmax(s_, v_, mx, N)))
            segk = brel if not group_keys else segb
            aggs = []
            for i, (nm, _arg, _ot) in enumerate(sites):
                if nm in ("min", "max"):
                    neutral = NEG if nm == "max" else POS
                    vv = jnp.where(all_valid, vals[i], neutral)
                    aggs.append(rmm(segk, vv, nm == "max"))
                else:
                    v = (all_valid.astype(FDT) if nm == "count"
                         else jnp.where(all_valid, vals[i], 0.0))
                    s = rsum(segk, v)
                    if nm == "avg":
                        s = s / jnp.maximum(rsum(segk, all_valid.astype(FDT)),
                                            1.0)
                    aggs.append(s)
            total = base + jnp.sum(all_valid)
            completed = (total // L) * L
            emit = all_valid & (gidx < completed)
            outs, row_ok = finish(env_all, aggs, emit)
            row_ts = all_ts
            pend = all_valid & (gidx >= completed)
            sl = lambda a: jax.lax.dynamic_slice(a, (k,), (C,))
            nst = {"seen": total, "ts": sl(all_ts), "valid": sl(pend)}
            for c in carry_cols:
                nst[f"c.{c}"] = sl(env_all[c])
            return nst, outs, row_ok, row_ts, jnp.int32(0)

        def step_extbatch(state, bts, bvalid, bcols, k):
            """externalTimeBatch: lengthBatch's per-bucket segmented scans
            with bucket ids (ets - start) // D; completed buckets (any
            later-bucket event arrived) emit, the current bucket's raw
            events carry.  Assumes nondecreasing event time, as the
            reference does."""
            SENT = jnp.int64(DeviceWindowAggPlan.EXT_START_SENTINEL)
            all_ts = jnp.concatenate([state["ts"], bts])      # arrival
            all_valid = jnp.concatenate([state["valid"], bvalid])
            env_all = {c: jnp.concatenate([state[f"c.{c}"], bcols[c]])
                       for c in carry_cols}
            env_all["__timestamp__"] = all_ts
            ets = env_all[ext_ts].astype(jnp.int64)
            idx0 = jnp.argmax(all_valid)          # first valid entry
            first_e = ets[idx0]
            # latch the bucket anchor only when the block actually holds
            # a valid event: argmax over an all-False mask is 0, and a
            # fully-filtered first micro-batch would otherwise latch a
            # garbage carry-slot timestamp, permanently shifting every
            # bucket boundary vs the host path
            start = jnp.where((state["start"] == SENT)
                              & jnp.any(all_valid),
                              first_e, state["start"])
            Dj = jnp.int64(D)
            b = jnp.where(all_valid, (ets - start) // Dj, jnp.int64(-1))
            bfirst = b[idx0]
            brel = jnp.where(all_valid, b - bfirst, jnp.int64(-1))
            blast = jnp.max(b)                    # monotone ts: current
            if group_keys:
                seg = group_seg(env_all, all_valid, N)
                segb = jnp.where(all_valid, brel * (N + 1) + seg,
                                 jnp.int64((N + 2) * (N + 1)))
            else:
                segb = None
            vals = site_vals(env_all, N)
            rsum = ((lambda s_, v_: _mono_running_sum(s_, v_))
                    if not group_keys else
                    (lambda s_, v_: _seg_running_sum(s_, v_, N)))
            rmm = ((lambda s_, v_, mx: _mono_running_minmax(s_, v_, mx))
                   if not group_keys else
                   (lambda s_, v_, mx: _seg_running_minmax(s_, v_, mx, N)))
            segk = brel if not group_keys else segb
            aggs = []
            for i, (nm, _arg, _ot) in enumerate(sites):
                if nm in ("min", "max"):
                    neutral = NEG if nm == "max" else POS
                    vv = jnp.where(all_valid, vals[i], neutral)
                    aggs.append(rmm(segk, vv, nm == "max"))
                else:
                    v = (all_valid.astype(FDT) if nm == "count"
                         else jnp.where(all_valid, vals[i], 0.0))
                    s = rsum(segk, v)
                    if nm == "avg":
                        s = s / jnp.maximum(rsum(segk, all_valid.astype(FDT)),
                                            1.0)
                    aggs.append(s)
            emit = all_valid & (b < blast)
            outs, row_ok = finish(env_all, aggs, emit)
            row_ts = all_ts
            pend = all_valid & (b == blast)
            sl = lambda a: jax.lax.dynamic_slice(a, (k,), (C,))
            nst = {"seen": state["seen"] + k, "ts": sl(all_ts),
                   "valid": sl(pend), "start": start}
            for c in carry_cols:
                nst[f"c.{c}"] = sl(env_all[c])
            overflow = (jnp.sum(pend) > C).astype(jnp.int32)
            return nst, outs, row_ok, row_ts, overflow

        def compact(mask, arr, fill):
            pos = jnp.cumsum(mask.astype(jnp.int32), dtype=jnp.int32) - mask
            wpos = jnp.where(mask, pos, T)
            return jnp.full((T,), fill, arr.dtype).at[wpos].set(
                arr, mode="drop")

        def step(state, env):
            with compute_dtypes(mode):
                # timestamps travel as offsets from a per-batch i64 base
                # and validity as a prefix count — 5 fewer upload bytes
                # per event through the tunnel than i64 ts + bool valid;
                # length kinds with no ts-reading expression skip ts
                # upload altogether (position-bounded, not time-bounded);
                # sliding externalTime's window clock is the declared
                # event column (externalTimeBatch keeps ARRIVAL time here
                # for its row stamps; its bucket ids read the column
                # inside step_extbatch)
                if ext_ts is not None and kind == "time":
                    ts64 = env[ext_ts].astype(jnp.int64)
                elif "__ts_off__" in env:
                    ts64 = env["__ts_base__"] \
                        + env["__ts_off__"].astype(jnp.int64)
                else:
                    ts64 = jnp.zeros(T, jnp.int64)
                mask = jnp.arange(T, dtype=jnp.int32) < env["__nvalid__"]
                if filt is not None:
                    fenv = dict(env)
                    fenv["__timestamp__"] = ts64
                    mask = mask & filt.fn(fenv)
                # compact filtered events to the front: one i32 cumsum + one
                # scatter per column (a stable argsort here cost 244s of
                # XLA compile at T=16K and dominated runtime)
                k = jnp.sum(mask, dtype=jnp.int32)
                bvalid = jnp.arange(T, dtype=jnp.int32) < k
                bts = compact(mask, ts64, _TS_PAD)
                bcols = {c: compact(mask, env[c], 0) for c in cols}
                if kind == "lengthbatch":
                    res = step_lengthbatch(state, bts, bvalid, bcols, k)
                elif kind == "externaltimebatch":
                    res = step_extbatch(state, bts, bvalid, bcols, k)
                else:
                    res = step_sliding(state, bts, bvalid, bcols, k)
                return pack(res, mask, k)

        def bits32(m):
            """(T,) bool -> (ceil(T/32),) i32 word stream, little-bit order."""
            n_ = m.shape[0]
            padded = -(-n_ // 32) * 32
            if padded != n_:
                m = jnp.concatenate([m, jnp.zeros(padded - n_, bool)])
            r = m.reshape(-1, 32).astype(jnp.uint32)
            w = (r << jnp.arange(32, dtype=jnp.uint32)[None, :]) \
                .sum(axis=1).astype(jnp.uint32)   # sum may promote to u64
            return jax.lax.bitcast_convert_type(w, jnp.int32)

        slim = kind not in ("lengthbatch", "externaltimebatch")
        has_filter = filt is not None

        def pack(res, mask, k):
            """Outputs travel in as few bytes as possible — every
            device->host pull through the tunnel pays ~100 ms fixed plus
            per-byte cost.  Sliding kinds are `slim`: row timestamps equal
            the (filter-compacted) input timestamps, which the host already
            holds, so only a small `b` vector ([overflow, k] + bit-packed
            masks when needed) plus the out columns travel.  lengthBatch
            rows can emit carried (previous-batch) events, so it keeps the
            full layout: [overflow]+ok+ts hi/lo rows ahead of the columns."""
            nst, outs, row_ok, row_ts, overflow = res
            n = row_ok.shape[0]
            irows, frows = [], []
            if slim:
                bparts = [jnp.stack([overflow, k]).astype(jnp.int32)]
                if has_filter:
                    bparts.append(bits32(mask))
                if having is not None:
                    bparts.append(bits32(row_ok))
            else:
                meta = jnp.zeros((n,), jnp.int32).at[0].set(overflow)
                row_ts = row_ts.astype(jnp.int64)
                irows += [meta, row_ok.astype(jnp.int32),
                          _w_hi32(row_ts), _w_lo32(row_ts)]
            # encode by DECLARED type so the host unpack (which switches on
            # the out schema) always reads the matching rows — the raw
            # device dtype may be widened (e.g. INT aggregates ride i64)
            for colv, t in zip(outs, out_types):
                colv = jnp.asarray(colv)
                if t == AttrType.DOUBLE and FDT == jnp.float64:
                    frows.append(colv.astype(jnp.float64))
                elif t in (AttrType.DOUBLE, AttrType.FLOAT):
                    irows.append(jax.lax.bitcast_convert_type(
                        colv.astype(jnp.float32), jnp.int32))
                elif t == AttrType.LONG:
                    colv = colv.astype(jnp.int64)
                    irows.append(_w_hi32(colv))
                    irows.append(_w_lo32(colv))
                else:
                    irows.append(colv.astype(jnp.int32))
            out = {"nst": nst}
            if irows:       # slim + f64 can route EVERY column to frows
                out["i"] = jnp.stack(irows, axis=0)
            if slim:
                out["b"] = jnp.concatenate(bparts)
            if frows:
                out["f"] = jnp.stack(frows, axis=0)
            return out

        mode = self._mode
        if self.mesh is None:
            return jax.jit(step)
        from jax.sharding import NamedSharding, PartitionSpec
        shard_t = NamedSharding(self.mesh, PartitionSpec("t"))
        repl = NamedSharding(self.mesh, PartitionSpec())
        state_sh = {k: repl for k in self.state}
        env_sh = {"__nvalid__": repl}
        if self._needs_ts:
            env_sh["__ts_off__"] = shard_t
            env_sh["__ts_base__"] = repl
        env_sh.update({c: shard_t for c in cols})
        return jax.jit(step, in_shardings=(state_sh, env_sh))

    # -- QueryPlan interface --------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        if batch.n == 0:
            return []
        with self.rt.stats.stage("host_build", plan=self.name):
            T = pow2_at_least(batch.n)
            if self.mesh is not None:
                # the sharded 't' axis must divide the device count
                T = max(T, self.mesh.devices.size)
            # pads are memoized on the batch (N plans on one stream share
            # ONE pad per column per flush) and backed by the runtime's
            # rotating PadPool, so steady-state flushes stop allocating;
            # depth + 2 slots keep envs of pipelined retries un-aliased
            pool = getattr(self.rt, "_pad_pool", None)
            slots = self.pipeline_depth + 2
            env = {"__nvalid__": np.int32(batch.n)}
            if self._needs_ts:
                off, base = batch.padded_ts_offsets(T, pool=pool,
                                                    min_slots=slots)
                env["__ts_off__"] = off
                env["__ts_base__"] = np.int64(base)
            for c in self.cols:
                dt = None
                if not self.f64 \
                        and batch.columns[c].dtype == np.float64:
                    dt = np.float32              # device DOUBLE policy
                env[c] = batch.padded(c, T, dtype=dt, pool=pool,
                                      min_slots=slots)
        # depth-D pipeline (opt-in @app:devicePipeline): batch i's pull
        # overlaps batch i+1..i+D's upload+compute, hiding the tunnel's
        # fixed D2H latency; outputs then deliver up to D batches late
        # (the runtime flush barrier drains the tail)
        return self._pipe.push(self._dispatch(env, batch, T))

    def _dispatch(self, env: dict, batch: EventBatch, T: int) -> dict:
        from .pipeline import start_d2h
        # dispatch-boundary fault injection (core/faults.py); state
        # commits only after the call returns, so a raise here leaves the
        # plan retryable (the runtime's degradation ladder re-dispatches
        # with a split batch — half the pad footprint)
        self.rt.inject("dispatch", self.name)
        pre = self.state
        prof = self.rt.profiler
        if not self.rt.stats.enabled and prof is None:
            res = self._step_fn(T, self.C)(self.state, env)
        else:
            hit = (T, self.C) in getattr(self, "_step_cache", {})
            fn = self._step_fn(T, self.C)
            res = call_kernel(
                self.rt.stats, self.name, fn, (self.state, env),
                cache_hit=hit, nbytes=env_nbytes(env), prof=prof)
        start_d2h(res, keys=("b", "i", "f"))
        self.state = res["nst"]
        return {"pre": pre, "env": env, "batch": batch, "T": T, "res": res}

    def _materialize(self, entry: dict) -> list:
        slim = self.kind not in ("lengthbatch", "externaltimebatch")
        bpack = None
        while True:
            res = entry["res"]
            with self.rt.stats.stage("transfer", plan=self.name):
                if slim:
                    bpack = np.asarray(res["b"])
                    overflow = int(bpack[0])
                else:
                    overflow = int(np.asarray(res["i"])[0, 0])
            if not overflow:
                break
            # carry overflow: grow C and replay this entry plus everything
            # dispatched after it (their pre-states are now invalid)
            chain = [entry] + self._pipe.take_all()
            self.state = entry["pre"]
            self._grow(2 * self.C)
            redone = [self._dispatch(e["env"], e["batch"], e["T"])
                      for e in chain]
            entry = redone[0]
            self._pipe.requeue(redone[1:])
        with self.rt.stats.stage("transfer", plan=self.name):
            ipack = np.asarray(res["i"]) if "i" in res else None
            fpack = np.asarray(res["f"]) if "f" in res else None
        batch = entry["batch"]
        T = entry["T"]
        from .nfa_device import join64_np
        if slim:
            # sliding rows align with the (filter-compacted) input events:
            # timestamps reconstruct host-side, only masks travel as bits
            k = int(bpack[1])
            off = 2
            if self._filter is not None:
                nw = -(-T // 32)
                maskb = _unbits32(bpack[off:off + nw], T)[:batch.n]
                off += nw
                ts_rows = batch.timestamps[maskb]
            else:
                ts_rows = batch.timestamps
            if self.having is not None:
                nw = -(-T // 32)
                valid = _unbits32(bpack[off:off + nw], T)[:k]
            else:
                valid = np.ones(k, dtype=bool)
            if k == 0 or not valid.any():
                return []
            ts_out = ts_rows[:k][valid].astype(TIMESTAMP_DTYPE)
            ii, fi = 0, 0
            take = lambda col: col[:k][valid]
        else:
            ok = ipack[1] != 0
            if not ok.any():
                return []
            ts_out = join64_np(ipack[2], ipack[3])[ok].astype(TIMESTAMP_DTYPE)
            ii, fi = 4, 0
            take = lambda col: col[ok]
        cols = {}
        for a in self.out_schema.attributes:
            dt = np.dtype(jnp_dtype(a.type)) if a.type != AttrType.DOUBLE \
                else np.dtype(np.float64 if self.f64 else np.float32)
            if dt == np.float64:
                col = fpack[fi]; fi += 1
            elif dt == np.float32:
                col = ipack[ii].view(np.float32); ii += 1
            elif dt == np.int64:
                col = join64_np(ipack[ii], ipack[ii + 1]); ii += 2
            else:
                col = ipack[ii]; ii += 1
            v = take(col)
            if a.type == AttrType.BOOL:
                v = v != 0
            cols[a.name] = v.astype(dtype_of(a.type))
        ts_out, cols = self._order_limit(ts_out, cols)
        out = EventBatch(self.out_schema, ts_out, cols, len(ts_out))
        return [OutputBatch(self.output_target, out)]

    def _order_limit(self, ts_out, cols):
        """order-by / offset / limit over one output chunk, host-side
        (device rows are already materialized columns; stable multi-key
        sort mirrors the interp selector's order_limit)."""
        if not (self._order_by or self.limit is not None or self.offset):
            return ts_out, cols
        n = len(ts_out)
        order = np.arange(n)
        for ob in reversed(self._order_by):
            col = cols[ob.var.attribute]
            if self.out_schema.type_of(ob.var.attribute) == AttrType.STRING \
                    and col.dtype.kind in "iu":
                dec = self.rt.strings._to_str
                col = np.array([dec[c] if 0 <= c < len(dec) else ""
                                for c in col.tolist()])
            # rank-inversion covers every dtype exactly (bool, i64 > 2^53,
            # strings lexicographically) and DESC is integer negation of
            # small ranks — no float round-trip (review r5)
            _u, ranks = np.unique(col, return_inverse=True)
            k = ranks[order].astype(np.int64)
            if ob.order == ast.OrderDir.DESC:
                k = -k
            order = order[np.argsort(k, kind="stable")]
        ts_out = ts_out[order]
        cols = {k2: v[order] for k2, v in cols.items()}
        off = self.offset or 0
        if off:
            ts_out = ts_out[off:]
            cols = {k2: v[off:] for k2, v in cols.items()}
        if self.limit is not None:
            ts_out = ts_out[:self.limit]
            cols = {k2: v[:self.limit] for k2, v in cols.items()}
        return ts_out, cols

    # -- snapshot -------------------------------------------------------------

    def device_metrics(self) -> dict:
        """Sampled carry-buffer fill (one D2H pull of the valid mask)."""
        try:
            fill = int(np.asarray(self.state["valid"]).sum())
        except Exception:   # lint: allow-swallow (best-effort metrics
            # sampling — a mid-regeometry scrape just skips the gauge)
            return {}
        return {"window_capacity": int(self.C), "window_fill": fill,
                "window_fill_ratio": round(fill / max(self.C, 1), 4)}

    def state_dict(self) -> dict:
        return {"state": {k: np.asarray(v) for k, v in self.state.items()},
                "C": self.C}

    def load_state_dict(self, d: dict) -> None:
        c = int(d.get("C", self.C))
        if c != self.C:
            self.C = c
        self._pipe.take_all()       # in-flight results predate the restore
        self.state = {k: jnp.asarray(v) for k, v in d["state"].items()}


def _unbits32(words: np.ndarray, n: int) -> np.ndarray:
    """Inverse of the device bits32 pack: i32 words -> (n,) bool."""
    b = ((words.view(np.uint32)[:, None]
          >> np.arange(32, dtype=np.uint32)) & 1).astype(bool)
    return b.reshape(-1)[:n]


from .nfa_device import _hi32 as _w_hi32, _lo32 as _w_lo32  # noqa: E402


def _cast_site(a: jnp.ndarray, t: AttrType) -> jnp.ndarray:
    if t in (AttrType.INT, AttrType.LONG):
        return a.astype(jnp.int64)
    return a


def _collect_site_args(exprs, acc: list) -> None:
    """Aggregator arg ASTs in extract_aggregators traversal order."""
    def walk(e):
        if isinstance(e, ast.FunctionCall) and e.namespace is None \
                and e.name.lower() in AGGREGATOR_NAMES:
            acc.append(e.args[0] if e.args else None)
            return
        if isinstance(e, (ast.Math, ast.Compare, ast.And, ast.Or)):
            walk(e.left)
            walk(e.right)
        elif isinstance(e, ast.Not):
            walk(e.expr)
        elif isinstance(e, ast.FunctionCall):
            for a in e.args:
                walk(a)
    for e in exprs:
        walk(e)
