"""Device pattern/sequence query plan — host wrapper around NFAKernel.

Buffers per-stream micro-batches, merges them by global arrival seq,
buckets events into dense (T, P) blocks (one event per partition per scan
step), runs the jitted batched-NFA block, and compacts emitted matches
back into an output EventBatch.

The partition axis is 1 for plain pattern queries; partitioned queries
(`partition with (key of Stream) begin ... end`) set a key extractor and
a partition capacity so thousands of per-key NFA instances run as one
kernel (reference clones the whole query graph per key instead:
core:partition/PartitionRuntime.java:257-306).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np

from ..query import ast
from .batch import EventBatch
from .expr import ExprError, MultiStreamContext, compile_expression
from .nfa_device import (ChainSpec, DeviceNFAUnsupported, NFAKernel,
                         lower_chain, pow2_at_least)
from .planner import (AGGREGATOR_NAMES, OutputBatch, PlanError, QueryPlan,
                      selector_has_aggregators)
from .schema import StreamSchema, TIMESTAMP_DTYPE, dtype_of


class DevicePatternPlan(QueryPlan):
    """from [every] e1=A[...] -> e2=B[...] within T — batched device NFA."""

    A_CAP = 512      # default adaptive slot-growth ceiling (@app:deviceSlotCap)

    def __init__(self, name: str, rt, q: ast.Query, state_input,
                 target: Optional[str], partitions: int = 1,
                 part_key_fns: Optional[dict] = None, slots: int = 16):
        from ..interp.engine import _collect_filters

        self.name = name
        self.rt = rt
        cap = ast.find_annotation(rt.app.annotations, "app:deviceSlotCap")
        if cap is not None:
            self.A_CAP = int(cap.element())
        self.output_target = target
        self.events_for = getattr(q.output, "events_for",
                                  ast.OutputEventsFor.CURRENT)
        if q.rate is not None:
            raise DeviceNFAUnsupported("output rate limiting")
        if q.selector.group_by or q.selector.order_by \
                or selector_has_aggregators(q.selector):
            raise DeviceNFAUnsupported("group-by/order-by/aggregating selector")
        self.limit, self.offset = q.selector.limit, q.selector.offset

        self.spec: ChainSpec = lower_chain(
            state_input, rt.schemas, rt.strings,
            _collect_filters(state_input.state))
        self.input_streams = tuple(self.spec.stream_ids)

        # partitioning: key fn per input stream (row cols -> np int codes)
        self.P = partitions
        self.part_key_fns = part_key_fns        # stream_id -> fn(batch)->codes
        self._key_to_part: dict = {}            # key value -> partition index

        # selector over capture refs
        sel = q.selector
        sctx = MultiStreamContext(self.spec.schemas, rt.strings)
        names, types, fns = [], [], []
        if sel.select_all:
            seen = set()
            for s in self.spec.states:
                for a in self.spec.schemas[s.ref].attributes:
                    nm = a.name if a.name not in seen else f"{s.ref}_{a.name}"
                    seen.add(nm)
                    ce = compile_expression(
                        ast.Variable(a.name, stream_ref=s.ref), sctx)
                    names.append(nm)
                    types.append(ce.type)
                    fns.append(ce)
        else:
            for oa in sel.attributes:
                try:
                    ce = compile_expression(oa.expr, sctx)
                except ExprError as e:
                    raise DeviceNFAUnsupported(f"selector: {e}")
                names.append(oa.name)
                types.append(ce.type)
                fns.append(ce)
        self._names, self._types = names, types
        having = None
        if sel.having is not None:
            import copy
            hctx = copy.copy(sctx)
            hctx.extra = {n: (n, t) for n, t in zip(names, types)}
            try:
                having = compile_expression(sel.having, hctx)
            except ExprError as e:
                raise DeviceNFAUnsupported(f"having: {e}")
        self.out_schema = StreamSchema(target or f"#{name}", tuple(
            ast.Attribute(n, t) for n, t in zip(names, types)))

        self.kernel = NFAKernel(self.spec, dict(zip(names, fns)), having,
                                self.P, slots)
        self.state = self.kernel.init_state()
        self._m_hint = 16           # last match-buffer capacity that sufficed
        self._of_slots_seen = 0     # accepted (at-cap) overflow totals
        self._buffered: list = []   # (stream_id, EventBatch)
        self._scode = {sid: i for i, sid in enumerate(self.spec.stream_ids)}

        # build-time validation: trace a tiny block so unsupported env keys
        # fail here (-> sequential fallback) instead of at first flush
        dummy = self._dense_dummy(T=2)
        jax.eval_shape(self.kernel.block_fn(2, 8), self.state, dummy)

    # -- helpers -------------------------------------------------------------

    def _dense_dummy(self, T: int) -> dict:
        import jax.numpy as jnp
        from .expr import jnp_dtype
        P = self.P
        ev = {"__ts__": jnp.zeros((T, P), dtype=jnp.int64),
              "__seq__": jnp.zeros((T, P), dtype=jnp.int64),
              "__scode__": jnp.zeros((T, P), dtype=jnp.int32),
              "__valid__": jnp.zeros((T, P), dtype=bool)}
        for sid in self.spec.stream_ids:
            si = self._scode[sid]
            for a in self.rt.schemas[sid].attributes:
                ev[f"{si}.{a.name}"] = jnp.zeros((T, P), dtype=jnp_dtype(a.type))
        return ev

    @property
    def dropped(self) -> int:
        """Partial matches / emissions lost to capacity exhaustion — only
        possible once adaptive growth hits the A_CAP ceiling.  Carried in
        device state, so snapshot-safe."""
        return int(np.asarray(self.state["of_slots"]).sum())

    def part_of(self, stream_id: str, batch: EventBatch) -> np.ndarray:
        """Partition index per event; grows the key map (host side)."""
        if self.part_key_fns is None:
            return np.zeros(batch.n, dtype=np.int32)
        keys = self.part_key_fns[stream_id](batch)
        out = np.empty(batch.n, dtype=np.int32)
        k2p = self._key_to_part
        for i, k in enumerate(keys.tolist()):
            p = k2p.get(k)
            if p is None:
                if len(k2p) >= self.P:
                    self._grow(2 * self.P)
                p = k2p[k] = len(k2p)
            out[i] = p
        return out

    def _grow(self, new_p: int) -> None:
        """Double the partition axis: pad state arrays, rebuild the kernel
        (the next block jit-compiles at the new P)."""
        import jax.numpy as jnp
        old = jax.tree_util.tree_map(np.asarray, self.state)
        kern = NFAKernel(self.spec, self.kernel.sel_fns, self.kernel.having,
                         new_p, self.kernel.A, self.kernel.E)
        fresh = kern.init_state()
        self.state = jax.tree_util.tree_map(
            lambda f, o: jnp.asarray(
                np.concatenate([o, np.asarray(f)[o.shape[0]:]], axis=0)),
            fresh, old)
        self.kernel = kern
        self.P = new_p

    def _grow_slots(self, new_a: int) -> None:
        """Pad the slot axis of all (P, A) state leaves and rebuild."""
        import jax.numpy as jnp
        old = jax.tree_util.tree_map(np.asarray, self.state)
        kern = NFAKernel(self.spec, self.kernel.sel_fns, self.kernel.having,
                         self.P, new_a, self.kernel.E)
        fresh = kern.init_state()
        self.state = jax.tree_util.tree_map(
            lambda f, o: jnp.asarray(np.concatenate(
                [o, np.asarray(f)[:, o.shape[1]:]], axis=1))
            if o.ndim == 2 else jnp.asarray(o),
            fresh, old)
        self.kernel = kern

    # -- QueryPlan interface -------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        if batch.n:
            self._buffered.append((stream_id, batch))
        return []

    def finalize(self) -> list:
        if not self._buffered:
            return []
        bufs, self._buffered = self._buffered, []

        # 1. union columns over all buffered batches
        N = sum(b.n for _s, b in bufs)
        ts = np.empty(N, dtype=np.int64)
        seq = np.empty(N, dtype=np.int64)
        scode = np.empty(N, dtype=np.int32)
        part = np.empty(N, dtype=np.int32)
        cols: dict = {}
        for sid in self.spec.stream_ids:
            si = self._scode[sid]
            for a in self.rt.schemas[sid].attributes:
                cols[f"{si}.{a.name}"] = np.zeros(N, dtype=dtype_of(a.type))
        o = 0
        for sid, b in bufs:
            si = self._scode[sid]
            sl = slice(o, o + b.n)
            ts[sl] = b.timestamps
            seq[sl] = b.seqs if b.seqs is not None else np.arange(o, o + b.n)
            scode[sl] = si
            part[sl] = self.part_of(sid, b)
            for a in self.rt.schemas[sid].attributes:
                cols[f"{si}.{a.name}"][sl] = b.columns[a.name]
            o += b.n

        # 2. order by arrival, compute index-within-partition
        order = np.lexsort((seq,))
        ts, seq, scode, part = ts[order], seq[order], scode[order], part[order]
        for k in cols:
            cols[k] = cols[k][order]
        by_part = np.lexsort((seq, part))
        idx_within = np.empty(N, dtype=np.int64)
        sp = part[by_part]
        run_start = np.flatnonzero(np.r_[True, sp[1:] != sp[:-1]])
        run_id = np.cumsum(np.r_[True, sp[1:] != sp[:-1]]) - 1
        idx_within[by_part] = np.arange(N) - run_start[run_id]

        # 3. run dense (T, P) blocks (chunked if one partition hogs the batch)
        T_CAP = 512
        rows_out: list = []
        n_chunks = int(idx_within.max()) // T_CAP + 1
        for c in range(n_chunks):
            m = (idx_within >= c * T_CAP) & (idx_within < (c + 1) * T_CAP)
            if not m.any():
                continue
            t_local = (idx_within[m] - c * T_CAP).astype(np.int64)
            T = pow2_at_least(int(t_local.max()) + 1)
            ev = {"__ts__": np.zeros((T, self.P), np.int64),
                  "__seq__": np.zeros((T, self.P), np.int64),
                  "__scode__": np.full((T, self.P), -1, np.int32),
                  "__valid__": np.zeros((T, self.P), bool)}
            for k, v in cols.items():
                ev[k] = np.zeros((T, self.P), v.dtype)
            pm = part[m]
            ev["__ts__"][t_local, pm] = ts[m]
            ev["__seq__"][t_local, pm] = seq[m]
            ev["__scode__"][t_local, pm] = scode[m]
            ev["__valid__"][t_local, pm] = True
            for k, v in cols.items():
                ev[k][t_local, pm] = v[m]
            rows_out.extend(self._run_block(ev, T))

        return self._rows_to_batches(rows_out)

    def _run_block(self, ev: dict, T: int) -> list:
        """Run one dense block; retry (exactly — state is functional) with
        doubled match buffer / slots on overflow, so the kernel adapts to
        the workload without ever losing a match (until the documented
        A_CAP ceiling; emission lanes cannot overflow — completions park
        in their slot and drain over subsequent steps)."""
        from .nfa_device import _unpack_i64
        M = max(self._m_hint, pow2_at_least(2 * T, lo=16))
        while True:
            fn = self.kernel.block_fn(T, M)
            state2, out = fn(self.state, ev)
            ipack = np.asarray(out["i"])     # two device->host transfers
            fpack = np.asarray(out["f"]) if "f" in out else None
            n, ofs = int(ipack[0, 0]), int(ipack[0, 1])
            if n > M:
                M = pow2_at_least(n)
                continue
            if ofs > self._of_slots_seen and self.kernel.A < self.A_CAP:
                self._grow_slots(min(2 * self.kernel.A, self.A_CAP))
                continue
            if ofs > self._of_slots_seen:
                import warnings
                warnings.warn(
                    f"pattern {self.name!r}: pending-match slots hit the "
                    f"deviceSlotCap ceiling ({self.A_CAP}); {ofs} partial "
                    f"matches dropped so far (raise @app:deviceSlotCap)",
                    RuntimeWarning, stacklevel=2)
            break
        self._m_hint = M           # avoid recompiling next flush
        self._of_slots_seen = ofs
        self.state = state2
        valid = ipack[1] != 0                     # (M,)
        if not valid.any():
            return []
        row = {}
        ii, fi = 2, 0
        for nm in self.kernel.out_names:
            if fpack is not None and nm in self.kernel.f64_names:
                row[nm] = fpack[fi]; fi += 1
            else:
                row[nm] = ipack[ii]; ii += 1
        seqs = row["__seq__"][valid]
        hseqs = row["__head_seq__"][valid]
        tss = row["__timestamp__"][valid]
        data = {nm: _unpack_i64(row[nm], dtype_of(t))[valid]
                for nm, t in zip(self._names, self._types)}
        # same-event completions tie on seq; order them by head arrival
        # (reference emits pending-list == arrival order)
        o = np.lexsort((hseqs, seqs))
        return [(int(tss[i]), int(seqs[i]),
                 tuple(data[nm][i] for nm in self._names)) for i in o]

    def _rows_to_batches(self, rows: list) -> list:
        if not rows or self.events_for == ast.OutputEventsFor.EXPIRED:
            return []
        rows.sort(key=lambda r: r[1])
        if self.offset:
            rows = rows[self.offset:]
        if self.limit is not None:
            rows = rows[:self.limit]
        if not rows:
            return []
        n = len(rows)
        cols = {}
        for j, (nm, t) in enumerate(zip(self._names, self._types)):
            cols[nm] = np.asarray([r[2][j] for r in rows], dtype=dtype_of(t))
        batch = EventBatch(self.out_schema,
                           np.asarray([r[0] for r in rows], dtype=TIMESTAMP_DTYPE),
                           cols, n)
        return [OutputBatch(self.output_target, batch)]

    # -- snapshot ------------------------------------------------------------

    def state_dict(self) -> dict:
        st = jax.tree_util.tree_map(np.asarray, self.state)
        return {"state": st, "key_to_part": dict(self._key_to_part)}

    def load_state_dict(self, d: dict) -> None:
        import jax.numpy as jnp
        st = d["state"]
        p, a = st["active"].shape
        if p != self.P or a != self.kernel.A:  # snapshot taken after growth
            self.kernel = NFAKernel(self.spec, self.kernel.sel_fns,
                                    self.kernel.having, p, a, self.kernel.E)
            self.P = p
        self.state = jax.tree_util.tree_map(jnp.asarray, st)
        self._key_to_part = dict(d["key_to_part"])
        self._of_slots_seen = int(np.asarray(st["of_slots"]).sum())
