"""Device pattern/sequence query plan — host wrapper around NFAKernel.

Buffers per-stream micro-batches, merges them by global arrival seq,
buckets events into dense (T, P) blocks (one event per partition per scan
step), runs the jitted batched-NFA block, and compacts emitted matches
back into an output EventBatch.

The partition axis is 1 for plain pattern queries; partitioned queries
(`partition with (key of Stream) begin ... end`) set a key extractor and
a partition capacity so thousands of per-key NFA instances run as one
kernel (reference clones the whole query graph per key instead:
core:partition/PartitionRuntime.java:257-306).

Timestamps and seqs are shipped to the device as i32 offsets from
per-plan bases (TPU x64 is emulated; see nfa_device.py); the plan
rebases the persistent slot state host-side before offsets can overflow.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np

from ..query import ast
from .batch import EventBatch
from .expr import ExprError, MultiStreamContext, compile_expression
from .nfa_device import (ChainSpec, DeviceNFAUnsupported, LOCAL_SPAN,
                         NFAKernel, join64_np, lower_chain, pow2_at_least)
from .planner import (AGGREGATOR_NAMES, OutputBatch, PlanError, QueryPlan,
                      selector_has_aggregators)
from .schema import StreamSchema, TIMESTAMP_DTYPE, dtype_of
from .telemetry import call_kernel, env_nbytes

_I32 = np.int32


def _m_bucket(n: int) -> int:
    """Match-buffer capacity bucket: pow2 up to 16K, then 16K multiples —
    every pull through the tunnel pays per-byte, so over-allocating 2x at
    large n (pow2) wastes real time; finer buckets cost a rare recompile."""
    if n <= 16384:
        return pow2_at_least(n, lo=16)
    return -(-n // 16384) * 16384


def _m_bucket_chunk(n: int) -> int:
    """Chunked-flat blocks compile ~10s each through the tunnel: coarse
    64K buckets keep M stable flush-to-flush (a 16K-granular bucket
    recompiled whenever the match count drifted past the last bucket)."""
    if n <= 16384:
        return pow2_at_least(n, lo=16)
    return -(-n // 65536) * 65536


class DevicePatternPlan(QueryPlan):
    """from [every] e1=A[...] -> e2=B[...] within T — batched device NFA."""

    A_CAP = 512      # default adaptive slot-growth ceiling (@app:deviceSlotCap)

    def __init__(self, name: str, rt, q: ast.Query, state_input,
                 target: Optional[str], partitions: int = 1,
                 part_key_fns: Optional[dict] = None, slots: int = 16,
                 param_extra: Optional[dict] = None,
                 broadcast_events: bool = False,
                 params: Optional[dict] = None):
        from ..interp.engine import _collect_filters
        self.param_extra = param_extra
        self.broadcast_events = broadcast_events

        self.name = name
        self.rt = rt
        cap = ast.find_annotation(rt.app.annotations, "app:deviceSlotCap")
        if cap is not None:
            self.A_CAP = int(cap.element())
        prec = ast.find_annotation(rt.app.annotations, "app:devicePrecision")
        self.f64 = prec is not None and str(prec.element()).lower() == "f64"
        self.output_target = target
        self.events_for = getattr(q.output, "events_for",
                                  ast.OutputEventsFor.CURRENT)
        if q.rate is not None:
            raise DeviceNFAUnsupported("output rate limiting")
        if q.selector.group_by or q.selector.order_by \
                or selector_has_aggregators(q.selector):
            raise DeviceNFAUnsupported("group-by/order-by/aggregating selector")
        self.limit, self.offset = q.selector.limit, q.selector.offset

        self.spec: ChainSpec = lower_chain(
            state_input, rt.schemas, rt.strings,
            _collect_filters(state_input.state), param_extra=param_extra)
        self.input_streams = tuple(self.spec.stream_ids)

        # partitioning: key fn per input stream (row cols -> np int codes)
        self.P = partitions
        self.part_key_fns = part_key_fns        # stream_id -> fn(batch)->codes
        self._key_to_part: dict = {}            # key value -> partition index

        # multi-chip mesh: shard the partition axis (last axis of every
        # state leaf / event grid) over jax.devices() — the production
        # analog of the reference's per-key clone fan-out scaled across
        # chips (SURVEY §2.3 item 2: our DP ≅ their partitions)
        self.mesh = None
        mode = getattr(rt, "device_mesh", "auto")
        ndev = len(jax.devices())
        if mode == "always" or (mode == "auto" and ndev > 1
                                and partitions >= ndev):
            from jax.sharding import Mesh
            self.mesh = Mesh(np.array(jax.devices()), ("part",))
            self.P = -(-self.P // ndev) * ndev     # even shards

        # selector over capture refs
        sel = q.selector
        sctx = MultiStreamContext(self.spec.schemas, rt.strings,
                                  extra=dict(param_extra or {}))
        names, types, fns = [], [], []
        if sel.select_all:
            seen = set()
            for nd in self.spec.all_nodes:
                for a in self.spec.schemas[nd.ref].attributes:
                    nm = a.name if a.name not in seen else f"{nd.ref}_{a.name}"
                    seen.add(nm)
                    ce = compile_expression(
                        ast.Variable(a.name, stream_ref=nd.ref), sctx)
                    names.append(nm)
                    types.append(ce.type)
                    fns.append(ce)
        else:
            for oa in sel.attributes:
                try:
                    ce = compile_expression(oa.expr, sctx)
                except ExprError as e:
                    raise DeviceNFAUnsupported(f"selector: {e}")
                names.append(oa.name)
                types.append(ce.type)
                fns.append(ce)
        self._names, self._types = names, types
        having = None
        if sel.having is not None:
            import copy
            hctx = copy.copy(sctx)
            hctx.extra = {n: (n, t) for n, t in zip(names, types)}
            try:
                having = compile_expression(sel.having, hctx)
            except ExprError as e:
                raise DeviceNFAUnsupported(f"having: {e}")
        self.out_schema = StreamSchema(target or f"#{name}", tuple(
            ast.Attribute(n, t) for n, t in zip(names, types)))

        if params:
            # pad per-lane parameter vectors to the (possibly mesh-rounded)
            # lane count; padding lanes never match (they get zero params,
            # and the host routes by qid < n_queries anyway)
            params = {k: (np.concatenate([v, np.zeros(self.P - len(v),
                                                      v.dtype)])
                          if len(v) < self.P else v)
                      for k, v in params.items()}
        # unpartitioned chains also arm their pre-registered START slot
        # on a timer tick (the host matcher starts at plan start);
        # partitioned lanes arm on their key's first event only
        self._init_on_tick = part_key_fns is None
        self.kernel = NFAKernel(self.spec, dict(zip(names, fns)), having,
                                self.P, slots, f64=self.f64,
                                playback=rt._playback, params=params,
                                emit_qid=broadcast_events,
                                init_on_tick=self._init_on_tick)
        self.state = self._shard(self.kernel.init_state())
        self._start_anchor: Optional[int] = None   # init-slot arm time
        self._ts_base: Optional[int] = None
        self._seq_base: Optional[int] = None
        self._m_hint = 16           # last match-buffer capacity that sufficed
        self._of_slots_seen = 0     # accepted (at-cap) overflow totals
        self._next_deadline: Optional[int] = None   # absent-state wakeup
        self._last_seq = 0
        self._buffered: list = []   # (stream_id, EventBatch)
        self._scode = {sid: i for i, sid in enumerate(self.spec.stream_ids)}

        # ---- plan-family selection (docs/PERFORMANCE.md "Plan families").
        # A within-bounded every-head pattern with no partition key can run
        # STATELESS: every pending instance dies within W of its head, so
        # blocks replay the last W of events at the next flush and drop
        # completions at or before the previous flush's last seq.  Three
        # stateless execution families share that harness:
        #   chunk — split each flush into K own-chunks scanned by K
        #           parallel lanes with halo reads (sequential-in-T per
        #           lane; `__can_start__` keeps matches exactly-once);
        #   scan  — associative-scan SFA lowering (nfa_parallel.py):
        #           whole-flush next-pointer composition, O(log T) depth;
        #   dfa   — bit-packed multi-stride hybrid lowering: u32 symbol
        #           words + stride-4 precomposed block tables.
        # Eligibility analysis picks the cheapest sound family; the
        # sequential kernel ("seq") is the universal fallback, and the
        # autotuner sweeps the family as a geometry axis (@app:patternFamily
        # / tuning-cache `plan_family` force one explicitly).
        self._chunk_cfg = None
        self._tail: Optional[dict] = None       # replayed raw events
        self._prev_last_seq = -1
        self._chunk_A = slots
        self._chunk_E: Optional[int] = None
        self._kern_by_p: dict = {}
        self._par_kerns: dict = {}              # family -> kernel
        self._of_dropped = 0
        self._family_dispatches: dict = {}
        self._lane_dispatches = 0               # lane-vmapped block count
        self._lanes_last = 0                    # lane width of the last one
        # partitioned/fused lane bookkeeping (scan/dfa lane-vmap path):
        # per-key replay tails + per-key last-emitted completion seq, and
        # the per-lane single-arm resolution flags for non-`every` heads
        self._lane_tail: Optional[dict] = None
        self._lane_prev = np.zeros(0, dtype=np.int64)
        self._lane_F = 0
        self._arm_done: Optional[np.ndarray] = None
        self.family = "seq"
        self._partitioned = part_key_fns is not None or \
            (partitions != 1 and not broadcast_events)
        # hard gates: no stateless family can run these shapes — blocks
        # would need device state or a deterministic flush order
        hard = None
        if getattr(rt, "_async_workers", 1) != 1:
            hard = "async ingest workers (flush order not deterministic)"
        elif self.kernel.has_absent or self.spec.needs_init_slot:
            hard = "absent state (timer-driven deadlines need device state)"
        elif not all(p.within_ms is not None for p in self.spec.positions):
            hard = "position without a `within` bound"
        self.families: dict = {"seq": True}
        from .autotune import (chunk_lanes_for, pattern_family_for,
                               pipeline_depth_for)
        self._stateless_lanes = chunk_lanes_for(rt, q)
        if hard is not None:
            self.families.update({"chunk": hard, "scan": hard, "dfa": hard})
        else:
            from .nfa_parallel import classify_parallel
            par = classify_parallel(self.spec, self.kernel, rt.strings,
                                    param_extra)
            if self._partitioned:
                # per-key lanes ride ONE vmap of the flat scan/dfa block
                # ((L, F) grids, per-lane tails/dedup); chunk's lane axis
                # is already spent on own-chunks, and a non-`every` arm
                # would need per-key persistent state
                if not self.spec.every_head:
                    par = {f: ("non-`every` head with partitioned lanes "
                               "(per-key single-arm state)")
                           if v is True else v for f, v in par.items()}
                self.families["chunk"] = ("partitioned (the lane axis "
                                          "holds partition keys)")
            elif broadcast_events:
                # fused multi-query lanes vmap the same way: per-lane
                # `__qparam` constants, events broadcast
                self.families["chunk"] = "fused multi-query lane kernel"
            elif not self.spec.every_head:
                self.families["chunk"] = ("non-`every` head (single "
                                          "stateful arm)")
            else:
                self.families["chunk"] = True if self._stateless_lanes > 1 \
                    else "chunk lanes <= 1 (@app:deviceChunkLanes)"
            if self.mesh is not None and not self._partitioned \
                    and not broadcast_events:
                # partitioned/fused lane grids shard their LANE axis over
                # the mesh (_dispatch_par); only the flat P=1 block has
                # no axis to shard
                for f in ("scan", "dfa"):
                    if par.get(f) is True:
                        par[f] = ("multi-device mesh (flat block has no "
                                  "lane axis to shard)")
            self.families.update(par)
        want = pattern_family_for(rt, q)
        fam = self._choose_family(want)
        if fam != "seq":
            # fused groups route matches through finalize_multi, which
            # drains synchronously — no deferred-pull pipeline there
            self.pipeline_depth = 0 if broadcast_events \
                else pipeline_depth_for(rt, "pattern", q)
            self._enter_stateless(fam)
        # device grids shipped per block: only attrs some predicate or
        # capture row reads, per scode
        self._grid_attrs: list = sorted(self._needed_grid_attrs())

        # build-time validation: trace a tiny block so unsupported env keys
        # fail here (-> sequential fallback) instead of at first flush
        dummy = self._dense_dummy(T=2)
        jax.eval_shape(self.kernel.block_fn(2, 8), self.state, dummy)
        lane_mode = self._partitioned or self.broadcast_events
        while self.family in ("scan", "dfa"):
            # same guarantee for the parallel-in-time families: a lowering
            # surprise demotes to the NEXT sound family at build (each
            # candidate validated in turn), never at first flush
            try:
                jax.eval_shape(
                    self._parallel_kernel().block_fn(
                        (2, 8) if lane_mode else 8, 16),
                    {}, self._flat_dummy(8, L=2 if lane_mode else None))
                break
            except Exception as e:   # pragma: no cover - safety net
                import warnings
                self.families[self.family] = \
                    f"build validation failed: {e}"
                self._par_kerns.pop(self.family, None)
                pl = getattr(rt, "placement", None)
                if pl is not None:
                    pl.demote(name, "D-FAMILY",
                              f"plan family {self.family!r} failed build "
                              f"validation", cause=e,
                              alternative=self.family)
                fam = self._choose_family(None)
                warnings.warn(
                    f"pattern {name!r}: plan family {self.family!r} failed "
                    f"build validation ({e}); demoting to {fam!r}",
                    RuntimeWarning, stacklevel=2)
                if fam == "seq":
                    self.family = "seq"
                    self._chunk_cfg = None
                    self._pipe = None
                    self.retryable_finalize = False
                else:
                    self.family = fam

    # -- helpers -------------------------------------------------------------

    def _needed_grid_attrs(self) -> set:
        """(scode, attr, AttrType) triples whose (T, P) grids the kernel
        reads (predicate inputs + capture writes)."""
        from .nfa_device import _base_ref
        keys: set = set()
        for nd in self.spec.all_nodes:
            for ce in nd.pre_conjs + nd.step_conjs:
                keys.update(k for k in ce.reads if "." in k)
        keys.update(k for k in self.kernel._row_of if not k.startswith("__"))
        ref_scode = {nd.ref: nd.scode for nd in self.spec.all_nodes}
        ref_schema = self.spec.schemas
        out = set()
        for k in keys:
            refpart, attr = k.split(".", 1)
            ref, _idx = _base_ref(refpart)
            if ref in ref_scode and attr in ref_schema[ref].types:
                out.add((ref_scode[ref], attr, ref_schema[ref].type_of(attr)))
        return out

    def _part_sharding(self, ndim: int):
        from jax.sharding import NamedSharding, PartitionSpec
        if ndim == 0:
            return NamedSharding(self.mesh, PartitionSpec())
        return NamedSharding(self.mesh,
                             PartitionSpec(*((None,) * (ndim - 1) + ("part",))))

    def _lane_sharding(self, ndim: int):
        """Lane-MAJOR sharding for the vmapped scan/dfa grids: axis 0 is
        the lane axis (partition keys / fused queries), everything else
        replicates."""
        from jax.sharding import NamedSharding, PartitionSpec
        if ndim == 0:
            return NamedSharding(self.mesh, PartitionSpec())
        return NamedSharding(self.mesh,
                             PartitionSpec(*(("part",) + (None,) * (ndim - 1))))

    def _shard(self, tree):
        """Place every leaf with its partition-axis sharding (no-op when
        no mesh is configured).  Leaves whose last dim is not the lane
        axis — e.g. (T, 1) broadcast event grids — replicate."""
        if self.mesh is None:
            return tree

        def put(a):
            nd = np.ndim(a)
            if nd and np.shape(a)[-1] == self.P:
                return jax.device_put(a, self._part_sharding(nd))
            return jax.device_put(a, self._part_sharding(0))
        return jax.tree_util.tree_map(put, tree)

    def _np_dtype(self, t: ast.AttrType):
        if not self.f64 and t == ast.AttrType.DOUBLE:
            return np.float32
        return dtype_of(t)

    def _flat_dummy(self, F: int, L: Optional[int] = None) -> dict:
        """Tiny flat-block ev (the scan/dfa families' input layout) for
        build-time shape validation.  L adds the lane axis: partitioned
        grids carry per-lane event arrays; fused (broadcast) lanes share
        the event arrays and vary only params/qids/arm flags."""
        import jax.numpy as jnp
        per_lane_ev = L is not None and not self.broadcast_events
        fs = (L, F) if per_lane_ev else (F,)
        ss = (L,) if per_lane_ev else ()
        ls = (L,) if L is not None else ()
        ev = {"__flat.__ts__": jnp.zeros(fs, jnp.int32),
              "__flat.__seq__": jnp.zeros(fs, jnp.int32),
              "__nev__": jnp.zeros(ss, jnp.int32),
              "__prev_seq__": jnp.zeros(ss, jnp.int32),
              "__base_ts__": jnp.zeros((), jnp.int64),
              "__base_seq__": jnp.zeros((), jnp.int64)}
        if len(self.spec.stream_ids) > 1:
            ev["__flat.__scode__"] = jnp.zeros(fs, jnp.int32)
        for si, attr, t in self._grid_attrs:
            ev[f"__flat.{si}.{attr}"] = jnp.zeros(fs, self._np_dtype(t))
        for k, v in (self.kernel.params or {}).items():
            ev[f"__param.{k}"] = jnp.zeros(ls, np.asarray(v).dtype)
        if self.kernel.emit_qid:
            ev["__lane_qid__"] = jnp.zeros(ls, jnp.int32)
        if not self.spec.every_head:
            ev["__arm_done__"] = jnp.zeros(ls, jnp.int32)
        return ev

    def _dense_dummy(self, T: int) -> dict:
        import jax.numpy as jnp
        P = 1 if self.broadcast_events else self.P
        ev = {"__ts__": jnp.zeros((T, P), dtype=jnp.int32),
              "__seq__": jnp.zeros((T, P), dtype=jnp.int32),
              "__valid__": jnp.zeros((T, P), dtype=bool),
              "__base_ts__": jnp.zeros((), dtype=jnp.int64),
              "__base_seq__": jnp.zeros((), dtype=jnp.int64)}
        if len(self.spec.stream_ids) > 1:
            ev["__scode__"] = jnp.zeros((T, P), dtype=jnp.int32)
        for si, attr, t in self._grid_attrs:
            ev[f"{si}.{attr}"] = jnp.zeros((T, P), dtype=self._np_dtype(t))
        return ev

    @property
    def dropped(self) -> int:
        """Partial matches / emissions lost to capacity exhaustion — only
        possible once adaptive growth hits the A_CAP ceiling.  Carried in
        device state (host-side counter in chunked mode), so snapshot-safe."""
        if self._chunk_cfg is not None:
            return self._of_dropped
        return int(np.asarray(self.state["of_slots"]).sum())

    def part_of(self, stream_id: str, batch: EventBatch) -> np.ndarray:
        """Partition index per event; grows the key map (host side).
        Vectorized: the python dict is consulted once per DISTINCT key."""
        if self.part_key_fns is None:
            return np.zeros(batch.n, dtype=_I32)
        keys = self.part_key_fns[stream_id](batch)
        uniq, inv = np.unique(keys, return_inverse=True)
        k2p = self._key_to_part
        parts_u = np.empty(len(uniq), dtype=_I32)
        for j, k in enumerate(uniq.tolist()):
            p = k2p.get(k)
            if p is None:
                # stateless lane families size their (L, F) grid per
                # flush: a hot-added key is just a new lane id — no
                # device-state growth, no recompile below the next
                # pow2 lane bucket
                if self._chunk_cfg is None and len(k2p) >= self.P:
                    self._grow(2 * self.P)
                p = k2p[k] = len(k2p)
            parts_u[j] = p
        return parts_u[inv]

    def _grow(self, new_p: int) -> None:
        """Double the partition axis (last axis of every state leaf): pad,
        rebuild the kernel (the next block jit-compiles at the new P)."""
        import jax.numpy as jnp
        if self.mesh is not None:
            nd = len(self.mesh.devices)
            new_p = -(-new_p // nd) * nd
        old = jax.tree_util.tree_map(np.asarray, self.state)
        kern = NFAKernel(self.spec, self.kernel.sel_fns, self.kernel.having,
                         new_p, self.kernel.A, self.kernel.E, f64=self.f64,
                         playback=self.rt._playback, params=self.kernel.params,
                         emit_qid=self.kernel.emit_qid,
                         init_on_tick=self._init_on_tick)
        fresh = kern.init_state()
        self.state = self._shard(jax.tree_util.tree_map(
            lambda f, o: np.concatenate(
                [o, np.asarray(f)[..., o.shape[-1]:]], axis=-1),
            fresh, old))
        self.kernel = kern
        self.P = new_p

    def _grow_slots(self, new_a: int) -> None:
        """Pad the slot axis of per-slot state leaves and rebuild."""
        import jax.numpy as jnp
        old = jax.tree_util.tree_map(np.asarray, self.state)
        kern = NFAKernel(self.spec, self.kernel.sel_fns, self.kernel.having,
                         self.P, new_a, self.kernel.E, f64=self.f64,
                         playback=self.rt._playback, params=self.kernel.params,
                         emit_qid=self.kernel.emit_qid,
                         init_on_tick=self._init_on_tick)
        fresh = kern.init_state()

        def pad(f, o):
            ax = {2: 0, 3: 1}.get(o.ndim)
            if ax is None or f.shape == o.shape:
                return jnp.asarray(o)
            filler = np.asarray(f)[(slice(None),) * ax + (slice(o.shape[ax], None),)]
            return np.concatenate([o, filler], axis=ax)
        self.state = self._shard(jax.tree_util.tree_map(pad, fresh, old))
        self.kernel = kern

    def _rebuild_kernel(self, E: int) -> None:
        import jax.numpy as jnp
        self.kernel = NFAKernel(self.spec, self.kernel.sel_fns,
                                self.kernel.having, self.P, self.kernel.A,
                                E, f64=self.f64, playback=self.rt._playback,
                                params=self.kernel.params,
                                emit_qid=self.kernel.emit_qid,
                                init_on_tick=self._init_on_tick)

    # -- plan families ---------------------------------------------------

    # auto-selection preference: cheapest sound family first, measured —
    # the associative-scan lowering beats the bit-packed multi-stride
    # tables on the shipping backends (bench kernel_eps_by_family:
    # static chain, scan ~3.4M eps vs dfa ~2.9M vs chunk ~57k on
    # CPU), and both beat K sequential chunk lanes everywhere; "seq" is
    # the universal fallback.  The autotuner's plan_family knob overrides
    # per app when a sweep finds otherwise on a given device.
    FAMILY_ORDER = ("scan", "dfa", "chunk")

    def _choose_family(self, want: Optional[str]) -> str:
        if want is not None:
            if want == "seq" or self.families.get(want) is True:
                return want
            import warnings
            pl = getattr(getattr(self, "rt", None), "placement", None)
            if pl is not None:
                pl.demote(self.name, "D-FAMILY",
                          f"requested plan family {want!r} is not "
                          f"eligible: {self.families.get(want)}",
                          alternative=want)
            warnings.warn(
                f"pattern {self.name!r}: requested plan family {want!r} is "
                f"not eligible ({self.families.get(want)}); falling back to "
                f"automatic selection", RuntimeWarning, stacklevel=2)
        for f in self.FAMILY_ORDER:
            if self.families.get(f) is True:
                return f
        return "seq"

    def _enter_stateless(self, fam: str) -> None:
        """Engage a stateless family (chunk/scan/dfa): blocks carry no
        device state, cross-flush continuity = tail replay + seq dedup,
        and finalize rolls its bookkeeping back on failure so the
        degradation ladder may halve and retry the flush."""
        self.family = fam
        if self._chunk_cfg is None:
            self._chunk_cfg = {
                "W": max(p.within_ms for p in self.spec.positions),
                "lanes": max(2, self._stateless_lanes)}
        if self._pipe is None:
            from .pipeline import DispatchPipeline
            self._pipe = DispatchPipeline(
                self.name, lambda e: [self._materialize_chunk(e)],
                depth=self.pipeline_depth)
        if not self.spec.every_head and self._arm_done is None:
            # non-`every`: ONE instance per lane ever; the device reports
            # resolution through the meta flag and the host stops
            # dispatching once every lane's arm is resolved
            nl = self.P if self.broadcast_events else 1
            self._arm_done = np.zeros(nl, dtype=bool)
        self.retryable_finalize = True

    def _set_family(self, fam: str) -> None:
        """Adaptive-geometry family switch (autotuner / regeometry).
        Stateless<->stateless moves are flush-boundary output-invariant
        (all three share the tail/dedup bookkeeping); seq<->stateless
        switches only before the plan has touched data (the persistent
        slot state and the replay tail don't interconvert)."""
        import warnings
        if fam == self.family:
            return
        if fam != "seq" and self.families.get(fam) is not True:
            warnings.warn(
                f"pattern {self.name!r}: plan family {fam!r} not eligible "
                f"({self.families.get(fam)}); keeping {self.family!r}",
                RuntimeWarning, stacklevel=2)
            return
        stateless = ("chunk", "scan", "dfa")
        if self.family in stateless and fam in stateless:
            self.family = fam
            return
        if self._ts_base is None and self._tail is None \
                and self._lane_tail is None and not self._buffered:
            if fam == "seq":
                self.family = "seq"
                self._chunk_cfg = None
                self._pipe = None
                self.retryable_finalize = False
            else:
                self._enter_stateless(fam)
            return
        warnings.warn(
            f"pattern {self.name!r}: cannot switch plan family "
            f"{self.family!r} -> {fam!r} mid-stream (device state and the "
            f"replay tail do not interconvert)", RuntimeWarning,
            stacklevel=2)

    def _parallel_kernel(self):
        """Build (and cache) the parallel-in-time kernel for the current
        scan/dfa family — shares the NFAKernel's selector/having/output
        metadata so packed blocks unpack identically."""
        kern = self._par_kerns.get(self.family)
        if kern is None:
            from .nfa_parallel import ParallelChainKernel, lower_parallel
            prog = lower_parallel(self.spec, self.rt.strings,
                                  self.param_extra)
            kern = ParallelChainKernel(prog, self.kernel,
                                       family=self.family)
            self._par_kerns[self.family] = kern
        return kern

    def _rebase(self, min_ts: int, min_seq: int) -> None:
        """Shift the plan's ts/seq bases forward and adjust persistent slot
        offsets so i32 locals never overflow.  Ancient slots clamp to
        -LOCAL_SPAN (their age saturates; `within` then expires them)."""
        import jax.numpy as jnp
        st = {k: np.asarray(v) for k, v in self.state.items()}
        if self._ts_base is not None and min_ts > self._ts_base:
            d = min_ts - self._ts_base
            no_first = st["first_ts"] == np.int32(LOCAL_SPAN)  # NO_FIRST
            st["first_ts"] = np.where(no_first, st["first_ts"], np.maximum(
                st["first_ts"].astype(np.int64) - d, -LOCAL_SPAN)).astype(_I32)
            if st["dl"].size:
                no_dl = st["dl"] == np.int32(2**31 - 1)
                st["dl"] = np.where(
                    no_dl, st["dl"],
                    np.maximum(st["dl"].astype(np.int64) - d,
                               -LOCAL_SPAN).astype(_I32))
            self._ts_base = min_ts
        if self._seq_base is not None and min_seq > self._seq_base:
            d = min_seq - self._seq_base
            st["head_seq"] = np.maximum(
                st["head_seq"].astype(np.int64) - d, -LOCAL_SPAN).astype(_I32)
            self._seq_base = min_seq
        self.state = self._shard(st)

    # -- telemetry ---------------------------------------------------------

    def _call_block(self, kern: NFAKernel, T: int, M: int, st, ev):
        """Invoke one jitted NFA block recording compile/kernel stage,
        block-cache hit/miss, and the H2D payload size."""
        self.rt.inject("dispatch", self.name)   # fault-injection boundary
        stats = self.rt.stats
        prof = self.rt.profiler
        if not stats.enabled and prof is None:
            return kern.block_fn(T, M)(st, ev)
        hit = (T, M) in kern._block_cache
        fn = kern.block_fn(T, M)
        return call_kernel(stats, self.name, fn, (st, ev),
                           cache_hit=hit, nbytes=env_nbytes(ev),
                           prof=prof)

    def device_metrics(self) -> dict:
        """Sampled device gauges: lane occupancy + state-frontier width
        (one D2H pull of `occ`), partition-key fill, capacity drops."""
        d = {"lanes_total": int(self.P)}
        if self._chunk_cfg is None:
            d.update(self.kernel.occupancy(self.state))
        if self.part_key_fns is not None:
            # distinct from lanes_active (lanes holding LIVE partial
            # matches): keys ever assigned to a lane
            d["keys_assigned"] = len(self._key_to_part)
        d["dropped_partials"] = int(self.dropped)
        # plan-family gauges: the selected execution family (string —
        # statistics() only; Prometheus skips non-numerics), per-family
        # dispatch counts, and eligibility reasons for rejected families
        d["plan_family"] = self.family
        for f, n in self._family_dispatches.items():
            d[f"dispatches_{f}"] = int(n)
        if self._lane_dispatches:
            # lane-vmapped scan/dfa blocks (partitioned keys / fused
            # queries ride ONE vmap of the flat block over the lanes)
            d["dispatches_lane_vmapped"] = int(self._lane_dispatches)
            d["lanes_last_dispatch"] = int(self._lanes_last)
        inel = {f: r for f, r in self.families.items() if r is not True}
        if inel:
            d["family_ineligible"] = inel
        return d

    # -- QueryPlan interface -------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        if batch.n:
            self._buffered.append((stream_id, batch))
        return []

    def finalize(self) -> list:
        if self._chunk_cfg is None or not self._buffered:
            return self._rows_to_batches(self._finalize_chunks())
        # chunked mode is retryable (degradation ladder): blocks carry no
        # device state, and _run_chunked_flat rolls back its host-side
        # tail/seq bookkeeping on a dispatch failure — so restoring the
        # input buffer makes a failed flush fully re-runnable (possibly
        # split in half by the runtime)
        snapshot = list(self._buffered)
        try:
            return self._rows_to_batches(self._finalize_chunks())
        except Exception:
            self._buffered = snapshot
            raise

    def _finalize_chunks(self) -> list:
        if not self._buffered:
            return []
        if self.spec.needs_init_slot and self._init_on_tick:
            # pin the START anchor while _buffered still holds the tape
            # (pre-clock playback anchors at the earliest buffered event;
            # after the pop the fallback would be the wall clock — review r5)
            self._anchor_ms()
        bufs, self._buffered = self._buffered, []

        with self.rt.stats.stage("host_build", plan=self.name):
            # 1. union columns over all buffered batches
            N = sum(b.n for _s, b in bufs)
            ts = np.empty(N, dtype=np.int64)
            seq = np.empty(N, dtype=np.int64)
            scode = np.empty(N, dtype=_I32)
            part = np.empty(N, dtype=_I32)
            cols: dict = {}
            for si, attr, t in self._grid_attrs:
                cols[f"{si}.{attr}"] = np.zeros(N, dtype=self._np_dtype(t))
            o = 0
            for sid, b in bufs:
                si = self._scode[sid]
                sl = slice(o, o + b.n)
                ts[sl] = b.timestamps
                seq[sl] = b.seqs if b.seqs is not None \
                    else np.arange(o, o + b.n)
                scode[sl] = si
                part[sl] = self.part_of(sid, b)
                for sj, attr, _t in self._grid_attrs:
                    if sj == si:
                        cols[f"{si}.{attr}"][sl] = b.columns[attr]
                o += b.n

            # 2. order by arrival, compute index-within-partition (broadcast
            # mode: every lane sees every event, so the grid is (T, 1))
            order = np.lexsort((seq,))
            ts, seq, scode, part = (ts[order], seq[order], scode[order],
                                    part[order])
            for k in cols:
                cols[k] = cols[k][order]
        if self._chunk_cfg is not None:
            return self._run_chunked_flat(ts, seq, scode, cols, part)
        with self.rt.stats.stage("host_build", plan=self.name):
            if self.broadcast_events:
                idx_within = np.arange(N, dtype=np.int64)
                part = np.zeros(N, dtype=_I32)
            else:
                by_part = np.lexsort((seq, part))
                idx_within = np.empty(N, dtype=np.int64)
                sp = part[by_part]
                run_start = np.flatnonzero(np.r_[True, sp[1:] != sp[:-1]])
                run_id = np.cumsum(np.r_[True, sp[1:] != sp[:-1]]) - 1
                idx_within[by_part] = np.arange(N) - run_start[run_id]

            # 3. i32 offset bases (+ rebase persistent state before overflow).
            # The base is chosen from the flush MAX so headroom is always
            # restored even when a stale event pins the minimum; events older
            # than base - LOCAL_SPAN clamp low (their age saturates and
            # `within` expires them — never a silent wrap).
            budget = LOCAL_SPAN - (1 << 16)
            if self._ts_base is None:
                lo = int(ts.min())
                if self.spec.needs_init_slot and self._init_on_tick:
                    lo = min(lo, self._anchor_ms())
                self._ts_base = max(lo, int(ts.max()) - budget)
                self._seq_base = max(int(seq.min()), int(seq.max()) - budget)
            if int(ts.max()) - self._ts_base >= budget \
                    or int(seq.max()) - self._seq_base >= budget:
                self._rebase(max(int(ts.min()), int(ts.max()) - budget),
                             max(int(seq.min()), int(seq.max()) - budget))
            ts32 = np.clip(ts - self._ts_base, -LOCAL_SPAN, LOCAL_SPAN).astype(_I32)
            seq32 = np.clip(seq - self._seq_base, -LOCAL_SPAN, LOCAL_SPAN).astype(_I32)
            self._last_seq = max(self._last_seq, int(seq.max()))

            # 4. run dense (T, P) blocks (chunked if one partition hogs the
            # batch); T_CAP widens for small P so single-partition patterns
            # amortize per-block overhead over longer scans
            T_CAP = min(8192, max(512, (1 << 19) // max(self.P, 1)))
            if self.broadcast_events:
                T_CAP = 4096
            GW = 1 if self.broadcast_events else self.P    # grid width
            multi = len(self.spec.stream_ids) > 1
            chunk_evs: list = []
            n_chunks = int(idx_within.max()) // T_CAP + 1
            for c in range(n_chunks):
                m = (idx_within >= c * T_CAP) & (idx_within < (c + 1) * T_CAP)
                if not m.any():
                    continue
                t_local = (idx_within[m] - c * T_CAP).astype(np.int64)
                T = pow2_at_least(int(t_local.max()) + 1)
                ev = {"__ts__": np.zeros((T, GW), _I32),
                      "__seq__": np.zeros((T, GW), _I32),
                      "__valid__": np.zeros((T, GW), bool)}
                if multi:
                    ev["__scode__"] = np.full((T, GW), -1, _I32)
                for k, v in cols.items():
                    ev[k] = np.zeros((T, GW), v.dtype)
                pm = part[m]
                ev["__ts__"][t_local, pm] = ts32[m]
                ev["__seq__"][t_local, pm] = seq32[m]
                if multi:
                    ev["__scode__"][t_local, pm] = scode[m]
                ev["__valid__"][t_local, pm] = True
                for k, v in cols.items():
                    ev[k][t_local, pm] = v[m]
                ev["__base_ts__"] = np.int64(self._ts_base)
                ev["__base_seq__"] = np.int64(self._seq_base)
                if self.spec.needs_init_slot and self._init_on_tick:
                    ev["__anchor__"] = np.int32(np.clip(
                        self._anchor_ms() - self._ts_base,
                        -LOCAL_SPAN, LOCAL_SPAN))
                chunk_evs.append((ev, T))

        return self._run_chunks(chunk_evs)

    def _run_chunks(self, chunk_evs: list) -> list:
        """Dispatch ALL blocks first (device state threads functionally),
        then pull outputs — async D2H copies overlap the tunnel's ~100 ms
        fixed latency (measured 3.3x on back-to-back pulls).

        Retries are exact because state is functional: a match-buffer
        overflow re-runs only that block from its saved pre-state (state
        evolution is M-independent); pending-slot exhaustion grows A and
        restarts the chain from the exhausted block (dropped heads change
        downstream state)."""
        results: list = [None] * len(chunk_evs)
        i = 0
        while i < len(chunk_evs):
            dispatched = []
            st = self.state
            for j in range(i, len(chunk_evs)):
                ev, T = chunk_evs[j]
                ev = self._shard(ev)
                if self.broadcast_events:
                    # multi-query lanes are matchy and this kernel costs
                    # ~17s to compile: size M generously in pow2 so the
                    # steady state reuses ONE compiled block
                    M = max(self._m_hint, pow2_at_least(32 * T))
                else:
                    M = max(self._m_hint, _m_bucket(2 * T))
                pre = st
                st, out = self._call_block(self.kernel, T, M, pre, ev)
                self._family_dispatches["seq"] = \
                    self._family_dispatches.get("seq", 0) + 1
                from .pipeline import start_d2h
                start_d2h(out, keys=("i",))   # pull overlaps the compute
                dispatched.append((j, pre, ev, T, M, out))
            restart = None
            for j, pre, ev, T, M, out in dispatched:
                with self.rt.stats.stage("transfer", plan=self.name):
                    ipack = np.asarray(out["i"])   # ONE D2H transfer
                    fpack = np.asarray(out["f"]) if "f" in out else None
                n, ofs, ofl = (int(ipack[0, 0]), int(ipack[0, 1]),
                               int(ipack[0, 2]))
                while n > M:                   # exact re-run, bigger buffer
                    M = pow2_at_least(n) if self.broadcast_events \
                        else _m_bucket(n)
                    _st2, out = self._call_block(self.kernel, T, M, pre, ev)
                    with self.rt.stats.stage("transfer", plan=self.name):
                        ipack = np.asarray(out["i"])
                        fpack = np.asarray(out["f"]) if "f" in out else None
                    n, ofs, ofl = (int(ipack[0, 0]), int(ipack[0, 1]),
                                   int(ipack[0, 2]))
                self._m_hint = max(self._m_hint, M)
                if ofs > self._of_slots_seen and self.kernel.A < self.A_CAP:
                    self.state = pre
                    self._grow_slots(min(2 * self.kernel.A, self.A_CAP))
                    restart = j
                    break
                if ofl > 0:
                    # a count-survivor emission burst outran the E lanes:
                    # widen E (recompile) and re-run from this block
                    self.state = pre
                    self._rebuild_kernel(E=self.kernel.E * 2)
                    restart = j
                    break
                if ofs > self._of_slots_seen:
                    import warnings
                    warnings.warn(
                        f"pattern {self.name!r}: pending-match slots hit the "
                        f"deviceSlotCap ceiling ({self.A_CAP}); {ofs} partial "
                        f"matches dropped so far (raise @app:deviceSlotCap)",
                        RuntimeWarning, stacklevel=2)
                    self._of_slots_seen = ofs
                dlm = int(ipack[0, 3])
                self._next_deadline = (None if dlm >= 2**31 - 1
                                       else self._ts_base + dlm)
                results[j] = self._unpack_block(ipack, fpack, n)
            if restart is None:
                self.state = st
                break
            i = restart
        return results

    # -- chunked-halo execution (stateless, within-bounded patterns) -----

    def _chunk_kernel(self, K: int) -> NFAKernel:
        kern = self._kern_by_p.get(K)
        if kern is None or kern.A != self._chunk_A \
                or (self._chunk_E is not None and kern.E != self._chunk_E):
            kern = NFAKernel(self.spec, self.kernel.sel_fns,
                             self.kernel.having, K, self._chunk_A,
                             self._chunk_E, f64=self.f64,
                             playback=self.rt._playback)
            self._kern_by_p[K] = kern
        return kern

    def _run_chunked_flat(self, ts, seq, scode, cols, part=None) -> list:
        """One stateless flat block per flush: [replayed tail | new events]
        split into K own-chunks, gathered into lanes on device.  Blocks
        carry no device state, so flushes pipeline independently
        (@app:devicePipeline) and retries are self-contained.  A dispatch
        failure rolls the host-side tail/seq bookkeeping back so the
        runtime's degradation ladder can re-run the flush.

        Partitioned patterns on a scan/dfa family route through the
        lane-grid variant instead: each key's events form an independent
        sub-stream, laid out as one (L, F) grid and executed by ONE vmap
        of the flat block over the lane axis."""
        if self._partitioned and self.family in ("scan", "dfa"):
            return self._run_lanes_flat(ts, seq, scode, cols, part)
        saved = (self._tail, self._prev_last_seq, self._last_seq,
                 getattr(self, "_chunk_F", 0))
        try:
            return self._run_chunked_flat_inner(ts, seq, scode, cols)
        except Exception:
            (self._tail, self._prev_last_seq, self._last_seq,
             self._chunk_F) = saved
            raise

    def _run_chunked_flat_inner(self, ts, seq, scode, cols) -> list:
        fam = self.family
        with self.rt.stats.stage("host_build", plan=self.name):
            cfg = self._chunk_cfg
            W = int(cfg["W"])
            if self._tail is not None:
                ts = np.concatenate([self._tail["ts"], ts])
                seq = np.concatenate([self._tail["seq"], seq])
                scode = np.concatenate([self._tail["scode"], scode])
                cols = {k: np.concatenate([self._tail["cols"][k], v])
                        for k, v in cols.items()}
            N = len(ts)
            ts_mono = np.maximum.accumulate(ts)
            # `within` compares RAW event timestamps, but halo/tail bounds
            # search the running max — a regressed (out-of-order) timestamp
            # could place a still-completable event past the searched bound.
            # Widening the window by the worst regression keeps every such
            # event inside the halo/tail (over-covering is harmless).
            W = W + int(np.max(ts_mono - ts)) if N else W

            K = CS = H = T = None
            if fam == "chunk":
                # lane geometry: halo-dominated data (few events per W)
                # gets fewer, longer chunks; K buckets to pow2 so kernels
                # are reused
                def _halo(K: int):
                    CS = -(-N // K)
                    ends = np.unique(np.minimum(np.arange(1, K + 1) * CS, N))
                    ends = ends[ends > 0]
                    to = np.searchsorted(ts_mono, ts_mono[ends - 1] + W,
                                         side="right")
                    return CS, int(np.max(to - ends))
                # K rides pow2 buckets: latency-capped ingest produces
                # VARIABLE small flushes, and every distinct K is a fresh
                # kernel compile (~10 s through the tunnel); empty lanes
                # are free
                K = min(int(cfg["lanes"]), pow2_at_least(max(1, N), lo=8))
                CS, H = _halo(K)
                if CS < H:
                    # halo-dominated: fewer, longer chunks (lo=8 keeps the
                    # K bucket set tiny — empty lanes are free, fresh
                    # compiles through the tunnel are not)
                    K = min(int(cfg["lanes"]),
                            pow2_at_least(max(1, N // max(H, 1)), lo=8))
                    CS, H = _halo(K)
                if self.mesh is not None:
                    # lane axis shards over the mesh: K must divide evenly
                    # over the device count (K = min(lanes, N) is arbitrary)
                    nd = self.mesh.devices.size
                    if K % nd:
                        K = -(-K // nd) * nd
                        CS, H = _halo(K)
                T = pow2_at_least(CS + H, lo=64)

            # fresh i32 bases every flush (no persistent device state)
            ts_base = int(ts_mono[0])
            seq_base = int(seq[0])
            ts32 = np.clip(ts - ts_base, -LOCAL_SPAN, LOCAL_SPAN).astype(_I32)
            self._last_seq = max(self._last_seq, int(seq[-1]))
            # completions at or before the previous flush's last seq are
            # replays — suppressed ON DEVICE so they never cross the tunnel
            prev_off = np.int32(np.clip(self._prev_last_seq - seq_base,
                                        -LOCAL_SPAN, LOCAL_SPAN))

            # flat-buffer capacity: fine-granular bucket + one granule of
            # headroom, STICKY per plan — the replay tail appearing after
            # flush 1 (or drifting in size) must not change F, because every
            # distinct F is a ~10s recompile through the tunnel.  Shrinks only
            # when the flush size drops 4x (batch regime change).
            f_min = (N // 2048 + 2) * 2048
            F = max(getattr(self, "_chunk_F", 0), f_min)
            if F > 4 * f_min:
                F = f_min
            self._chunk_F = F

            def pad(a):
                out = np.zeros(F, dtype=a.dtype)
                out[:N] = a
                return out
            ev = {"__flat.__ts__": pad(ts32),
                  "__nev__": np.int32(N),
                  "__prev_seq__": prev_off,
                  "__base_ts__": np.int64(ts_base),
                  "__base_seq__": np.int64(seq_base)}
            if fam == "chunk":
                ev["__cs__"] = np.int32(CS)
            if fam == "chunk" and seq[-1] - seq[0] == N - 1:
                # consecutive seqs derive on device from one scalar.
                # Chunk-family only: output events consume seqs, so flush
                # 2+ always lands on the explicit-seq variant anyway —
                # the scan/dfa families ship it from flush 1 and save a
                # whole structural recompile (~3 s CPU / ~10 s tunnel)
                # for 4 bytes/event of upload
                ev["__seq0__"] = np.int32(0)
            else:
                ev["__flat.__seq__"] = pad(
                    np.clip(seq - seq_base, -LOCAL_SPAN, LOCAL_SPAN).astype(_I32))
            if len(self.spec.stream_ids) > 1:
                ev["__flat.__scode__"] = pad(scode)
            for k, v in cols.items():
                ev[f"__flat.{k}"] = pad(v)

            last_ts = int(ts_mono[-1])
            keep = ts_mono >= last_ts - W
            self._tail = {"ts": ts[keep], "seq": seq[keep],
                          "scode": scode[keep],
                          "cols": {k: v[keep] for k, v in cols.items()}}
            self._prev_last_seq = int(seq[-1])

        # M sizing: the first flush guesses from N (could retry once);
        # after that the hint PINS it — an N-based floor would drift
        # across 64K buckets as the replay tail varies, and every drift
        # is a ~10s recompile through the tunnel
        if fam != "chunk":
            # scan/dfa: one candidate completion per head (times the
            # final count's emission lanes), so M = F rarely overflows
            # and riding the sticky F bucket means M never recompiles on
            # its own; a final-count burst retries with a bigger M
            lanes = None
            if self.broadcast_events:
                if self._arm_done is not None and self._arm_done.all():
                    return []      # every lane's single arm is resolved
                lanes = self.P
                for k, v in (self.kernel.params or {}).items():
                    ev[f"__param.{k}"] = np.asarray(v)
                ev["__lane_qid__"] = np.arange(self.P, dtype=_I32)
                if self._arm_done is not None:
                    ev["__arm_done__"] = self._arm_done.astype(_I32)
            elif self._arm_done is not None:
                if self._arm_done.all():
                    return []      # the one non-`every` arm is resolved
                ev["__arm_done__"] = np.int32(0)
            return self._pipe.push(self._dispatch_par(
                ev, F, F, ts_base, seq_base, lanes=lanes))
        M = (self._m_hint if self._m_hint >= 16384
             else max(self._m_hint, _m_bucket_chunk(N)))
        return self._pipe.push(self._dispatch_chunk(
            ev, K, T, M, ts_base, seq_base))

    def _run_lanes_flat(self, ts, seq, scode, cols, part) -> list:
        """Partitioned scan/dfa: each key's events are an independent
        sub-stream — ONE (L, F) lane grid, ONE vmapped flat block, with
        per-lane replay tails and per-lane completion-seq dedup.  A
        dispatch failure rolls the per-lane bookkeeping back so the
        degradation ladder can re-run the flush."""
        saved = (self._lane_tail, self._lane_prev.copy(), self._last_seq,
                 self._lane_F)
        try:
            return self._run_lanes_flat_inner(ts, seq, scode, cols, part)
        except Exception:
            (self._lane_tail, self._lane_prev, self._last_seq,
             self._lane_F) = saved
            raise

    def _run_lanes_flat_inner(self, ts, seq, scode, cols, part) -> list:
        with self.rt.stats.stage("host_build", plan=self.name):
            W0 = int(self._chunk_cfg["W"])
            tl = self._lane_tail
            held = None
            if tl is not None:
                # only lanes with NEW events this flush replay their
                # tail; a quiet lane cannot produce a new completion
                # (everything it could emit is at or before its prev
                # seq), and letting its old events into the flush would
                # pin the shared i32 ts/seq bases forever (review
                # finding: a long-quiet lane saturated every live
                # lane's offsets at the 2^30 clip)
                active = np.isin(tl["part"], np.unique(part))
                if not active.all():
                    inactive = ~active
                    held = {"ts": tl["ts"][inactive],
                            "seq": tl["seq"][inactive],
                            "scode": tl["scode"][inactive],
                            "part": tl["part"][inactive],
                            "cols": {k: v[inactive]
                                     for k, v in tl["cols"].items()}}
                    tl = {"ts": tl["ts"][active], "seq": tl["seq"][active],
                          "scode": tl["scode"][active],
                          "part": tl["part"][active],
                          "cols": {k: v[active]
                                   for k, v in tl["cols"].items()}}
                ts = np.concatenate([tl["ts"], ts])
                seq = np.concatenate([tl["seq"], seq])
                scode = np.concatenate([tl["scode"], scode])
                part = np.concatenate([tl["part"], part])
                cols = {k: np.concatenate([tl["cols"][k], v])
                        for k, v in cols.items()}
            N = len(ts)
            order = np.lexsort((seq, part))
            ts, seq, scode, part = (ts[order], seq[order], scode[order],
                                    part[order])
            cols = {k: v[order] for k, v in cols.items()}
            change = np.r_[True, part[1:] != part[:-1]]
            run_id = np.cumsum(change) - 1
            run_start = np.flatnonzero(change)
            lane_ids = part[run_start].astype(np.int64)
            counts = np.diff(np.r_[run_start, N])
            idx_within = np.arange(N) - run_start[run_id]
            Lr = len(lane_ids)
            run_end = run_start + counts - 1

            # per-lane running-max ts in ONE pass (offset trick): feeds
            # the tail-retention bound and the out-of-order `within`
            # widening, exactly like the flat path's global cummax
            span = int(ts.max()) - int(ts.min()) + 1
            sh = ts.astype(np.int64) + run_id.astype(np.int64) * span
            tsmono = np.maximum.accumulate(sh) \
                - run_id.astype(np.int64) * span
            W = W0 + int(np.max(tsmono - ts))

            # lane-grid geometry: the lane axis pads to pow2 (hot-adding
            # a key keeps the compiled (L, F) shape until the count
            # crosses the next pow2 — no per-key recompile), and F rides
            # a sticky 64-granule bucket so tail drift never recompiles:
            # finer than pow2 because every padded cell multiplies by
            # the lane count (pow2 wasted up to 2x the whole grid)
            fm = int(counts.max())
            f_min = pow2_at_least(fm, lo=16) if fm <= 64 \
                else (fm // 64 + 2) * 64
            F = max(self._lane_F, f_min)
            if F > 4 * f_min:
                F = f_min
            self._lane_F = F
            Lpad = pow2_at_least(max(Lr, 1), lo=8)
            if self.mesh is not None:
                nd = self.mesh.devices.size
                Lpad = -(-Lpad // nd) * nd      # even lane shards

            # bases anchor at the flush MAX with i32 headroom (like the
            # dense path): a lane resuming after a >2^30 ms / seq gap
            # saturates ITS stale offsets low — which reads as "ancient,
            # expired, already-deduped" on device, the conservative and
            # host-identical outcome — instead of saturating every live
            # lane's offsets high
            budget = LOCAL_SPAN - (1 << 16)
            ts_base = max(int(ts.min()), int(ts.max()) - budget)
            seq_base = max(int(seq.min()), int(seq.max()) - budget)
            self._last_seq = max(self._last_seq, int(seq.max()))
            if len(self._lane_prev) < len(self._key_to_part):
                grown = np.full(len(self._key_to_part), -(2 ** 62),
                                dtype=np.int64)
                grown[:len(self._lane_prev)] = self._lane_prev
                self._lane_prev = grown

            def grid(a):
                g = np.zeros((Lpad, F), dtype=a.dtype)
                g[run_id, idx_within] = a
                return g

            nev = np.zeros(Lpad, _I32)
            nev[:Lr] = counts
            prev = np.full(Lpad, -LOCAL_SPAN, _I32)
            prev[:Lr] = np.clip(self._lane_prev[lane_ids] - seq_base,
                                -LOCAL_SPAN, LOCAL_SPAN).astype(_I32)
            ev = {"__flat.__ts__": grid(np.clip(
                      ts - ts_base, -LOCAL_SPAN, LOCAL_SPAN).astype(_I32)),
                  "__flat.__seq__": grid(np.clip(
                      seq - seq_base, -LOCAL_SPAN, LOCAL_SPAN).astype(_I32)),
                  "__nev__": nev, "__prev_seq__": prev,
                  "__base_ts__": np.int64(ts_base),
                  "__base_seq__": np.int64(seq_base)}
            if len(self.spec.stream_ids) > 1:
                ev["__flat.__scode__"] = grid(scode)
            for k, v in cols.items():
                ev[f"__flat.{k}"] = grid(v)

            # per-lane tail: the last `within` window of each lane's
            # events replays at that lane's next flush (lanes quiet this
            # flush keep their stored tail untouched)
            last_ts = tsmono[run_end]
            keep = tsmono >= (last_ts[run_id] - W)
            self._lane_tail = {
                "ts": ts[keep], "seq": seq[keep], "scode": scode[keep],
                "part": part[keep],
                "cols": {k: v[keep] for k, v in cols.items()}}
            if held is not None:
                # quiet lanes' tails ride along untouched (next flush
                # re-sorts, so concatenation order is irrelevant)
                self._lane_tail = {
                    k: (np.concatenate([self._lane_tail[k], held[k]])
                        if k != "cols" else
                        {c: np.concatenate([self._lane_tail["cols"][c],
                                            held["cols"][c]])
                         for c in held["cols"]})
                    for k in self._lane_tail}
            self._lane_prev[lane_ids] = seq[run_end]

        return self._pipe.push(self._dispatch_par(
            ev, F, F, ts_base, seq_base, lanes=Lpad))

    def _dispatch_par(self, ev, F, M, ts_base, seq_base,
                      lanes=None) -> dict:
        """One stateless scan/dfa-family block over the whole flat flush
        (no chunk-lane geometry — the kernel is log-depth in T).  With
        `lanes`, the SAME block runs once per lane under jax.vmap
        (partitioned (L, F) grids / fused broadcast lanes)."""
        with self.rt.stats.stage("host_build", plan=self.name):
            kern = self._parallel_kernel()
            if self.mesh is not None and lanes:
                # lane axis shards over the mesh; shared scalars and
                # fused broadcast event arrays replicate
                ev = {k: jax.device_put(
                          v, self._lane_sharding(np.ndim(v))
                          if np.ndim(v) and np.shape(v)[0] == lanes
                          else self._lane_sharding(0))
                      for k, v in ev.items()}
        T = (lanes, F) if lanes else F
        _st, out = self._call_block(kern, T, M, {}, ev)
        from .pipeline import start_d2h
        start_d2h(out)      # start the D2H pull while the device computes
        self._family_dispatches[self.family] = \
            self._family_dispatches.get(self.family, 0) + 1
        if lanes:
            self._lane_dispatches += 1
            self._lanes_last = int(lanes)
        return {"ev": ev, "F": F, "M": M, "L": lanes, "out": out,
                "ts_base": ts_base, "seq_base": seq_base}

    def _materialize_par(self, e: dict):
        lanes = e.get("L")
        while True:
            with self.rt.stats.stage("transfer", plan=self.name):
                ipack = np.asarray(e["out"]["i"])
                fpack = np.asarray(e["out"]["f"]) if "f" in e["out"] \
                    else None
            n = int(ipack[..., 0, 0].max()) if lanes else int(ipack[0, 0])
            if n > e["M"]:      # final-count emission burst: exact retry
                e = self._dispatch_par(e["ev"], e["F"], _m_bucket_chunk(n),
                                       e["ts_base"], e["seq_base"],
                                       lanes=lanes)
                continue
            break
        if self._arm_done is not None:
            from .nfa_parallel import ARM_RESOLVED
            kern = self._parallel_kernel()
            if kern.prog.single_arm:
                flags = np.asarray(ipack[:, 0, 4] if lanes
                                   else ipack[0, 4:5])
                done = flags == ARM_RESOLVED
                nl = min(len(self._arm_done), len(done))
                self._arm_done[:nl] |= done[:nl]
        # NOTE: _m_hint deliberately not updated — it sizes the chunk/seq
        # match buffers, and par blocks ride M = F instead
        # bases are per-flush: _unpack_block must see THIS entry's
        self._ts_base, self._seq_base = e["ts_base"], e["seq_base"]
        if lanes:
            return self._unpack_lanes(ipack, fpack)
        return self._unpack_block(ipack, fpack, n)

    def _dispatch_chunk(self, ev, K, T, M, ts_base, seq_base) -> dict:
        with self.rt.stats.stage("host_build", plan=self.name):
            kern = self._chunk_kernel(K)
            st0 = kern.init_state()
            if self.mesh is not None:
                # lane-axis sharding: state (.., K) shards over the mesh, the
                # flat event buffers replicate (each device gathers its own
                # lanes' chunk+halo windows on device)
                st0 = jax.tree_util.tree_map(
                    lambda a: jax.device_put(
                        a, self._part_sharding(np.ndim(a))
                        if np.ndim(a) and np.shape(a)[-1] == K
                        else self._part_sharding(0)), st0)
                ev = {k: jax.device_put(v, self._part_sharding(0))
                      for k, v in ev.items()}
        _st, out = self._call_block(kern, T, M, st0, ev)
        from .pipeline import start_d2h
        start_d2h(out)      # start the D2H pull while the device computes
        self._family_dispatches["chunk"] = \
            self._family_dispatches.get("chunk", 0) + 1
        return {"ev": ev, "K": K, "T": T, "M": M, "out": out,
                "ts_base": ts_base, "seq_base": seq_base}

    def _materialize_chunk(self, e: dict):
        if "F" in e:                  # scan/dfa-family entry
            return self._materialize_par(e)
        while True:
            with self.rt.stats.stage("transfer", plan=self.name):
                ipack = np.asarray(e["out"]["i"])
                fpack = np.asarray(e["out"]["f"]) if "f" in e["out"] \
                    else None
            n, ofs, ofl = (int(ipack[0, 0]), int(ipack[0, 1]),
                           int(ipack[0, 2]))
            if n > e["M"]:
                e = self._dispatch_chunk(e["ev"], e["K"], e["T"],
                                         _m_bucket_chunk(n),
                                         e["ts_base"], e["seq_base"])
                continue
            if ofs > 0 and self._chunk_A < self.A_CAP:
                self._chunk_A = min(2 * self._chunk_A, self.A_CAP)
                e = self._dispatch_chunk(e["ev"], e["K"], e["T"], e["M"],
                                         e["ts_base"], e["seq_base"])
                continue
            if ofl > 0:
                self._chunk_E = 2 * self._kern_by_p[e["K"]].E
                e = self._dispatch_chunk(e["ev"], e["K"], e["T"], e["M"],
                                         e["ts_base"], e["seq_base"])
                continue
            if ofs > 0:
                import warnings
                self._of_dropped += ofs
                warnings.warn(
                    f"pattern {self.name!r}: pending-match slots hit the "
                    f"deviceSlotCap ceiling ({self.A_CAP}); {ofs} partial "
                    f"matches dropped this flush (raise @app:deviceSlotCap)",
                    RuntimeWarning, stacklevel=2)
            break
        self._m_hint = max(self._m_hint, e["M"])
        # bases are per-flush: _unpack_block must see THIS entry's
        self._ts_base, self._seq_base = e["ts_base"], e["seq_base"]
        return self._unpack_block(ipack, fpack, n)

    def regeometry(self, batch_hint=None, depth=None, chunk_lanes=None,
                   plan_family=None, **knobs) -> None:
        """Pattern-family geometry: base knobs plus the chunked-halo lane
        count K and the execution family.  A lane-count change only
        affects how FUTURE flushes split into own-chunks (heads arm on
        owned events regardless of K); a stateless family switch applies
        to future flushes over the same tail/dedup bookkeeping — both
        output-invariant like every other geometry move."""
        super().regeometry(batch_hint=batch_hint, depth=depth, **knobs)
        if chunk_lanes is not None and self._chunk_cfg is not None:
            self._chunk_cfg["lanes"] = max(2, int(chunk_lanes))
        if plan_family is not None:
            self._set_family(str(plan_family))

    def flush_pending(self) -> list:
        # chunk results are raw columnar match tables, not OutputBatches:
        # wrap the base pipeline drain/collect in _rows_to_batches
        if self._pipe is None or not len(self._pipe):
            return []
        return self._rows_to_batches(self._pipe.drain())

    def collect_ready(self) -> list:
        if self._pipe is None:
            return []
        chunks = self._pipe.collect()
        return self._rows_to_batches(chunks) if chunks else []

    def _unpack_lanes(self, ipack, fpack):
        """Columnar match table from one lane-vmapped block's packed
        output: (L, rows, M) transposes to (rows, L*M) and the per-lane
        match counts become one validity mask — the row decode is then
        identical to the flat path (no per-lane python)."""
        Ln, rows, Mm = ipack.shape
        n_l = ipack[:, 0, 0]
        ip2 = np.swapaxes(ipack, 0, 1).reshape(rows, Ln * Mm)
        fp2 = (np.swapaxes(fpack, 0, 1).reshape(fpack.shape[1], Ln * Mm)
               if fpack is not None else None)
        base = (np.arange(Mm)[None, :] < n_l[:, None]).reshape(-1)
        return self._unpack_rows(ip2, fp2, base)

    def _unpack_block(self, ipack, fpack, n: int):
        """Columnar match table from one flat block's packed output."""
        return self._unpack_rows(ipack, fpack,
                                 np.arange(ipack.shape[1]) < n)

    def _unpack_rows(self, ipack, fpack, base_valid):
        with self.rt.stats.stage("scatter", plan=self.name):
            if self.kernel.having is not None:
                valid = base_valid & (ipack[1] != 0)
                ii = 2
            else:
                valid = base_valid
                ii = 1
            if not valid.any():
                return None
            # unpack columns in out_names order (columnar, no per-row python):
            # f32 rows are bitcast into the i32 pack, f64 rows (f64 mode) come
            # from the float pack, i64 as hi/lo row pairs
            row = {}
            fi = 0
            for nm in self.kernel.out_names:
                dt = np.dtype(self.kernel.out_dtypes[nm])
                if dt == np.float64:
                    row[nm] = fpack[fi]; fi += 1
                elif dt == np.float32:
                    row[nm] = ipack[ii].view(np.float32); ii += 1
                elif dt == np.int64:
                    row[nm] = join64_np(ipack[ii], ipack[ii + 1]); ii += 2
                else:
                    row[nm] = ipack[ii]; ii += 1
            tss = row["__timestamp__"][valid].astype(np.int64) + self._ts_base
            seqs = row["__seq__"][valid].astype(np.int64) + self._seq_base
            hseqs = row["__head_seq__"][valid]
            self._last_qids = (row["__qid__"][valid]
                               if self.kernel.emit_qid else None)
            data = {}
            for nm, t in zip(self._names, self._types):
                col = row[nm][valid]
                if t == ast.AttrType.BOOL:
                    col = col != 0
                data[nm] = col.astype(dtype_of(t))
            nulls = {}
            for nm, ref in self.kernel.null_outputs.items():
                pres = row.get(f"__present__.{ref}")
                if pres is not None:
                    mask = pres[valid] == 0
                    if mask.any():
                        nulls[nm] = mask
            return (tss, seqs, hseqs, data, nulls, self._last_qids)

    def _rows_to_batches(self, chunks: list) -> list:
        """chunks: list of (tss, seqs, hseqs, data) columnar match tables."""
        with self.rt.stats.stage("scatter", plan=self.name):
            chunks = [c for c in chunks if c is not None]
            if not chunks or self.events_for == ast.OutputEventsFor.EXPIRED:
                return []
            if self.broadcast_events:
                raise RuntimeError("multi-query plans use finalize_multi()")
            tss = np.concatenate([c[0] for c in chunks])
            seqs = np.concatenate([c[1] for c in chunks])
            hseqs = np.concatenate([c[2] for c in chunks])
            data = {nm: np.concatenate([c[3][nm] for c in chunks])
                    for nm in self._names}
            nulls_all = {}
            if any(c[4] for c in chunks):
                for nm in self._names:
                    parts = [c[4].get(nm, np.zeros(len(c[0]), bool))
                             for c in chunks]
                    m = np.concatenate(parts)
                    if m.any():
                        nulls_all[nm] = m
            # emit in completion order; same-event ties by head arrival
            # (reference emits pending-list == arrival order)
            o = np.lexsort((hseqs, seqs))
            if self.offset:
                o = o[self.offset:]
            if self.limit is not None:
                o = o[:self.limit]
            if not len(o):
                return []
            cols = {nm: data[nm][o] for nm in self._names}
            nulls = {nm: m[o] for nm, m in nulls_all.items()} or None
            batch = EventBatch(self.out_schema, tss[o].astype(TIMESTAMP_DTYPE),
                               cols, len(o), seqs[o], nulls)
            return [OutputBatch(self.output_target, batch)]

    def finalize_multi(self):
        """Multi-query mode: drain buffered events and return the raw
        columnar match table (tss, seqs, hseqs, data, qids) — the outer
        MultiQueryDevicePatternPlan routes rows per lane."""
        chunks = list(getattr(self, "_tick_chunks", ()) or ())
        self._tick_chunks = []
        chunks += [c for c in self._finalize_chunks() if c is not None]
        chunks = [c for c in chunks if c is not None]
        if not chunks:
            return None
        tss = np.concatenate([c[0] for c in chunks])
        seqs = np.concatenate([c[1] for c in chunks])
        hseqs = np.concatenate([c[2] for c in chunks])
        data = {nm: np.concatenate([c[3][nm] for c in chunks])
                for nm in self._names}
        qids = np.concatenate([c[5] for c in chunks])
        return (tss, seqs, hseqs, data, qids)

    # -- timers (absent-state deadlines) ---------------------------------

    def _anchor_ms(self) -> int:
        """START-state arm time for init-slot chains (host parity:
        matcher.start at first finalize/next_wakeup with rt.now_ms(), or
        the earliest buffered event time in pre-clock playback)."""
        if self._start_anchor is None:
            now = self.rt.now_ms()
            if self.rt._playback and self.rt._clock_ms is None \
                    and self._buffered:
                now = min(int(b.timestamps.min())
                          for _s, b in self._buffered)
            self._start_anchor = int(now)
        return self._start_anchor

    def next_wakeup(self) -> Optional[int]:
        if (self.spec.needs_init_slot and self._init_on_tick
                and self._ts_base is None):
            # pre-registered absent head, no block run yet: the first
            # deadline is anchor + waiting (host: matcher.start then
            # next_wakeup)
            ws = [n.waiting_ms for n in self.spec.positions[0].nodes
                  if n.kind == "absent" and n.waiting_ms is not None]
            if ws:
                return self._anchor_ms() + min(ws)
        return self._next_deadline

    def on_timer(self, now_ms: int) -> list:
        """Fire pending absent-state deadlines <= now via a 1-step tick
        block (valid=False cells with the timer's timestamp)."""
        if not self.kernel.has_absent:
            return []
        if self._ts_base is None:
            if not (self.spec.needs_init_slot and self._init_on_tick):
                return []
            w = self.next_wakeup()
            if w is None or now_ms < w:
                return []
            # first activity is a timer: anchor the offset bases so the
            # tick block can arm the init slots and fire their deadlines
            self._ts_base = self._anchor_ms()
            self._seq_base = 0
        elif self._next_deadline is None or now_ms < self._next_deadline:
            return []
        import jax.numpy as jnp
        T = 1
        GW = 1 if self.broadcast_events else self.P
        ev = {"__ts__": np.full((T, GW),
                                np.clip(now_ms - self._ts_base, -LOCAL_SPAN,
                                        LOCAL_SPAN), _I32),
              "__seq__": np.full((T, GW),
                                 np.clip(self._last_seq - self._seq_base,
                                         -LOCAL_SPAN, LOCAL_SPAN), _I32),
              "__valid__": np.zeros((T, GW), bool),
              "__tick__": np.ones((T, GW), bool)}
        if self.spec.needs_init_slot and self._init_on_tick:
            ev["__anchor__"] = np.int32(np.clip(
                self._anchor_ms() - self._ts_base, -LOCAL_SPAN, LOCAL_SPAN))
        if len(self.spec.stream_ids) > 1:
            ev["__scode__"] = np.full((T, GW), -1, _I32)
        for si, attr, t in self._grid_attrs:
            ev[f"{si}.{attr}"] = np.zeros((T, GW), self._np_dtype(t))
        ev["__base_ts__"] = np.int64(self._ts_base)
        ev["__base_seq__"] = np.int64(self._seq_base)
        chunks = self._run_chunks([(ev, T)])
        if self.broadcast_events:
            self._tick_chunks = [c for c in chunks if c is not None]
            return []
        return self._rows_to_batches(chunks)

    # -- snapshot ------------------------------------------------------------

    def state_dict(self) -> dict:
        st = jax.tree_util.tree_map(np.asarray, self.state)
        d = {"state": st, "key_to_part": dict(self._key_to_part),
             "ts_base": self._ts_base, "seq_base": self._seq_base,
             "next_deadline": self._next_deadline,
             "last_seq": self._last_seq,
             "start_anchor": self._start_anchor}
        if self._chunk_cfg is not None:
            # chunked mode keeps no device state: continuity lives in the
            # replayed tail + the last-emitted completion seq (per lane
            # for partitioned grids, plus single-arm resolution flags)
            d["chunk_tail"] = self._tail
            d["chunk_prev_last_seq"] = self._prev_last_seq
            d["chunk_of_dropped"] = self._of_dropped
            d["lane_tail"] = self._lane_tail
            d["lane_prev"] = np.asarray(self._lane_prev)
            d["arm_done"] = (np.asarray(self._arm_done)
                             if self._arm_done is not None else None)
        return d

    def load_state_dict(self, d: dict) -> None:
        import jax.numpy as jnp
        if self._pipe is not None:
            self._pipe.take_all()   # in-flight results predate the restore
        st = d["state"]
        a, p = st["occ"].shape
        if self.mesh is not None:
            nd = len(self.mesh.devices)
            p_r = -(-p // nd) * nd
            if p_r != p:       # snapshot from a differently-sized mesh/host
                kern = NFAKernel(self.spec, self.kernel.sel_fns,
                                 self.kernel.having, p_r, a, self.kernel.E,
                                 f64=self.f64, playback=self.rt._playback,
                                 params=self.kernel.params,
                                 emit_qid=self.kernel.emit_qid,
                                 init_on_tick=self._init_on_tick)
                fresh = jax.tree_util.tree_map(np.asarray, kern.init_state())
                st = jax.tree_util.tree_map(
                    lambda o, f: np.concatenate(
                        [o, f[..., o.shape[-1]:]], axis=-1)
                    if np.ndim(o) else o, dict(st), fresh)
                p = p_r
        if p != self.P or a != self.kernel.A:  # snapshot taken after growth
            self.kernel = NFAKernel(self.spec, self.kernel.sel_fns,
                                    self.kernel.having, p, a, self.kernel.E,
                                    f64=self.f64, playback=self.rt._playback,
                                    params=self.kernel.params,
                                    emit_qid=self.kernel.emit_qid,
                                    init_on_tick=self._init_on_tick)
            self.P = p
        self.state = self._shard(st)
        self._key_to_part = dict(d["key_to_part"])
        self._ts_base = d.get("ts_base")
        self._seq_base = d.get("seq_base")
        self._start_anchor = d.get("start_anchor")
        # legacy snapshots (no last_seq) fall back to the seq base — a
        # deadline fired before the next batch must not emit seq 0-based
        self._last_seq = int(d["last_seq"] if d.get("last_seq") is not None
                             else (d.get("seq_base") or 0))
        self._of_slots_seen = int(np.asarray(st["of_slots"]).sum())
        # pending absent-state deadlines must survive the restore, or the
        # scheduler never wakes to fire them; older snapshots (no key)
        # recompute the earliest armed deadline from the restored dl rows
        if "next_deadline" in d:
            self._next_deadline = d["next_deadline"]
        elif self.kernel.has_absent and st["dl"].size \
                and self._ts_base is not None:
            live = (st["occ"] > 0) & (st["occ"] <= self.spec.S)
            dls = np.where(live[None], st["dl"], np.int32(2**31 - 1))
            dlm = int(dls.min()) if dls.size else 2**31 - 1
            self._next_deadline = (None if dlm >= 2**31 - 1
                                   else self._ts_base + dlm)
        else:
            self._next_deadline = None
        if self._chunk_cfg is not None and "chunk_prev_last_seq" in d:
            self._tail = d.get("chunk_tail")
            self._prev_last_seq = int(d["chunk_prev_last_seq"])
            self._of_dropped = int(d.get("chunk_of_dropped", 0))
            self._lane_tail = d.get("lane_tail")
            if d.get("lane_prev") is not None:
                self._lane_prev = np.asarray(d["lane_prev"],
                                             dtype=np.int64)
            if d.get("arm_done") is not None:
                self._arm_done = np.asarray(d["arm_done"], dtype=bool)
