"""On-demand (store) queries: `runtime.query("from T on price > 10 select …")`.

Reference: core:util/parser/StoreQueryParser.java:548 builds
Select/Find/Update/Delete/UpdateOrInsert StoreQueryRuntimes executed by
core:query/StoreQueryRuntime.java:48; SiddhiAppRuntime.query LRU-caches
compiled queries (SiddhiAppRuntime.java:280-316).

Sources: tables (index-aware find), named windows (contents scan), and
incremental aggregations (within/per bucket selection).  An optional
trailing action applies the selected rows to a target table through the
same writers the streaming path uses.
"""
from __future__ import annotations

from typing import Optional

from ..query import ast
from .batch import BatchBuilder
from .planner import PlanError
from .schema import StreamSchema


class StoreQueryExec:
    """One compiled store query, re-executable against live state."""

    def __init__(self, rt, sq: ast.StoreQuery):
        from ..interp.engine import InterpSelector
        from ..interp.expr import PyExprContext, compile_py

        self.rt = rt
        self.sq = sq
        sid = sq.input.stream_id
        self.source_id = sid
        self.table = rt.tables.get(sid)
        self.named_window = rt.named_windows.get(sid)
        self.aggregation = rt.aggregations.get(sid)
        if (self.table is None and self.named_window is None
                and self.aggregation is None):
            raise PlanError(f"store query: {sid!r} is not a table, named "
                            f"window, or aggregation")
        if self.aggregation is not None:
            # delegated entirely to the aggregation runtime (within/per)
            self._agg_exec = self.aggregation.compile_store_query(sq)
            self.out_schema = self._agg_exec.out_schema
            self.writer = None
            return
        self._agg_exec = None

        schema = (self.table.schema if self.table is not None
                  else self.named_window.schema)
        self.schema = schema
        ctx = PyExprContext({sid: schema}, default_ref=sid, tables=rt.tables)
        on = None
        for f in sq.input.filters:
            on = f.expr if on is None else ast.And(on, f.expr)
        if self.table is not None:
            from .table import compile_table_condition
            # probe env is empty (no stream side) — conditions reference
            # only table columns and constants
            empty_ctx = PyExprContext({}, tables=rt.tables)
            self.cond = compile_table_condition(on, self.table, (sid,),
                                                empty_ctx)
            self.filter = None
        else:
            self.cond = None
            self.filter = compile_py(on, ctx)[0] if on is not None else None

        self.sel = InterpSelector(sq.selector, ctx, schema, f"#store_{sid}")
        self.out_schema = self.sel.out_schema
        self.writer = self._make_writer(sq.action)

    def _make_writer(self, action) -> Optional[object]:
        if action is None or isinstance(action, ast.ReturnAction):
            return None
        from .table import TableError, make_table_writer
        target = action.target
        table = self.rt.tables.get(target)
        if table is None:
            raise PlanError(f"store query action target {target!r} is not a "
                            f"defined table")
        try:
            return make_table_writer(action, table, self.out_schema)
        except TableError as e:
            raise PlanError(str(e)) from None

    # -- execution -----------------------------------------------------------

    def _source_envs(self) -> list:
        """(timestamp, env) per matching source row."""
        out = []
        names = self.schema.names
        sid = self.source_id
        if self.table is not None:
            t = self.table
            for i in self.cond.find({}):
                i = int(i)
                row = t.row_tuple(i)
                env = dict(zip(names, row))
                for n, v in zip(names, row):
                    env[f"{sid}.{n}"] = v
                ts_i = t.row_ts(i)
                env["__timestamp__"] = ts_i
                out.append((ts_i, env))
            return out
        for ev in self.named_window.contents():
            env = dict(zip(names, ev.data))
            for n, v in zip(names, ev.data):
                env[f"{sid}.{n}"] = v
            env["__timestamp__"] = ev.timestamp
            if self.filter is None or self.filter(env):
                out.append((ev.timestamp, env))
        return out

    def execute(self) -> list:
        """Returns decoded output rows [(timestamp, tuple)], after applying
        any trailing table action."""
        if self._agg_exec is not None:
            return self._agg_exec.execute()
        sel = self.sel
        aggregated = bool(sel.sites) or bool(sel.group_fns)
        rows: list = []
        last_per_group: dict = {}
        for ts, env in self._source_envs():
            key = (tuple(f(env) for f in sel.group_fns)
                   if sel.group_fns else ())
            row = sel.process("current", env)
            if row is None:
                continue
            if aggregated:
                last_per_group[key] = (ts, row)
            else:
                rows.append((ts, row))
        if aggregated:
            rows = list(last_per_group.values())
            # one-shot execution: clear aggregate banks for the next call
            sel._groups.clear()
        rows = [(t, r) for t, r in sel.order_limit(rows)]
        if self.writer is not None and rows:
            bb = BatchBuilder(self.out_schema, self.rt.strings)
            for t, r in rows:
                bb.append(t, tuple(r))
            self.writer.apply(bb.freeze())
        return [(t, tuple(r)) for t, r in rows]
